#!/usr/bin/env bash
# Seeded 3-replica routed-fleet chaos drill on CPU: one FleetRouter over
# three in-process inference engines, a kill_replica fault landing on the
# replica that prefix-affinity routing loaded (the rendezvous target of the
# shared prompt prefix — so the kill provably hits live decodes), and
# token-identical failover onto the survivors.
#
#   bash tools/fleet_smoke.sh
#
# What it proves:
#   * the shared-prefix requests all rendezvous-route to ONE replica (the
#     victim) and the short prompts spread by least-loaded fallback;
#   * at router round 4 the victim is abandoned mid-decode (in-process
#     SIGKILL analogy: its engine object is never stepped or closed again);
#   * the router detects the death on next contact, rebuilds shadow
#     RequestSnapshots from committed tokens only, and re-admits them on a
#     survivor — every request completes with greedy output token-identical
#     to ONE uninterrupted single-engine reference run (union parity:
#     pre-kill finishes + failed-over finishes together equal the
#     reference);
#   * each replica runs with the flight recorder on: the chaos fault dumps
#     a postmortem ring the instant it fires (validated here by replaying
#     the victim's dump into a Chrome trace-event document, then preserved
#     as traces/fleet_chaos_postmortem.json for the CI artifact upload);
#   * zero leaked pages: survivors' pages_referenced gauges read 0 after
#     the last finish, and router.close() runs assert_quiescent on every
#     surviving allocator (the dead replica is exempt — its pages died with
#     it, exactly like a real SIGKILL).
#
# The <90s pytest version of this drill is
# tests/test_serving_fleet.py::test_fleet_kill_drill_token_parity; this
# script adds the flight-recorder postmortem path and the artifact upload.
#
#   bash tools/fleet_smoke.sh procs
#
# runs the CROSS-PROCESS variant: three replica WORKER SUBPROCESSES behind
# ProcessReplicaClient (each its own engine + control endpoint + rolling
# on-disk flight dumps), a kill_replica_process fault delivering a REAL
# SIGKILL to the affinity-loaded worker under queue pressure, union token
# parity on the survivors, worker-side assert_quiescent on clean shutdown
# (a leaked page = non-zero worker exit = drill failure), and the victim's
# LAST rolling flight dump as the postmortem artifact — a SIGKILLed
# process by definition cannot dump at fault time, so the rolling dump IS
# the recovery artifact. The pytest version is
# tests/test_fleet_procs.py::test_process_fleet_kill_drill.
#
#   bash tools/fleet_smoke.sh router
#
# runs the DURABLE-CONTROL-PLANE variant: the ROUTER is the victim. Phase 1
# spawns three registry-tracked worker subprocesses with an orphan-grace
# window (TPURUN_ORPHAN_GRACE), journals every submit into a write-ahead
# request journal, pumps seeded Poisson load, and takes a REAL SIGKILL from
# an armed kill_router fault at a step boundary. Phase 2 — a fresh process,
# the successor router — replays the journal, re-adopts all three orphaned
# workers from the on-disk registry (worker-wins reconciliation on
# committed tokens), resubmits whatever the journal proves was never
# admitted, and drains the union to completion with greedy output
# token-identical to one uninterrupted single-engine reference. The journal
# segments and the recovery flight dump (with the reconciliation summary)
# are preserved under traces/ for the CI artifact upload. The pytest
# version is tests/test_router_procs.py::test_router_sigkill_recovery_drill.

set -euo pipefail
cd "$(dirname "$0")/.."
REPO="$PWD"
SCENARIO="${1:-inproc}"

WORK="$(mktemp -d /tmp/fleet_smoke.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT
echo "[fleet_smoke] workdir: $WORK (scenario: $SCENARIO)"

if [ "$SCENARIO" = "procs" ]; then
cat > "$WORK/drill_procs.py" <<'EOF'
"""Cross-process fleet chaos drill driver: reference run in-parent, then a
routed run over three worker SUBPROCESSES with a seeded real SIGKILL (see
fleet_smoke.sh for the full scenario)."""
import json
import os

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    InferenceEngine,
    SamplingParams,
    prefix_affinity_key,
    spawn_replica_clients,
)
from distributed_pytorch_tpu.serving.fleet import _rendezvous

PAGE = 4
PREFIX = [5, 7, 11, 2]  # one full page -> a routable affinity key
PROMPTS = (
    [PREFIX + [t, t + 1] for t in (1, 9, 17, 25)]  # affinity -> victim
    + [[3, 3, 7], [6, 1, 9, 9, 2], [2, 40, 17], [8, 8, 8, 1]]
)
MAX_NEW = 8
MODEL_KW = dict(vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32)
ENGINE_KW = dict(max_slots=2, max_seq_len=32, page_size=PAGE,
                 token_budget=16, max_prefill_chunk=8, debug=True)

# Uninterrupted single-engine reference, in-parent, same init seed as the
# workers build from: the token-parity oracle.
model = TransformerLM(**MODEL_KW, dtype=jnp.float32)
params = model.init(
    jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
)["params"]
ref = InferenceEngine(model, params, **ENGINE_KW)
ref_ids = [ref.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
           for p in PROMPTS]
ref.run()
REF = [ref.poll(i).generated for i in ref_ids]
ref.close()

# The kill must land on the replica the shared prefix routes to.
names = ["r0", "r1", "r2"]
victim = _rendezvous(prefix_affinity_key(PROMPTS[0], PAGE), names)
vidx = int(victim[1:])
os.environ[chaos.ENV_VAR] = json.dumps({
    "seed": 7,
    "faults": [{"kind": "kill_replica_process", "replica": vidx,
                "at_step": 4}],
})
chaos._reset()

# Three worker subprocesses, spawned concurrently. Each runs its engine
# with the flight recorder on and dumps the ring to disk after EVERY
# control step — the victim cannot dump at SIGKILL time, so its last
# rolling dump is the postmortem.
clients = spawn_replica_clients([
    {
        "name": f"r{i}",
        "model": dict(MODEL_KW, dtype="float32"),
        "init_seed": 0,
        "engine": ENGINE_KW,
        "flight": {"capacity": 2048,
                   "path": f"postmortem_proc_r{i}.json"},
        "flight_dump_every": 1,
        "warm_chunks": [3, 6],  # pre-compile the drill's prefill buckets
    }
    for i in range(3)
])
router = FleetRouter(clients)
queue = list(enumerate(PROMPTS))
fids = {}
rounds = 0
while queue or any(not s.finished for s in router._shadows.values()):
    for _ in range(2):  # 2 admissions per router round: open-loop load
        if queue:
            i, p = queue.pop(0)
            fids[i] = router.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
    router.step()
    rounds += 1
    assert rounds < 500, "fleet never drained"

vrep = next(r for r in router.replicas() if r.name == victim)
assert vrep.state == "dead", f"victim {victim} state={vrep.state}"
assert vrep.dead_reason == "kill_replica_process", vrep.dead_reason
assert clients[vidx]._proc.poll() == -9, (
    f"victim worker should be SIGKILLed, exit={clients[vidx]._proc.poll()}"
)
failed_over = int(router.registry.read_counter("requests_failed_over_total"))
assert failed_over >= 1, "kill landed on an idle replica (no failover)"
detection_s = router.registry.read_gauge("dead_replica_detection_seconds")

outs = [router.poll(fids[i]).generated for i in range(len(PROMPTS))]
for i, (got, want) in enumerate(zip(outs, REF)):
    assert list(got) == list(want), (
        f"request {i} diverged after failover: {got} != {want}"
    )

# Zero leaked pages on the survivors, read over the wire (dead replica
# exempt — its pages died with the process, as a real SIGKILL should).
for rep in router.replicas():
    if rep.state != "dead":
        held = rep.client.read_gauge("pages_referenced")
        assert held == 0, f"{rep.name} leaked {held} page(s)"
# close() shuts surviving workers down politely: each worker's
# engine.close() runs assert_quiescent IN the worker; a leak there is a
# non-zero worker exit and close() raises.
router.close()

print(json.dumps({
    "victim": victim,
    "victim_postmortem": f"postmortem_proc_r{vidx}.json",
    "requests_failed_over": failed_over,
    "detection_ms": round(detection_s * 1e3, 3),
    "rounds": rounds,
    "routed_affinity": int(
        router.registry.read_counter("routed_affinity_total")
    ),
}))
print("FLEET-PROCS-DRILL-OK")
EOF

cd "$WORK"
rc=0
env PYTHONPATH="$REPO" JAX_PLATFORMS=cpu python drill_procs.py > drill.log 2>&1 || rc=$?
echo "--- drill.log"
cat drill.log

fail() { echo "[fleet_smoke] FAIL: $1"; exit 1; }
[ "$rc" -eq 0 ] || fail "drill exited with $rc"
grep -q "FLEET-PROCS-DRILL-OK" drill.log || fail "drill never reached the final assertion"
grep -q "fleet fault kill_replica_process" drill.log || fail "kill_replica_process never fired"
grep -q "dead (kill_replica_process)" drill.log || fail "router never marked the victim dead"

POSTMORTEM="$(grep -oE 'postmortem_proc_r[0-9]+\.json' drill.log | head -1)"
[ -n "$POSTMORTEM" ] && [ -e "$POSTMORTEM" ] || fail "no victim rolling flight dump on disk"

# The victim's LAST rolling dump (written worker-side before the SIGKILL)
# must replay into a valid Chrome trace-event document.
env PYTHONPATH="$REPO" POSTMORTEM="$POSTMORTEM" python - <<'EOF'
import json
import os

from distributed_pytorch_tpu.obs import replay_to_tracer

dump = json.load(open(os.environ["POSTMORTEM"]))
assert dump["reason"] == "rolling", dump["reason"]
assert dump["events"], "postmortem ring buffer is empty"
kinds = {e["kind"] for e in dump["events"]}
assert "step" in kinds, f"no engine step records in dump: {kinds}"
doc = json.loads(json.dumps(replay_to_tracer(dump).to_perfetto()))
events = doc["traceEvents"]
assert isinstance(events, list) and events, "replay produced no trace events"
print(f"[fleet_smoke] victim rolling dump: {len(dump['events'])} events "
      f"(reason={dump['reason']}) -> {len(events)} trace events, replay OK")
EOF

mkdir -p "$REPO/traces"
cp "$POSTMORTEM" "$REPO/traces/fleet_procs_postmortem.json"

echo "[fleet_smoke] PASS (procs)"
exit 0
fi

if [ "$SCENARIO" = "router" ]; then
JDIR="$WORK/journal"
mkdir -p "$JDIR"

cat > "$WORK/router_phase1.py" <<'EOF'
"""Durable-control-plane drill, phase 1: the doomed router incarnation.
Spawns three registry-tracked orphan-grace workers, journals every submit,
pumps seeded Poisson load, and SIGKILLs ITSELF via an armed kill_router
fault at a step boundary (see fleet_smoke.sh for the full scenario)."""
import json
import os
import random
import sys

jdir = sys.argv[1]

from distributed_pytorch_tpu import chaos

os.environ[chaos.ENV_VAR] = json.dumps({
    "seed": 1234,
    "faults": [{"kind": "kill_router", "at_step": 4}],
})
chaos._reset()

from distributed_pytorch_tpu.serving import (
    FleetRouter,
    SamplingParams,
    spawn_replica_clients,
)

PREFIX = [5, 7, 11, 2]  # one full page -> a routable affinity key
PROMPTS = (
    [PREFIX + [t, t + 1] for t in (1, 9, 17, 25)]  # shared-prefix herd
    + [[3, 3, 7], [6, 1, 9, 9, 2], [2, 40, 17], [8, 8, 8, 1]]
)
MAX_NEW = 8
MODEL_KW = dict(vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32)
ENGINE_KW = dict(max_slots=2, max_seq_len=32, page_size=4,
                 token_budget=16, max_prefill_chunk=8, debug=True)

env = dict(os.environ)
env["TPURUN_ORPHAN_GRACE"] = "300"
clients = spawn_replica_clients([
    {
        "name": f"r{i}",
        "model": dict(MODEL_KW, dtype="float32"),
        "init_seed": 0,
        "engine": ENGINE_KW,
        "flight": {"capacity": 8192},
    }
    for i in range(3)
], run_dir=jdir, env=env)
router = FleetRouter(clients, journal_dir=jdir)

# Seeded Poisson arrivals: the kill lands mid-decode with work queued.
rng = random.Random(1234)
schedule = {}
rnd = 0
for idx in range(len(PROMPTS)):
    schedule.setdefault(rnd, []).append(idx)
    while rng.random() < 0.5:
        rnd += 1

fids = {}
rounds = 0
while True:
    for idx in schedule.pop(rounds, []):
        fids[idx] = router.submit(
            PROMPTS[idx], SamplingParams(max_new_tokens=MAX_NEW)
        )
        tmp = os.path.join(jdir, "fids.json.tmp")
        with open(tmp, "w") as f:
            json.dump(fids, f)
        os.replace(tmp, os.path.join(jdir, "fids.json"))
    router.step()  # the armed kill_router SIGKILLs this process here
    rounds += 1
    if rounds > 200:
        print("kill_router never fired", flush=True)
        sys.exit(1)
EOF

cat > "$WORK/router_phase2.py" <<'EOF'
"""Durable-control-plane drill, phase 2: the successor router. Replays
the journal, re-adopts the orphaned workers, drains the union, and proves
token parity against one uninterrupted reference run."""
import json
import os
import sys

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import FlightRecorder
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    InferenceEngine,
    SamplingParams,
    pid_alive,
    read_worker_registry,
)

jdir = sys.argv[1]

PREFIX = [5, 7, 11, 2]
PROMPTS = (
    [PREFIX + [t, t + 1] for t in (1, 9, 17, 25)]
    + [[3, 3, 7], [6, 1, 9, 9, 2], [2, 40, 17], [8, 8, 8, 1]]
)
MAX_NEW = 8
MODEL_KW = dict(vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32)
ENGINE_KW = dict(max_slots=2, max_seq_len=32, page_size=4,
                 token_budget=16, max_prefill_chunk=8, debug=True)

# Uninterrupted single-engine reference: the token-parity oracle.
model = TransformerLM(**MODEL_KW, dtype=jnp.float32)
params = model.init(
    jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
)["params"]
ref = InferenceEngine(model, params, **ENGINE_KW)
ref_ids = [ref.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
           for p in PROMPTS]
ref.run()
REF = [ref.poll(i).generated for i in ref_ids]
ref.close()

fids = {
    int(k): int(v)
    for k, v in json.load(open(os.path.join(jdir, "fids.json"))).items()
}
assert fids, "phase 1 died before any submit"

registry = read_worker_registry(jdir)
assert sorted(registry) == ["r0", "r1", "r2"], sorted(registry)
for name, entry in registry.items():
    assert pid_alive(entry["pid"]), f"{name} (pid {entry['pid']}) died"

router = FleetRouter.recover(jdir, flight=FlightRecorder(capacity=4096))
summary = router.last_recovery
assert sorted(summary["re_adopted_workers"]) == ["r0", "r1", "r2"], summary
assert summary["lost_workers"] == [], summary
for rep in router.replicas():
    assert rep.client.adopted and rep.client.adopted_orphan, rep.name

# The journal proves which prompts were never admitted: resubmit them.
resubmitted = 0
for idx in range(len(PROMPTS)):
    if idx not in fids:
        fids[idx] = router.submit(
            PROMPTS[idx], SamplingParams(max_new_tokens=MAX_NEW)
        )
        resubmitted += 1

rounds = 0
while any(not s.finished for s in router._shadows.values()):
    router.step()
    rounds += 1
    assert rounds < 500, "recovered fleet never drained"

outs = [router.poll(fids[i]).generated for i in range(len(PROMPTS))]
for i, (got, want) in enumerate(zip(outs, REF)):
    assert list(got) == list(want), (
        f"request {i} diverged across the router restart: {got} != {want}"
    )

# Zero leaked pages fleet-wide: the workers survived the router, so every
# allocator must read clean (no SIGKILL exemption in this drill).
for rep in router.replicas():
    held = rep.client.read_gauge("pages_referenced")
    assert held == 0, f"{rep.name} leaked {held} page(s)"
router.close()

print(json.dumps({
    "re_adopted": summary["re_adopted"],
    "re_admitted": summary["re_admitted"],
    "finished_tails": summary["finished_tails"],
    "lost": summary["lost"],
    "records_replayed": summary["records_replayed"],
    "resubmitted": resubmitted,
    "rounds": rounds,
}))
print("FLEET-ROUTER-DRILL-OK")
EOF

cd "$WORK"
fail() { echo "[fleet_smoke] FAIL: $1"; exit 1; }

rc=0
env PYTHONPATH="$REPO" JAX_PLATFORMS=cpu \
    python router_phase1.py "$JDIR" > phase1.log 2>&1 || rc=$?
echo "--- phase1.log"
cat phase1.log
# 137 = 128 + SIGKILL: the armed fault really killed the router process.
[ "$rc" -eq 137 ] || fail "phase 1 exited $rc, expected SIGKILL (137)"
ls "$JDIR"/journal-*.jsonl >/dev/null 2>&1 || fail "no journal segments on disk"
[ -e "$JDIR/fids.json" ] || fail "phase 1 never journaled a submit"

rc=0
env PYTHONPATH="$REPO" JAX_PLATFORMS=cpu \
    python router_phase2.py "$JDIR" > phase2.log 2>&1 || rc=$?
echo "--- phase2.log"
cat phase2.log
[ "$rc" -eq 0 ] || fail "phase 2 exited with $rc"
grep -q "FLEET-ROUTER-DRILL-OK" phase2.log || fail "recovery drill never reached the final assertion"
[ -e "$JDIR/router_recovery_flight.json" ] || fail "no recovery flight dump"

# Preserve the durable-control-plane artifacts for the CI upload: the
# (recompacted) journal and the recovery flight dump with its
# reconciliation summary.
mkdir -p "$REPO/traces"
cp "$JDIR/router_recovery_flight.json" "$REPO/traces/fleet_router_recovery.json"
tar -czf "$REPO/traces/fleet_router_journal.tar.gz" -C "$JDIR" \
    $(cd "$JDIR" && ls journal-*.jsonl) 2>/dev/null \
    || fail "could not archive journal segments"

echo "[fleet_smoke] PASS (router)"
exit 0
fi

cat > "$WORK/drill.py" <<'EOF'
"""Fleet chaos drill driver: reference run, then routed run with a seeded
replica kill (see fleet_smoke.sh for the full scenario)."""
import json
import os

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import FlightRecorder
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    InferenceEngine,
    SamplingParams,
    prefix_affinity_key,
)
from distributed_pytorch_tpu.serving.fleet import _rendezvous

PAGE = 4
PREFIX = [5, 7, 11, 2]  # one full page -> a routable affinity key
PROMPTS = (
    [PREFIX + [t, t + 1] for t in (1, 9, 17, 25)]  # affinity -> victim
    + [[3, 3, 7], [6, 1, 9, 9, 2], [2, 40, 17], [8, 8, 8, 1]]
)
MAX_NEW = 8

model = TransformerLM(vocab_size=48, d_model=16, n_layers=2, n_heads=2,
                      d_ff=32, dtype=jnp.float32)
params = model.init(
    jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
)["params"]


def mk(flight=None):
    # 2 slots per replica under 8 requests: real queue pressure, so the
    # kill lands while the victim still has waiting AND decoding work.
    return InferenceEngine(
        model, params, max_slots=2, max_seq_len=32, page_size=PAGE,
        token_budget=16, max_prefill_chunk=8, debug=True, flight=flight,
    )


# Uninterrupted single-engine reference: the token-parity oracle.
ref = mk()
ref_ids = [ref.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
           for p in PROMPTS]
ref.run()
REF = [ref.poll(i).generated for i in ref_ids]
ref.close()

# The kill must land on the replica the shared prefix routes to.
names = ["r0", "r1", "r2"]
victim = _rendezvous(prefix_affinity_key(PROMPTS[0], PAGE), names)
vidx = int(victim[1:])
os.environ[chaos.ENV_VAR] = json.dumps({
    "seed": 7,
    "faults": [{"kind": "kill_replica", "replica": vidx, "at_step": 4}],
})
chaos._reset()

router = FleetRouter([
    mk(flight=FlightRecorder(capacity=2048, path=f"postmortem_r{i}.json"))
    for i in range(3)
])
queue = list(enumerate(PROMPTS))
fids = {}
rounds = 0
while queue or any(not s.finished for s in router._shadows.values()):
    for _ in range(2):  # 2 admissions per router round: open-loop load
        if queue:
            i, p = queue.pop(0)
            fids[i] = router.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
    router.step()
    rounds += 1
    assert rounds < 500, "fleet never drained"

vrep = next(r for r in router.replicas() if r.name == victim)
assert vrep.state == "dead", f"victim {victim} state={vrep.state}"
assert vrep.dead_reason == "kill_replica", vrep.dead_reason
failed_over = int(router.registry.read_counter("requests_failed_over_total"))
assert failed_over >= 1, "kill landed on an idle replica (no failover)"
detection_s = router.registry.read_gauge("dead_replica_detection_seconds")

outs = [router.poll(fids[i]).generated for i in range(len(PROMPTS))]
for i, (got, want) in enumerate(zip(outs, REF)):
    assert got == want, f"request {i} diverged after failover: {got} != {want}"

# Zero leaked pages on the survivors (dead replica exempt — SIGKILL).
for rep in router.replicas():
    if rep.state != "dead":
        held = rep.engine.registry.read_gauge("pages_referenced")
        assert held == 0, f"{rep.name} leaked {held} page(s)"
router.close()  # runs assert_quiescent on every surviving allocator

print(json.dumps({
    "victim": victim,
    "victim_postmortem": f"postmortem_r{vidx}.json",
    "requests_failed_over": failed_over,
    "detection_ms": round(detection_s * 1e3, 3),
    "rounds": rounds,
    "routed_affinity": int(
        router.registry.read_counter("routed_affinity_total")
    ),
}))
print("FLEET-DRILL-OK")
EOF

cd "$WORK"
rc=0
env PYTHONPATH="$REPO" JAX_PLATFORMS=cpu python drill.py > drill.log 2>&1 || rc=$?
echo "--- drill.log"
cat drill.log

fail() { echo "[fleet_smoke] FAIL: $1"; exit 1; }
[ "$rc" -eq 0 ] || fail "drill exited with $rc"
grep -q "FLEET-DRILL-OK" drill.log || fail "drill never reached the final assertion"
grep -q "fleet fault kill_replica" drill.log || fail "kill_replica never fired"
grep -q "dead (kill_replica)" drill.log || fail "router never marked the victim dead"

POSTMORTEM="$(grep -oE 'postmortem_r[0-9]+\.json' drill.log | head -1)"
[ -n "$POSTMORTEM" ] && [ -e "$POSTMORTEM" ] || fail "no victim postmortem dump"

# The victim's postmortem must replay into a valid Chrome trace-event doc.
env PYTHONPATH="$REPO" POSTMORTEM="$POSTMORTEM" python - <<'EOF'
import json
import os

from distributed_pytorch_tpu.obs import replay_to_tracer

dump = json.load(open(os.environ["POSTMORTEM"]))
assert dump["reason"] == "chaos:kill_replica", dump["reason"]
assert dump["events"], "postmortem ring buffer is empty"
kinds = {e["kind"] for e in dump["events"]}
assert "chaos_fault" in kinds, f"no chaos_fault event in dump: {kinds}"
assert "step" in kinds, f"no engine step records in dump: {kinds}"
doc = json.loads(json.dumps(replay_to_tracer(dump).to_perfetto()))
events = doc["traceEvents"]
assert isinstance(events, list) and events, "replay produced no trace events"
print(f"[fleet_smoke] postmortem: {len(dump['events'])} events "
      f"(reason={dump['reason']}) -> {len(events)} trace events, replay OK")
EOF

# Preserve the victim's postmortem for the CI artifact upload (WORK is
# wiped on exit).
mkdir -p "$REPO/traces"
cp "$POSTMORTEM" "$REPO/traces/fleet_chaos_postmortem.json"

echo "[fleet_smoke] PASS"
