#!/usr/bin/env bash
# Tunnel-recovery automation (VERDICT r04 item 1): probe the axon TPU tunnel
# on a fixed cadence, log every attempt, and fire the payload script
# (default tools/chip_day.sh; override with PROBE_PAYLOAD=) the moment a
# probe succeeds — so no chip-minute is wasted waiting on a human.
#
#   bash tools/probe_and_fire.sh &        # logs to chip_probe.log
#   PROBE_PAYLOAD=tools/chip_day2.sh PROBE_INTERVAL=600 \
#     bash tools/probe_and_fire.sh &      # round-5 remainder queue, 10 min
#
# Design constraints (BASELINE.md round-3/4 outage notes):
#  * The probe is a plain `jax.devices()` dial — no compile in flight, so
#    timing it out cannot wedge the relay (killing a mid-flight COMPILE can).
#  * Only ONE axon client at a time: the probe and chip_day.sh never overlap
#    (the fire happens in the same serialized loop iteration).
#  * PYTHONPATH must include /root/.axon_site for the plugin (memory note).
set -u
cd "$(dirname "$0")/.."

LOG=${PROBE_LOG:-chip_probe.log}
INTERVAL=${PROBE_INTERVAL:-1200}   # seconds between probes
PROBE_TIMEOUT=${PROBE_TIMEOUT:-150}
PAYLOAD=${PROBE_PAYLOAD:-tools/chip_day.sh}  # fired once on recovery
# Default log derives from the payload name so overriding PROBE_PAYLOAD
# alone cannot truncate an earlier payload's log (the only record of any
# rows captured before a mid-run wedge).
PAYLOAD_LOG=${PROBE_PAYLOAD_LOG:-$(basename "${PAYLOAD%.sh}").log}
[ -f "$PAYLOAD" ] || { echo "payload missing: $PAYLOAD" >&2; exit 1; }

say() { echo "[$(date -u +%FT%TZ)] $*" | tee -a "$LOG" >&2; }

say "probe loop start (interval=${INTERVAL}s, timeout=${PROBE_TIMEOUT}s)"
while :; do
  if timeout "$PROBE_TIMEOUT" env PYTHONPATH=/root/.axon_site python - <<'EOF' >>"$LOG" 2>&1
import jax
ds = jax.devices()
assert ds and ds[0].platform != "cpu", ds
print("TUNNEL UP:", ds)
EOF
  then
    say "tunnel recovered — firing $PAYLOAD (serialized, do not interrupt)"
    # The payload needs the SAME axon plugin env the probe used, or every
    # step silently falls back to CPU and wastes the recovered chip window.
    PYTHONPATH=/root/.axon_site bash "$PAYLOAD" >"$PAYLOAD_LOG" 2>&1
    say "$PAYLOAD finished rc=$? — see $PAYLOAD_LOG; probe loop exiting"
    exit 0
  else
    say "probe failed (tunnel still wedged); next attempt in ${INTERVAL}s"
  fi
  sleep "$INTERVAL"
done
