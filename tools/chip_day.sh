#!/usr/bin/env bash
# Every queued on-chip measurement, one serialized run (BASELINE.md "Pending
# on-chip measurements"). The axon tunnel wedges if a TPU process is killed
# mid-compile and cannot handle two concurrent clients — so: run THIS SCRIPT
# ALONE, never ctrl-C a step, and let each step finish. Usage:
#
#   bash tools/chip_day.sh 2>&1 | tee chip_day.log
#
# Steps (each is independently restartable; comment out what you have):
source "$(dirname "$0")/_chip_common.sh"

# 1. Headline (driver metric): ResNet-50 b32 steps/s + MFU.
run python bench.py

# 2. Full matrix -> BENCH_MATRIX.json (ViT + d_head=128 rows included).
run python bench.py --matrix

# 3. int8 decode A/B pairs (weights + KV cache) -> BASELINE.md rows;
#    decides the quant_matmul wiring (see BASELINE.md round-3 queue).
run python tools/decode_bench.py

# 4. Real-data-rung curve on the hard synthetic stand-in (fast on chip;
#    full 50k train set — the CPU runs only managed 3-4k subsets, where
#    ResNet-18 overfits noise instead of pooling the template signal).
#    NO --augment: crop/flip destroy the stand-in's pixel-aligned signal
#    (BASELINE.md round 4); use --augment only with real CIFAR-10 data.
#    --smooth_frac 0.0 pins the ORIGINAL white-template stand-in this
#    queue's round-4 record used (now known GAP-conv-unlearnable, BASELINE
#    round 5); the current recipe lives in chip_day2.sh step 4.
run python examples/real_data.py --epochs 6 --batch_size 128 --lr 0.02 --smooth_frac 0.0

# 5. Sliding-window step-time-vs-band sweep (round-4 queue; now includes
#    the round-5 windowed-ring kernel offsets) -> BENCH_WINDOW.json.
run python bench.py --window_sweep

# 6. GQA decode A/B: kv-head reduction x int8 (round-4 queue) — compare
#    against step 3's full-head rows.
run python tools/decode_bench.py --n_kv_heads 2

# 7. Flash block-table sweep IF this chip kind is not already in
#    DEFAULT_TABLE (prints a mergeable entry; skip on v5e).
# run python tools/flash_autotune_gen.py --export blocks_$(date +%s).json

# NOTE pod-only A/Bs stay queued for multi-chip hardware (cannot run on one
# tunneled chip): ring-vs-ulysses (examples/longcontext_lm.py --sp_mode),
# windowed-ring hop elision, bench.py --scaling real efficiency.

echo "done (failed steps: $FAILED_STEPS) — commit BENCH_MATRIX.json + BENCH_WINDOW.json + BASELINE.md updates" >&2
exit "$FAILED_STEPS"
