#!/usr/bin/env bash
# Run ANY host-CPU command without polluting a concurrently-recovered chip
# window: SIGSTOPs the command whenever a chip payload (chip-day queue,
# driver bench, decode bench) is live on this one-core host, SIGCONTs when
# the chip is idle (BASELINE.md round-5 operational lesson). Usage:
#
#   bash tools/host_guarded.sh python -m pytest tests/ -q   >suite.log 2>&1 &
#
# tools/cpu_curve_guarded.sh is the real-data-curve instance of this.
source "$(dirname "$0")/_chip_common.sh"

"$@" &
PID=$!
echo "[guard] guarded pid=$PID: $*" >&2
# CONT before TERM: a plain TERM to a SIGSTOPped process stays pending
# forever, orphaning the child in state T. Trap signals too, not just EXIT
# (bash delivers the trap only after the current sleep finishes, <=20s),
# and exit explicitly from the signal path or bash resumes the loop.
ppid_of() {
  # /proc/<pid>/stat embeds comm in parens and comm may contain spaces
  # ("tmux: server"), so positional awk on the raw line is wrong; strip
  # through the LAST ')' first — field 2 of the remainder is ppid.
  local rest
  rest=$(sed 's/^.*) //' "/proc/$1/stat" 2>/dev/null)
  [ -n "$rest" ] || return 1
  set -- $rest
  echo "$2"
}

cleanup() { kill -CONT "$PID" 2>/dev/null; kill "$PID" 2>/dev/null; }
trap cleanup EXIT
trap 'cleanup; trap - EXIT; exit 143' INT TERM

paused=0
while kill -0 "$PID" 2>/dev/null; do
  # "bash <path>" / "python <path>" with no space in the path survives
  # absolute/relative launch variants, while launcher shells that merely
  # MENTION these scripts in an env assignment (probe_and_fire's
  # PROBE_PAYLOAD=... argv) don't read as a live payload forever.
  # Exclude the guarded child, the guard itself, AND the guard's ancestor
  # chain: guarding a command that IS a chip payload (e.g.
  # `host_guarded.sh python bench.py ...`) must not self-pause into a
  # permanent STOP (a stopped process still matches pgrep, so the idle
  # branch would never fire) — and launcher/harness shells above us carry
  # the same command string in their argv.
  excl="$PID $$"
  anc=$$
  while [ "$anc" -gt 1 ] 2>/dev/null; do
    anc=$(ppid_of "$anc") || break
    excl="$excl $anc"
  done
  # ...and our DESCENDANTS: $(...) substitutions fork subshells carrying
  # the guard's own argv, which would otherwise match the pattern every
  # poll when the guarded command is itself bench-shaped.
  is_ours() {
    local p=$1
    case " $excl " in *" $p "*) return 0 ;; esac
    while [ "$p" -gt 1 ] 2>/dev/null; do
      p=$(ppid_of "$p") || return 1
      [ "$p" = "$$" ] && return 0
    done
    return 1
  }
  others=""
  for cand in $(pgrep -f "bash [^ ]*tools/chip_day|python [^ ]*bench\.py|python [^ ]*tools/decode_bench"); do
    is_ours "$cand" || others="$others $cand"
  done
  if [ -n "$others" ]; then
    if [ "$paused" = 0 ]; then
      echo "[guard $(date +%H:%M:%S)] chip payload active - pausing" >&2
      kill -STOP "$PID"; paused=1
    fi
  elif [ "$paused" = 1 ]; then
    echo "[guard $(date +%H:%M:%S)] chip idle - resuming" >&2
    kill -CONT "$PID"; paused=0
  fi
  sleep 20
done
wait "$PID"
rc=$?
trap - EXIT
echo "[guard] command finished rc=$rc" >&2
exit $rc
