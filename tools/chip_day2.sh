#!/usr/bin/env bash
# Round-5 remainder queue: the chip_day.sh steps that failed (ModuleNotFoundError,
# fixed since) or were polluted by concurrent host load, most valuable first so a
# short recovery window still captures the headline. Same rules as chip_day.sh:
# run ALONE, never ctrl-C a step. Usage:
#
#   bash tools/chip_day2.sh 2>&1 | tee chip_day2.log
source "$(dirname "$0")/_chip_common.sh"

# 1. Clean headline (the 03:48 run had a concurrent pytest stealing host CPU).
run python bench.py

# 2+3. int8 decode A/Bs (weights + KV cache), full-head then kv_heads=2 —
#      decides the quant_matmul wiring (BASELINE.md round-3 queue).
run python tools/decode_bench.py
run python tools/decode_bench.py --n_kv_heads 2

# 4. Real-data-rung curve, full 50k stand-in (NO --augment: crop/flip destroy
#    the stand-in's pixel-aligned signal — BASELINE.md round 4). smooth_frac
#    defaults to 0.5: the round-5 CPU-measured recipe that lifts a conv net
#    off chance (white templates are GAP-conv-unlearnable, BASELINE.md r5).
run python examples/real_data.py --epochs 8 --batch_size 128 --lr 0.1

# 5. Clean full matrix -> BENCH_MATRIX.json (the 03:50 run was host-polluted:
#    b32 rows ~10-18% low vs the standalone headline at the same hour).
run python bench.py --matrix

# 6. Window sweep re-run: the first attempt printed 4/5 rows then the relay
#    wedged mid w=4096 compile; BENCH_WINDOW.json is only written at the end.
run python bench.py --window_sweep

# 7. (optional) Speculative-decode speedup A/B. NOTE: the on-the-fly draft
#    distills against a RANDOM-INIT target, whose conditionals a small
#    draft largely cannot learn - expect low acceptance and an honest
#    sub-1.0 speedup; the trained-target acceptance story (1.00 -> 4.00)
#    is examples/draft_distill.py. Uncomment when chip time is plentiful.
# run python tools/decode_bench.py --speculative

echo "done (failed steps: $FAILED_STEPS) — commit BENCH_MATRIX.json + BENCH_WINDOW.json + BASELINE.md updates" >&2
exit "$FAILED_STEPS"
