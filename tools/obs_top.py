#!/usr/bin/env python
"""``top`` for serving engines — the human end of the observability wire.

Polls one or more ``/statusz`` endpoints (see ``obs/server.py``) and
redraws an ANSI dashboard: per-engine health, queue depth, running
requests, page states, TTFT/TPOT percentiles, tokens/sec, SLO firing set,
and the recompile-sentinel counter — plus the busiest in-flight requests
of the first engine. Engines exposing the performance observatory's
``/timeseries`` endpoint additionally get sparkline columns
(tokens/sec, per-step TPOT, and — when the hierarchical-KV host tier is
on — d2h spill / h2d fetch byte rates over the last minute); engines
without it show ``-`` cells, nothing breaks. Engines running with
``host_pages`` also get a ``HOST r/c`` cell (host pages resident /
capacity) next to the device-page gauges, read straight from the
``hostkv`` block of ``/statusz``. Stdlib only, one process, no curses
dependency (ANSI home+clear is enough and survives dumb terminals via
``--once``).

Usage:
    python tools/obs_top.py http://127.0.0.1:8321 [more urls...]
    python tools/obs_top.py --once URL        # one frame, no screen clear
    python tools/obs_top.py --interval 0.5 URL
    python tools/obs_top.py --tenant URL      # per-tenant waterfall view

``--tenant`` switches to the front door's per-tenant table (the door's
``/statusz`` carries a ``tenants`` block with queue-wait / pacing /
decode p95s straight from its waterfall reservoirs).

``render_frame`` / ``render_tenant_frame`` are pure functions of the
polled documents, so tests drive them with canned statusz payloads and
never open a socket.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import List, Optional, Tuple

CLEAR = "\x1b[H\x1b[2J"
BOLD = "\x1b[1m"
RED = "\x1b[31m"
YELLOW = "\x1b[33m"
GREEN = "\x1b[32m"
RESET = "\x1b[0m"


def poll(url: str, timeout: float = 2.0) -> Optional[dict]:
    """One ``/statusz`` GET; None when the engine is unreachable (a dead
    replica is a row in the dashboard, not a crash of the dashboard)."""
    try:
        with urllib.request.urlopen(
            url.rstrip("/") + "/statusz", timeout=timeout
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:
        return None


def poll_timeseries(
    url: str,
    timeout: float = 2.0,
    window_s: float = 60.0,
    series: str = (
        "tokens_per_sec,tpot_step_seconds,"
        "serving_hostkv_spill_bytes_total,"
        "serving_hostkv_fetch_bytes_total"
    ),
) -> Optional[dict]:
    """One ``/timeseries`` GET for the sparkline columns; None when the
    engine predates the performance observatory (404) or is down — the
    row just renders ``-`` cells."""
    try:
        with urllib.request.urlopen(
            url.rstrip("/")
            + f"/timeseries?series={series}&window={window_s:g}",
            timeout=timeout,
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:
        return None


SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _spark(values: List[float], width: int = 12) -> str:
    """Unicode sparkline of the trailing ``width`` values, min..max
    normalised per cell (same ramp as ``obs.timeseries.sparkline``).
    Charset-only — honors ``--no-color`` for free."""
    vals = [
        v for v in values if isinstance(v, (int, float)) and v == v
    ][-width:]
    if not vals:
        return "-" * width
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return ("▄" * len(vals)).rjust(width)
    top = len(SPARK_CHARS) - 1
    return "".join(
        SPARK_CHARS[min(top, int((v - lo) / (hi - lo) * top + 0.5))]
        for v in vals
    ).rjust(width)


def _series_spark(ts_doc: Optional[dict], name: str, width: int = 12) -> str:
    """Sparkline cell for one named series out of a ``/timeseries`` doc."""
    if not ts_doc:
        return "-" * width
    points = (ts_doc.get("series", {}).get(name) or {}).get("points", [])
    return _spark([p[1] for p in points if len(p) == 2], width)


def _rate_spark(ts_doc: Optional[dict], name: str, width: int = 12) -> str:
    """Sparkline of a CUMULATIVE counter series as a per-second rate:
    successive deltas divided by their time gaps (the TSDB's documented
    counter semantics — no extrapolation, no reset detection). The host
    tier's spill/fetch byte counters render through this, so the cell
    shows transfer *activity*, not the monotone lifetime total."""
    if not ts_doc:
        return "-" * width
    points = (ts_doc.get("series", {}).get(name) or {}).get("points", [])
    points = [p for p in points if len(p) == 2]
    rates: List[float] = []
    for (t0, v0), (t1, v1) in zip(points, points[1:]):
        dt = t1 - t0
        if dt > 0:
            rates.append(max(0.0, v1 - v0) / dt)
    return _spark(rates, width)


def _ms(value) -> str:
    """Seconds -> fixed-width milliseconds, '-' for missing/NaN."""
    if not isinstance(value, (int, float)) or value != value:
        return "     -"
    return f"{value * 1e3:6.2f}"


def _health_cell(health: str, color: bool) -> str:
    text = f"{health:<8}"
    if not color:
        return text
    tint = {"live": GREEN, "draining": YELLOW}.get(health, RED)
    return f"{tint}{text}{RESET}"


def render_frame(
    polled: List[Tuple[str, Optional[dict]]],
    color: bool = True,
    timeseries: Optional[dict] = None,
) -> str:
    """One dashboard frame from ``[(url, statusz-or-None), ...]``.

    ``timeseries`` optionally maps url -> ``/timeseries`` doc (see
    :func:`poll_timeseries`); when present the frame grows two sparkline
    columns — tokens/sec and per-step TPOT over the polled window —
    rendered entirely from the doc so this stays a pure function."""
    bold = BOLD if color else ""
    reset = RESET if color else ""
    lines = [
        f"{bold}{'ENGINE':<28} {'HEALTH':<8} {'Q':>4} {'RUN':>4} "
        f"{'PAGES f/r/i':>14} {'HOST r/c':>9} {'TTFT p50':>9} "
        f"{'TPOT p50':>9} {'TPOT p95':>9} {'TOK/S':>8} "
        f"{'TOK/S 60s':>12} {'TPOT 60s':>12} {'SPILL B/s':>12} "
        f"{'FETCH B/s':>12} {'RECOMP':>7}  SLO{reset}"
    ]
    for url, doc in polled:
        name = url.replace("http://", "")[:28]
        if doc is None:
            down = f"{RED}down{RESET}    " if color else "down    "
            lines.append(f"{name:<28} {down}")
            continue
        pages = doc.get("pages", {})
        page_cell = (
            f"{pages.get('pages_free', 0)}/"
            f"{pages.get('pages_referenced', 0)}/"
            f"{pages.get('pages_cached_idle', 0)}"
        )
        hostkv = doc.get("hostkv") or {}
        host_cell = (
            f"{hostkv.get('hostkv_pages_resident', 0)}/"
            f"{hostkv.get('hostkv_pages_capacity', 0)}"
            if hostkv
            else "-"
        )
        latency = doc.get("latency", {})
        sentinel = doc.get("recompile_sentinel") or {}
        recomp = sentinel.get("count", 0)
        recomp_cell = f"{recomp:>7}"
        if color and recomp:
            recomp_cell = f"{RED}{recomp_cell}{RESET}"
        slo = doc.get("slo") or {}
        firing = slo.get("firing", [])
        slo_cell = ",".join(firing) if firing else "ok"
        if color and firing:
            slo_cell = f"{RED}{slo_cell}{RESET}"
        ts_doc = (timeseries or {}).get(url)
        lines.append(
            f"{name:<28} {_health_cell(doc.get('health', '?'), color)} "
            f"{doc.get('queue_depth', 0):>4} "
            f"{doc.get('running_requests', 0):>4} "
            f"{page_cell:>14} "
            f"{host_cell:>9} "
            f"{_ms(latency.get('ttft_p50_s')):>9} "
            f"{_ms(latency.get('tpot_p50_s')):>9} "
            f"{_ms(latency.get('tpot_p95_s')):>9} "
            f"{latency.get('tokens_per_sec', 0) or 0:>8.1f} "
            f"{_series_spark(ts_doc, 'tokens_per_sec'):>12} "
            f"{_series_spark(ts_doc, 'tpot_step_seconds'):>12} "
            f"{_rate_spark(ts_doc, 'serving_hostkv_spill_bytes_total'):>12} "
            f"{_rate_spark(ts_doc, 'serving_hostkv_fetch_bytes_total'):>12} "
            f"{recomp_cell}  {slo_cell}"
        )
    first = next((doc for _u, doc in polled if doc), None)
    if first and first.get("requests"):
        lines.append("")
        lines.append(
            f"{bold}{'REQ':>6} {'PHASE':<9} {'SLOT':>4} {'AGE s':>7} "
            f"{'PROMPT':>7} {'CACHED':>7} {'GEN':>6} {'PREEMPT':>7}"
            f"{reset}"
        )
        requests = sorted(
            first["requests"], key=lambda r: -r.get("age_s", 0)
        )[:12]
        for req in requests:
            lines.append(
                f"{req.get('req_id', '?'):>6} "
                f"{req.get('phase', '?'):<9} "
                f"{str(req.get('slot', '-')):>4} "
                f"{req.get('age_s', 0):>7.2f} "
                f"{req.get('prompt_len', 0):>7} "
                f"{req.get('len_cached', 0):>7} "
                f"{req.get('generated', 0):>6} "
                f"{req.get('preempt_count', 0):>7}"
            )
    return "\n".join(lines) + "\n"


def render_tenant_frame(
    polled: List[Tuple[str, Optional[dict]]], color: bool = True
) -> str:
    """Per-tenant dashboard frame from front-door ``/statusz`` documents:
    one row per (door, tenant) with the waterfall-component p95s the
    door's per-tenant reservoirs record at admission and finalize."""
    bold = BOLD if color else ""
    reset = RESET if color else ""
    lines = [
        f"{bold}{'DOOR':<24} {'TENANT':<12} {'W':>4} {'Q':>4} "
        f"{'QWAIT p95':>10} {'PACE p95':>10} {'DECODE p95':>11} "
        f"{'TTFT p95':>9} {'TPOT p95':>9}{reset}"
    ]
    for url, doc in polled:
        name = url.replace("http://", "")[:24]
        if doc is None:
            down = f"{RED}down{RESET}" if color else "down"
            lines.append(f"{name:<24} {down}")
            continue
        tenants = doc.get("tenants") or {}
        if not tenants:
            lines.append(f"{name:<24} (no tenants block — not a door?)")
            continue
        for tenant in sorted(tenants):
            t = tenants[tenant]
            lines.append(
                f"{name:<24} {tenant:<12} "
                f"{t.get('weight', 0):>4.1f} "
                f"{t.get('queued', 0):>4} "
                f"{_ms(t.get('queue_wait_p95_s')):>10} "
                f"{_ms(t.get('pacing_p95_s')):>10} "
                f"{_ms(t.get('decode_p95_s')):>11} "
                f"{_ms(t.get('ttft_p95_s')):>9} "
                f"{_ms(t.get('tpot_p95_s')):>9}"
            )
        sampler = doc.get("trace_sampler")
        if sampler:
            lines.append(
                f"{'':<24} traces kept={sampler.get('kept', 0)} "
                f"head={sampler.get('traces_kept_head', 0)} "
                f"tail={sampler.get('traces_kept_tail', 0)} "
                f"dropped={sampler.get('traces_dropped', 0)} "
                f"evicted={sampler.get('traces_evicted', 0)}"
            )
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("urls", nargs="+", help="engine base URLs")
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--once",
        action="store_true",
        help="render one frame and exit (no screen clearing)",
    )
    parser.add_argument(
        "--no-color", action="store_true", help="plain-text output"
    )
    parser.add_argument(
        "--tenant",
        action="store_true",
        help="per-tenant waterfall view (front-door /statusz)",
    )
    args = parser.parse_args(argv)
    color = not args.no_color and sys.stdout.isatty()
    try:
        while True:
            polled = [(url, poll(url)) for url in args.urls]
            if args.tenant:
                frame = render_tenant_frame(polled, color=color)
            else:
                frame = render_frame(
                    polled,
                    color=color,
                    timeseries={
                        url: poll_timeseries(url) for url in args.urls
                    },
                )
            if args.once:
                sys.stdout.write(frame)
                return 0
            sys.stdout.write(CLEAR + frame)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
