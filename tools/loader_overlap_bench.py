"""Loader-on-the-clock benchmark: the C++ prefetch pool's overlap win.

The pool (``native/prefetch.cpp`` + :class:`NativeShardedLoader`) replaces
``DataLoader(num_workers=..., pin_memory=True)`` (reference
``multigpu.py:72-79``): GIL-free worker threads gather batches into a bounded
ring while the training loop consumes. ``bench.py`` deliberately pre-stages
batches off the clock (the axon tunnel's per-step H2D would otherwise swamp
everything), so THIS bench supplies the pool's missing number: a CPU-backend
train loop with batch assembly ON the clock, identical batches either way.

    JAX_PLATFORMS=cpu python tools/loader_overlap_bench.py

Prints steps/s for the Python loader vs the native pool, plus the decomposed
assembly-only and compute-only rates so the overlap arithmetic is visible:
python ~ 1/(assembly + compute), native ~ 1/max(assembly', compute) with the
gather itself also moving to C++ memcpy.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(n_steps: int = 100, batch: int = 256, features: int = 8192, hidden: int = 16):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import flax.linen as nn

    jax.config.update("jax_platforms", "cpu")

    from distributed_pytorch_tpu.training.losses import mse_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from distributed_pytorch_tpu.utils.data import (
        ArrayDataset,
        NativeShardedLoader,
        ShardedLoader,
    )

    class WideMLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(hidden)(x)
            x = nn.relu(x)
            x = nn.Dense(hidden)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    rng = np.random.default_rng(0)
    n_samples = n_steps * batch  # one full epoch, no repeats
    data = ArrayDataset(
        rng.standard_normal((n_samples, features)).astype(np.float32),
        rng.standard_normal((n_samples, 1)).astype(np.float32),
    )

    optimizer = optax.sgd(1e-3)
    model = WideMLP()
    step = make_train_step(model.apply, optimizer, mse_loss)

    def loaders():
        return {
            "python_loader": ShardedLoader(data, batch, shuffle=True),
            "native_pool": NativeShardedLoader(
                data, batch, shuffle=True, num_workers=4, prefetch_depth=4
            ),
        }

    # The train step donates its state buffer; every run needs a fresh one.
    fresh = lambda: create_train_state(model, optimizer, data.inputs[:1])  # noqa: E731

    # Warm the jit cache once.
    xs, ys = next(iter(loaders()["python_loader"]))
    state, loss = step(fresh(), jax.device_put((xs, ys)))
    float(loss)

    results = {}
    for name, loader in loaders().items():
        state = fresh()
        t0 = time.perf_counter()
        for xs, ys in loader:
            state, loss = step(state, jax.device_put((xs, ys)))
        float(loss)
        elapsed = time.perf_counter() - t0
        results[name] = n_steps / elapsed

    # Decomposition: assembly-only (drain each loader, no compute) and
    # compute-only (one resident batch re-fed).
    for name, loader in loaders().items():
        t0 = time.perf_counter()
        for _ in loader:
            pass
        results[f"{name}_assembly_only"] = n_steps / (time.perf_counter() - t0)
    resident = jax.device_put((xs, ys))
    state = fresh()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, resident)
    float(loss)
    results["compute_only"] = n_steps / (time.perf_counter() - t0)

    results = {k: round(v, 2) for k, v in results.items()}
    results["overlap_speedup"] = round(
        results["native_pool"] / results["python_loader"], 3
    )
    print(json.dumps(results, indent=1))
    return results


if __name__ == "__main__":
    main()
