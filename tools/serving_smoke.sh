#!/usr/bin/env bash
# Serving smoke: boot the continuous-batching engine on CPU, submit 8
# staggered requests (some mid-flight, after the first batch is half
# drained), and assert every one completes with the right token count and
# non-empty latency metrics. A second scenario replays waves of requests
# sharing a system-prompt prefix and asserts the prefix cache actually
# hits (nonzero hit rate, cached tokens admitted, TTFT hit-reservoir
# populated) with zero page leaks. A third scenario reruns the first
# workload under SPECULATIVE decoding (a one-layer draft plus a self-draft
# pass) and asserts greedy token parity with the plain engine, nonzero
# acceptance, and zero leaks across both page pools. A fourth scenario
# runs with request tracing + the step timeline ON, asserts the tokens are
# bitwise-identical to an untraced engine, validates the exported Perfetto
# trace (well-formed JSON, per-request span count == completed requests,
# step slices present), and cross-checks the unified metrics registry
# against engine ground truth; CI uploads traces/serving_trace.json as a
# build artifact. A fifth scenario pins the SLO burn-rate monitor (tight
# objective fires, loose stays quiet). A sixth scenario drives the
# observability WIRE: introspection server scraped (/metrics under the
# strict Prometheus grammar, /healthz, /statusz) from another thread
# mid-run with token parity, the XLA program ledger populated, and the
# armed recompile sentinel reading zero at steady state;
# traces/statusz_snapshot.json is uploaded as a CI artifact.
#
#   bash tools/serving_smoke.sh            # the six default scenarios
#   bash tools/serving_smoke.sh mesh       # mesh-sharded scenario only
#   bash tools/serving_smoke.sh frontdoor  # front-door scenario only
#   bash tools/serving_smoke.sh disttrace  # fleet-wide tracing scenario
#   bash tools/serving_smoke.sh perfwatch  # performance observatory drill
#   bash tools/serving_smoke.sh hostkv     # hierarchical-KV host tier
#   bash tools/serving_smoke.sh pagedkernel  # paged-attention kernel + int8 KV
#
# The ``pagedkernel`` scenario drives the paged-attention decode kernel
# end to end on a GQA model: the interpret-mode Pallas kernel AND the
# XLA reference fallback must both produce greedy tokens bitwise-equal
# to the kernel-off inline gather, with the fused ``decode_step_paged``
# program (and no plain ``decode_step``) in the XLA ledger. Then int8
# KV quantization rides a host-tier working set and the per-page spill
# bytes are asserted TO THE BYTE against the quantized layout
# (layers x {K,V} x (page*Hkv*D x 1B payload + page*Hkv x 4B scales)),
# cross-checked against the d2h/h2d transfer ledger, with zero leaked
# pages and both quiescence gates clean over the scale buffers.
#
# The ``hostkv`` scenario drives the host-RAM page tier with a prefix
# working set FOUR TIMES the device page pool: every re-used prompt's
# pages must round-trip through a d2h spill and an h2d fetch, with a
# nonzero host-tier hit rate, greedy tokens bitwise-identical to a
# tier-off engine, the spill/fetch byte counters matching the XLA
# transfer ledger exactly, and close()'s quiescence gate covering the
# host buffers (no pinned entries, no undrained spills, zero leaks).
#
# The ``mesh`` scenario boots the engine on a (2,4) ("data","model") mesh
# over 8 virtual CPU devices, replays a shared-prefix workload, and
# asserts greedy-token parity against a (1,1) mesh AND the unsharded
# engine, a nonzero prefix hit rate, the mesh gauges, and zero page
# leaks.
#
# The ``frontdoor`` scenario drives the streaming gateway: six streams
# across two declared tenants, one cancelled mid-stream (partial output
# a strict prefix of the polled reference, pages freed), one
# grammar-constrained request replayed through its compiled DFA, every
# surviving stream bitwise-identical to a polled bare-engine run, and
# the armed recompile sentinel reading zero across the whole mix.
#
# This is the CI end-to-end drill for the serving subsystem: engine +
# scheduler + paged cache + prefix cache + admission metrics in one pass,
# deterministic (greedy decode, fixed seeds), < a minute on a laptop CPU.

set -euo pipefail
cd "$(dirname "$0")/.."

scenario="${1:-default}"

if [ "$scenario" = "mesh" ]; then
  env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" python - <<'EOF'
import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    InferenceEngine,
    SamplingParams,
    make_serving_mesh,
)

assert len(jax.devices()) == 8, jax.devices()

# n_heads 8 so every sharded dim divides the model axis of a (2,4) mesh.
model = TransformerLM(
    vocab_size=128, d_model=32, n_layers=2, n_heads=8, d_ff=64,
    dtype=jnp.float32,
)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

rng = np.random.default_rng(0)
system = rng.integers(0, 128, 12).tolist()
waves = [
    [
        system + rng.integers(0, 128, int(rng.integers(2, 6))).tolist()
        for _ in range(2)
    ]
    for _ in range(3)
]

def replay(mesh):
    e = InferenceEngine(
        model, params, max_slots=4, max_seq_len=32, page_size=4,
        token_budget=16, max_prefill_chunk=8, debug=True, mesh=mesh,
    )
    rids = []
    for wave in waves:  # later waves find earlier waves' pages cached
        rids += [
            e.submit(p, SamplingParams(max_new_tokens=4)) for p in wave
        ]
        e.run()
    toks = [e.poll(r).generated for r in rids]
    stats = e.stats()
    gauges = e.registry.snapshot()["gauges"]
    e.close()
    e.allocator.check_invariants()
    return toks, stats, gauges

base, s0, _ = replay(None)
one, s1, g1 = replay(make_serving_mesh(1, 1))
sharded, s2, g2 = replay(make_serving_mesh(2, 4))

assert one == base, "(1,1) mesh diverged from the unsharded engine"
assert sharded == base, "(2,4) mesh diverged from the unsharded engine"
for name, s in (("unsharded", s0), ("1x1", s1), ("2x4", s2)):
    assert s["prefix_hit_rate"] > 0, (
        f"{name}: shared-prefix workload produced no cache hits: {s}"
    )
    assert s["pages_allocated"] == 0, f"{name}: pages leaked after drain"
assert g2["serving_data_axis_size"] == 2
assert g2["serving_model_axis_size"] == 4
assert g2["serving_mesh_2x4_info"] == 1.0
assert g2["serving_sharded_program_count"] >= 2
assert g1["serving_mesh_1x1_info"] == 1.0

print(
    "[serving_smoke] PASS: mesh scenario, greedy parity unsharded == "
    f"1x1 == 2x4 over {len(base)} requests, "
    f"hit_rate={s2['prefix_hit_rate']:.2f} "
    f"sharded_programs={int(g2['serving_sharded_program_count'])}"
)
EOF
  exit 0
fi

if [ "$scenario" = "frontdoor" ]; then
  env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python - <<'EOF'
import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    FrontDoor,
    InferenceEngine,
    Mods,
    SamplingParams,
    TenantConfig,
    compile_grammar,
)

VOCAB = 128
model = TransformerLM(
    vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
rng = np.random.default_rng(7)
prompts = [
    rng.integers(0, VOCAB, int(n)).tolist() for n in rng.integers(3, 9, 6)
]
sp = SamplingParams(max_new_tokens=6)

# Polled reference: same prompts on a bare engine, no door in the path.
ref_eng = InferenceEngine(model, params, **ENGINE_KW)
rids = [ref_eng.submit(p, sp) for p in prompts]
ref_eng.run()
ref = [list(ref_eng.requests[r].generated) for r in rids]
ref_eng.close()

eng = InferenceEngine(model, params, xla_ledger=True, **ENGINE_KW)
door = FrontDoor(
    eng,
    tenants={
        "gold": TenantConfig(weight=3.0, ttft_slo_s=30.0),
        "bronze": TenantConfig(weight=1.0),
    },
)

GRAMMAR = "[10-40] [10-40]+"
# Warm both group shapes (clean async + grammar sync) before arming: the
# sentinel gates steady state, not first-touch compilation.
warm = [
    door.open_stream(prompts[0], "gold", params=sp),
    door.open_stream(prompts[1], "bronze", params=sp,
                     mods=Mods(grammar=GRAMMAR)),
]
door.drive()
assert warm[0].drain() == ref[0], "warmup stream diverged from reference"
sentinel = eng.arm_recompile_sentinel()

streams = [
    door.open_stream(p, "gold" if i % 2 == 0 else "bronze", params=sp)
    for i, p in enumerate(prompts)
]
gstream = door.open_stream(prompts[0], "gold", params=sp,
                           mods=Mods(grammar=GRAMMAR))

# Cancel one stream mid-flight: deliver two tokens, then kill it.
victim = streams[3]
pumps = 0
while victim.backlog() < 2 and not victim.done:
    door.pump()
    pumps += 1
    assert pumps < 10_000, "victim never produced two tokens"
partial = [next(victim), next(victim)]
victim.cancel()
partial += victim.drain()
assert partial == ref[3][: len(partial)], (
    f"cancelled partial {partial} is not a prefix of {ref[3]}"
)
assert len(partial) < len(ref[3]), "cancel landed after completion"
assert door.registry.read_counter("cancelled_by_client_total") == 1

# Survivors drain to completion, bitwise-identical to the polled run.
for i, s in enumerate(streams):
    if s is victim:
        continue
    assert s.drain() == ref[i], f"stream {i} diverged from polled engine"

# Grammar stream: every token must walk the compiled DFA.
gtoks = gstream.drain()
dfa = compile_grammar(GRAMMAR, VOCAB)
state = dfa.start
for tok in gtoks:
    state = dfa.advance(state, tok)  # raises on any violation
assert len(gtoks) >= 2, f"grammar needs >=2 tokens, got {gtoks}"

door.drive()
assert sentinel.count == 0, sentinel.trips
assert eng.registry.read_counter("engine_recompiles_total") == 0
stats = eng.stats()
assert stats["pages_allocated"] == 0, "pages leaked after cancel + drain"
eng.close()
eng.allocator.check_invariants()

print(
    "[serving_smoke] PASS: front door, "
    f"{len(streams) - 1} streams bitwise == polled across 2 tenants, "
    f"cancel mid-stream after {len(partial)} tokens, "
    f"grammar {GRAMMAR!r} validated over {len(gtoks)} tokens, "
    f"recompile sentinel == 0"
)
EOF
  exit 0
fi

# ``disttrace``: the fleet-wide distributed-tracing drill — door over a
# 3-replica router, every layer tracing, seeded kill of the affinity
# replica. Asserts token parity with an untraced bare engine, ONE
# trace_id for the victim across original + survivor replicas with a
# nonzero failover_gap, flow arrows spanning door -> router -> replica
# lanes, the /requestz waterfall summing to e2e within 5%, and writes the
# merged trace to traces/fleet_trace.json (uploaded as a CI artifact).
if [ "$scenario" = "disttrace" ]; then
  env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python - <<'EOF'
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import (
    TraceSampler,
    Tracer,
    format_waterfall,
    merge_traces,
    request_waterfall,
    scrape,
    trace_ids,
)
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    FrontDoor,
    InferenceEngine,
    SamplingParams,
    TenantConfig,
)

VOCAB = 128
model = TransformerLM(
    vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
sp = SamplingParams(max_new_tokens=6)
# One page-aligned shared prefix -> the whole batch routes by affinity to
# one replica, which is the one the chaos plan will kill.
PREFIX = [5, 7, 11, 2]
prompts = [PREFIX + [t, t + 1] for t in (1, 9, 17, 25, 33, 41)]

# Untraced single-engine reference (token streams are batch/slot/engine
# invariant, so one bare engine covers every fleet outcome).
ref_eng = InferenceEngine(model, params, **ENGINE_KW)
rids = [ref_eng.submit(p, sp) for p in prompts]
ref_eng.run()
ref = [list(ref_eng.requests[r].generated) for r in rids]
ref_eng.close()

engines = [
    InferenceEngine(model, params, tracer=Tracer(), **ENGINE_KW)
    for _ in range(3)
]
router = FleetRouter(engines, tracer=Tracer())
sampler = TraceSampler(head_rate=1.0, max_kept=64)
door = FrontDoor(
    router,
    tenants={"anon": TenantConfig()},
    tracer=Tracer(),
    sampler=sampler,
)
streams = [door.open_stream(p, params=sp) for p in prompts]
door.pump()  # admit + route, so the affinity target is knowable
target = router._shadows[streams[0].req_id].replica
target_idx = [
    i for i, r in enumerate(router.replicas()) if r.name == target
][0]
os.environ[chaos.ENV_VAR] = json.dumps(
    {
        "seed": 0,
        "faults": [
            {"kind": "kill_replica", "replica": target_idx, "at_step": 2}
        ],
    }
)
chaos._reset()
door.drive()
outs = [s.drain() for s in streams]
os.environ.pop(chaos.ENV_VAR, None)
chaos._reset()

dead = [r.name for r in router.replicas() if r.state == "dead"]
assert dead == [target], f"expected {target} dead, got {dead}"
for i, (s, out) in enumerate(zip(streams, outs)):
    assert out == ref[i], (
        f"stream {i} diverged across failover: {out} != {ref[i]}"
    )

victims = [
    s for s in streams if router._shadows[s.req_id].failovers > 0
]
assert victims, "kill landed but no stream failed over"

docs = door.trace_documents()
assert len(docs) == 5, f"door+router+3 replicas, got {len(docs)} docs"
merged = merge_traces(*docs)
json.loads(json.dumps(merged))  # valid, round-trippable Chrome JSON
assert merged["displayTimeUnit"] == "ms"
ids = trace_ids(merged)
assert len(ids) == len(prompts), (len(ids), len(prompts))

victim = victims[0]
# ONE trace_id, visible in door + router + BOTH engine incarnations.
opened_pids = sorted(
    {
        e["pid"]
        for e in merged["traceEvents"]
        if e.get("ph") == "b"
        and e.get("args", {}).get("trace_id") == victim.trace_id
    }
)
assert len(opened_pids) >= 4, (
    f"victim {victim.trace_id} spans pids {opened_pids}; expected door, "
    "router, original replica, and failover survivor"
)
flow_phs = {
    e["ph"]
    for e in merged["traceEvents"]
    if e.get("cat") == "flow"
    and e.get("args", {}).get("trace_id") == victim.trace_id
}
assert flow_phs == {"s", "t"}, f"flow arrows incomplete: {flow_phs}"

wf = request_waterfall(merged, victim.trace_id)
print(format_waterfall(wf))
total = sum(wf["components"].values())
assert abs(total - wf["e2e_s"]) <= 0.05 * wf["e2e_s"], (
    f"waterfall sum {total} vs e2e {wf['e2e_s']} off by more than 5%"
)
assert wf["components"]["failover_gap"] > 0, "no failover gap attributed"

# The wire view: /requestz on the door's own introspection server.
server = door.serve()
try:
    listing = scrape(server.url, "/requestz")
    assert victim.trace_id in listing["trace_ids"], listing
    remote_wf = scrape(server.url, f"/requestz?id={victim.trace_id}")
    assert remote_wf["trace_id"] == victim.trace_id
    remote_total = sum(remote_wf["components"].values())
    assert abs(remote_total - remote_wf["e2e_s"]) <= 0.05 * remote_wf["e2e_s"]
finally:
    server.stop()

assert sampler.counters()["traces_ended"] == len(prompts)

os.makedirs("traces", exist_ok=True)
with open("traces/fleet_trace.json", "w") as f:
    json.dump(merged, f)

for eng in engines:
    try:
        eng.close()
    except Exception:
        pass

print(
    "[serving_smoke] PASS: disttrace scenario, "
    f"{len(prompts)} streams token-identical across a seeded kill of "
    f"{target}, victim {victim.trace_id} is ONE trace across "
    f"{len(opened_pids)} lanes with failover_gap "
    f"{wf['components']['failover_gap'] * 1e3:.1f} ms, waterfall sums to "
    f"e2e within 5%, merged trace -> traces/fleet_trace.json"
)
EOF
  exit 0
fi

# ``perfwatch``: the performance-observatory drill — TSDB + roofline +
# regression detector ON, tokens bitwise-identical to a bare engine, the
# /timeseries and /graphz wire views scraped mid-run, then a seeded
# slow_program chaos stall that the CUSUM detector must notice within
# budget AND blame on the stalled phase. Writes the full TSDB dump to
# traces/timeseries_dump.json (uploaded as a CI artifact).
if [ "$scenario" = "perfwatch" ]; then
  env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python - <<'EOF'
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs.server import scrape
from distributed_pytorch_tpu.obs.timeseries import TimeSeriesDB
from distributed_pytorch_tpu.serving import InferenceEngine, SamplingParams

VOCAB = 128
model = TransformerLM(
    vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
sp = SamplingParams(max_new_tokens=6)
rng = np.random.default_rng(11)
prompts = [
    rng.integers(0, VOCAB, int(n)).tolist() for n in rng.integers(3, 10, 6)
]
drill_prompts = [
    rng.integers(0, VOCAB, int(n)).tolist() for n in rng.integers(3, 10, 6)
]

def replay(eng, batch):
    rids = [eng.submit(p, sp) for p in batch]
    eng.run()
    return [eng.poll(r).generated for r in rids]

# Bare reference: no observatory anywhere near the engine.
ref_eng = InferenceEngine(model, params, **ENGINE_KW)
ref = replay(ref_eng, prompts)
ref_drill = replay(ref_eng, drill_prompts)
ref_eng.close()

os.environ.pop(chaos.ENV_VAR, None)
chaos._reset()
# Small raw ring (16 samples) so it WRAPS during the clean pass and the
# flat-memory assertion below checks steady state, not a filling buffer.
eng = InferenceEngine(
    model, params, timeseries=TimeSeriesDB(raw_capacity=16),
    xla_ledger=True, **ENGINE_KW
)
# Warm every prefill bucket + decode so the clean pass is compile-free
# (this also runs the detector through its median/MAD warm-up).
chunk = 1
while chunk <= 8:
    warm = eng.submit(
        [(37 * chunk + i) % VOCAB for i in range(chunk + 1)],
        SamplingParams(max_new_tokens=2),
    )
    eng.run()
    assert eng.poll(warm).finished
    chunk *= 2

toks = replay(eng, prompts)
assert toks == ref, "the performance observatory changed the tokens"
assert eng.regress.alerts == 0, (
    f"detector fired on a clean pass: {eng.regress.state()}"
)
assert eng.timeseries.status()["samples_taken"] > 16, (
    "clean pass too short to wrap the raw ring"
)
mem_before = eng.timeseries.memory_bytes()

# The wire views, mid-run: /timeseries JSON + /graphz sparklines.
server = eng.serve()
try:
    ts = scrape(server.url, "/timeseries?series=step_wall_seconds,tokens_per_sec")
    assert set(ts["series"]) == {"step_wall_seconds", "tokens_per_sec"}, ts
    assert ts["series"]["step_wall_seconds"]["points"], "empty step series"
    graphz = scrape(server.url, "/graphz")
    assert "step_wall_seconds" in graphz, "graphz missing the step series"
    assert any(c in graphz for c in "▁▂▃▄▅▆▇█"), "graphz has no sparklines"
finally:
    server.stop()

# Seeded drill: stall the dispatch phase persistently from the 3rd step
# of the drill batch. The detector must fire within budget and blame
# dispatch — and the stall must not change a single greedy token.
injected = {}

def observer(kind, step, mode):
    if kind == "slow_program" and "at" not in injected:
        injected["at"] = eng.regress.steps + 1

os.environ[chaos.ENV_VAR] = json.dumps({
    "faults": [
        {"kind": "slow_program", "phase": "dispatch",
         "duration": 0.05, "at_step": 3}
    ],
})
chaos._reset()  # re-arm from the env var (this also clears observers)
chaos.add_fault_observer(observer)
try:
    drill_toks = replay(eng, drill_prompts)
finally:
    chaos.remove_fault_observer(observer)
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()

assert drill_toks == ref_drill, "the injected stall changed the tokens"
assert eng.regress.alerts >= 1, (
    f"detector never fired under a persistent stall: {eng.regress.state()}"
)
event = eng.regress.events[0]
latency = event["step"] - injected["at"] + 1
assert 1 <= latency <= 10, (
    f"detection took {latency} stalled steps (event {event}, "
    f"injected at {injected['at']})"
)
assert event["attributed_phase"] == "dispatch", event
snap = eng.registry.snapshot()
assert snap["counters"]["serving_perf_regressions_total"] >= 1
assert snap["gauges"]["serving_perf_regression_firing"] == 1.0

# Memory bound: the drill added ~30 steps past a wrapped raw ring; only
# a couple of fresh downsample buckets may appear, never per-step growth.
mem_after = eng.timeseries.memory_bytes()
assert mem_after <= mem_before * 1.05 + 8192, (mem_before, mem_after)

os.makedirs("traces", exist_ok=True)
with open("traces/timeseries_dump.json", "w") as f:
    json.dump(
        {
            "status": eng.timeseries.status(),
            "regress": eng.regress.state(),
            "roofline": eng.roofline.report() if eng.roofline else None,
            "dump": eng.timeseries.dump(),
        },
        f, indent=1, default=str,
    )
n_series = eng.timeseries.status()["series"]
eng.close()

print(
    "[serving_smoke] PASS: perfwatch scenario, tokens identical with the "
    f"observatory on AND under a seeded dispatch stall, detector fired "
    f"after {latency} stalled step(s) blaming "
    f"{event['attributed_phase']!r}, "
    f"tsdb {n_series} series / "
    f"{mem_after} bytes -> traces/timeseries_dump.json"
)
EOF
  exit 0
fi

if [ "$scenario" = "hostkv" ]; then
  env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python - <<'EOF'
import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import InferenceEngine, SamplingParams

VOCAB = 128
model = TransformerLM(
    vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

# Device pool: 8 usable pages. Prefix working set: 16 disjoint two-page
# prompts = 32 pages, FOUR TIMES the device pool — every prompt's pages
# are long evicted by the time it recurs, so pass 2 can only hit through
# the host tier.
DEVICE_PAGES = 9
PROMPTS = [
    [(i * 8 + j) % VOCAB + 1 for j in range(8)] for i in range(16)
]
ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, num_pages=DEVICE_PAGES,
    token_budget=16, max_prefill_chunk=8, debug=True,
)
sp = SamplingParams(max_new_tokens=4)

def run_workload(host_pages):
    eng = InferenceEngine(
        model, params, host_pages=host_pages, xla_ledger=True, **ENGINE_KW
    )
    outs = []
    for _ in range(2):
        for p in PROMPTS:
            rid = eng.submit(p, sp)
            eng.run()
            outs.append(eng.poll(rid).generated)
    stats = eng.stats()
    assert stats["pages_allocated"] == 0, "device pages leaked"
    eng.allocator.check_invariants()
    # close() drains trailing spills and runs BOTH quiescence gates:
    # allocator (zero referenced pages) and host tier (no pinned
    # entries, no undrained spills, slot partition exact).
    eng.close()
    return eng, outs, stats

eng_off, outs_off, stats_off = run_workload(None)
eng_on, outs_on, stats_on = run_workload(48)

assert outs_on == outs_off, "host tier changed greedy tokens"
assert stats_on["prefix_tokens_hit_host"] > 0, (
    f"no host-tier hits over a 4x working set: {stats_on}"
)
assert stats_on["hostkv_spills"] > 0 and stats_on["hostkv_fetches"] > 0
assert stats_on["prefix_hit_rate_total"] > stats_off["prefix_hit_rate_total"]
md = eng_on.xla.metadata()
assert md["bytes_d2h_by_tag"].get("hostkv_spill", 0) == \
    eng_on.hostkv.spill_bytes_total, "spill bytes drifted from the ledger"
assert md["bytes_h2d_by_tag"].get("hostkv_fetch", 0) == \
    eng_on.hostkv.fetch_bytes_total, "fetch bytes drifted from the ledger"

print(
    "[serving_smoke] PASS: hostkv scenario, 32/32 requests over a "
    f"4x-device working set, host hit tokens "
    f"{stats_on['prefix_tokens_hit_host']}, "
    f"hit rate {stats_off['prefix_hit_rate_total']:.3f} -> "
    f"{stats_on['prefix_hit_rate_total']:.3f}, "
    f"{stats_on['hostkv_spills']} spills / "
    f"{stats_on['hostkv_fetches']} fetches, byte counters == ledger, "
    "zero leaks, both tiers quiescent at close()"
)
EOF
  exit 0
fi

if [ "$scenario" = "pagedkernel" ]; then
  env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python - <<'EOF'
import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import InferenceEngine, SamplingParams

VOCAB = 128
# GQA (4 query heads over 2 KV heads) so the kernel's grouped-query head
# mapping is actually exercised, not just the degenerate MHA case.
model = TransformerLM(
    vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, dtype=jnp.float32,
)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
sp = SamplingParams(max_new_tokens=4)
rng = np.random.default_rng(19)
prompts = [
    rng.integers(0, VOCAB, int(n)).tolist() for n in rng.integers(3, 10, 6)
]

def replay(**kw):
    eng = InferenceEngine(model, params, xla_ledger=True, **ENGINE_KW, **kw)
    rids = [eng.submit(p, sp) for p in prompts]
    eng.run()
    outs = [eng.poll(r).generated for r in rids]
    stats = eng.stats()
    assert stats["pages_allocated"] == 0, "pages leaked after drain"
    names = {r.name for r in eng.xla.programs.values()}
    eng.allocator.check_invariants()
    eng.close()
    return outs, names

# --- part 1: kernel parity. The fp paged path is BITWISE: the inline
# gather, the XLA reference fallback, and the real kernel math (Pallas
# interpret mode on CPU) must agree on every greedy token.
base, base_names = replay()
xla_outs, xla_names = replay(paged_kernel="xla")
interp_outs, interp_names = replay(paged_kernel="interpret")

assert xla_outs == base, "XLA fallback diverged from the inline gather"
assert interp_outs == base, "interpret-mode kernel diverged from inline gather"
for label, names in (("xla", xla_names), ("interpret", interp_names)):
    assert any(n.startswith("decode_step_paged") for n in names), (
        f"{label}: fused decode program missing from the ledger: {names}"
    )
    assert "decode_step" not in names, (
        f"{label}: plain decode_step compiled alongside the paged one"
    )
assert any(n == "decode_step" for n in base_names), base_names

# --- part 2: int8 KV pages over the host tier, byte-exact accounting.
# Working set 4x the device pool (as in the hostkv scenario) so every
# recurring prompt's pages round-trip a d2h spill + h2d fetch at the
# QUANTIZED page size.
DEVICE_PAGES = 9
WS_PROMPTS = [
    [(i * 8 + j) % VOCAB + 1 for j in range(8)] for i in range(16)
]

def working_set(**kw):
    eng = InferenceEngine(
        model, params, num_pages=DEVICE_PAGES, host_pages=48,
        xla_ledger=True, **ENGINE_KW, **kw,
    )
    outs = []
    for _ in range(2):
        for p in WS_PROMPTS:
            rid = eng.submit(p, sp)
            eng.run()
            outs.append(eng.poll(rid).generated)
    stats = eng.stats()
    assert stats["pages_allocated"] == 0, "device pages leaked"
    assert stats["prefix_tokens_hit_host"] > 0, stats
    eng.allocator.check_invariants()
    # close() runs both quiescence gates — allocator AND host tier —
    # which for int8 covers the scale buffers riding in each page.
    eng.close()
    return eng, outs

fp_eng, _ = working_set()
q8_eng, q8_outs = working_set(kv_quant="int8", paged_kernel="xla")

# Per-page spill bytes, TO THE BYTE: fp pages are layers x {K,V} x
# page*Hkv*D fp32 words; int8 pages are the same payload at 1 byte per
# element plus a per-(position, head) fp32 scale.
kv_heads = model.n_kv_heads or model.n_heads
d = model.d_model // model.n_heads
page = ENGINE_KW["page_size"]
fp_page = model.n_layers * 2 * page * kv_heads * d * 4
q8_page = model.n_layers * 2 * (page * kv_heads * d + page * kv_heads * 4)
assert q8_page < fp_page / 2, (q8_page, fp_page)

for label, eng, page_bytes in (
    ("fp", fp_eng, fp_page), ("int8", q8_eng, q8_page),
):
    c = eng.hostkv.counters()
    assert c["hostkv_spills"] > 0 and c["hostkv_fetches"] > 0, (label, c)
    assert eng.hostkv.spill_bytes_total == c["hostkv_spills"] * page_bytes, (
        f"{label}: spill bytes not an exact multiple of the page layout"
    )
    md = eng.xla.metadata()
    assert md["bytes_d2h_by_tag"].get("hostkv_spill", 0) == \
        eng.hostkv.spill_bytes_total, f"{label}: spill drifted from ledger"
    assert md["bytes_h2d_by_tag"].get("hostkv_fetch", 0) == \
        eng.hostkv.fetch_bytes_total, f"{label}: fetch drifted from ledger"

print(
    "[serving_smoke] PASS: pagedkernel scenario, interpret kernel == XLA "
    f"fallback == inline gather over {len(prompts)} GQA requests, fused "
    "decode_step_paged ledgered, int8 pages spill at "
    f"{q8_page}B vs {fp_page}B fp ({q8_page / fp_page:.3f}x, byte-exact "
    "against the d2h/h2d ledger), zero leaks, both tiers quiescent"
)
EOF
  exit 0
fi

env JAX_PLATFORMS=cpu PYTHONPATH="$PWD" python - <<'EOF'
import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import InferenceEngine, SamplingParams

model = TransformerLM(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
# Pool sized below the worst case (16 usable pages vs 32 worst-case) so
# the drill can cross the preemption path when staggered arrivals overlap.
eng = InferenceEngine(
    model, params, max_slots=4, max_seq_len=32, page_size=4,
    num_pages=17, token_budget=16, max_prefill_chunk=8,
)

rng = np.random.default_rng(0)
ids = []
want = {}
for wave in range(4):  # 4 waves x 2 requests, separated by engine steps
    for _ in range(2):
        prompt = rng.integers(0, 128, int(rng.integers(3, 10))).tolist()
        n_new = int(rng.integers(4, 9))
        rid = eng.submit(prompt, SamplingParams(max_new_tokens=n_new))
        ids.append(rid)
        want[rid] = n_new
    for _ in range(3):
        eng.step()
eng.run()

assert len(ids) == 8
for rid in ids:
    st = eng.poll(rid)
    assert st.finished, f"request {rid} did not finish: {st}"
    assert len(st.generated) == want[rid], (
        f"request {rid}: {len(st.generated)} tokens, wanted {want[rid]}"
    )

s = eng.stats()
for key in ("ttft_s_p50", "tpot_s_p50", "e2e_s_p50"):
    assert s[f"{key[:-3]}count"] == 8, f"{key}: reservoir not fully populated"
    assert np.isfinite(s[key]) and s[key] > 0, f"{key} empty: {s[key]}"
assert s["requests_completed"] == 8
assert s["pages_allocated"] == 0, "pages leaked after drain"
eng.allocator.check_invariants()

print(
    "[serving_smoke] PASS: 8/8 requests, "
    f"{s['tokens_generated']} tokens, "
    f"ttft_p50={s['ttft_s_p50'] * 1e3:.1f}ms "
    f"tpot_p50={s['tpot_s_p50'] * 1e3:.2f}ms "
    f"preemptions={s['preemptions']}"
)

# ---- scenario 2: shared system prompt -> the prefix cache must hit ----
eng2 = InferenceEngine(
    model, params, max_slots=4, max_seq_len=32, page_size=4,
    token_budget=16, max_prefill_chunk=8, debug=True,
)
system = rng.integers(0, 128, 12).tolist()  # 3 full pages when aligned
ids2 = []
for wave in range(3):  # later waves find earlier waves' pages cached
    for _ in range(2):
        tail = rng.integers(0, 128, int(rng.integers(2, 6))).tolist()
        ids2.append(
            eng2.submit(system + tail, SamplingParams(max_new_tokens=4))
        )
    eng2.run()
for rid in ids2:
    assert eng2.poll(rid).finished, f"request {rid} did not finish"

s2 = eng2.stats()
assert s2["prefix_hit_rate"] > 0, (
    f"shared-prefix workload produced no cache hits: {s2['prefix_hit_rate']}"
)
assert s2["prefix_tokens_hit"] >= 8, (
    f"expected the shared system pages to be re-served: {s2}"
)
assert s2["cached_tokens_admitted"] > 0
assert s2["ttft_s_hit_count"] > 0, "TTFT hit-reservoir never populated"
assert s2["pages_allocated"] == 0, "pages leaked after drain"
eng2.allocator.check_invariants()

print(
    "[serving_smoke] PASS: shared-prefix scenario, "
    f"hit_rate={s2['prefix_hit_rate']:.2f} "
    f"tokens_hit={s2['prefix_tokens_hit']} "
    f"cow_copies={s2['cow_copies']} evictions={s2['page_evictions']}"
)

# ---- scenario 3: speculative decoding must match the plain engine ----
# Replay a fixed workload on a plain engine, then on speculative engines.
# Greedy speculative serving is exact (not approximate): every request's
# tokens must be identical. The self-draft pass pins acceptance == 1 and
# multi-token advance; the independent one-layer draft exercises real
# rejections/rollback and must STILL be token-identical.
draft = TransformerLM(
    vocab_size=128, d_model=32, n_layers=1, n_heads=2, d_ff=64,
    dtype=jnp.float32,
)
draft_params = draft.init(
    jax.random.PRNGKey(3), jnp.zeros((1, 8), jnp.int32)
)["params"]

prompts3 = [
    rng.integers(0, 128, int(rng.integers(3, 10))).tolist()
    for _ in range(6)
]

def replay(**kw):
    e = InferenceEngine(
        model, params, max_slots=4, max_seq_len=32, page_size=4,
        token_budget=16, max_prefill_chunk=8, debug=True, **kw,
    )
    rids = [e.submit(p, SamplingParams(max_new_tokens=6)) for p in prompts3]
    e.run()
    return [e.poll(r).generated for r in rids], e

plain, _ = replay()
for name, dm, dp in (
    ("one-layer draft", draft, draft_params),
    ("self-draft", model, params),
):
    spec, e3 = replay(draft_model=dm, draft_params=dp, gamma=3)
    assert spec == plain, f"speculative ({name}) diverged from plain engine"
    s3 = e3.stats()
    assert s3["verify_rounds"] > 0
    assert s3["spec_acceptance_rate"] >= 0.0
    assert s3["pages_allocated"] == 0, "pages leaked after spec drain"
    e3.allocator.check_invariants()
assert s3["spec_acceptance_rate"] == 1.0, (
    "self-draft must accept every proposal"
)
assert s3["tokens_generated"] > s3["verify_rounds"], (
    "full acceptance should advance multiple tokens per round"
)

print(
    "[serving_smoke] PASS: speculative scenario, greedy parity across "
    f"drafts, self-draft acceptance={s3['spec_acceptance_rate']:.2f} "
    f"tokens/verify={s3['spec_tokens_per_verify_mean']:.2f}"
)

# ---- scenario 4: tracing on -> identical tokens + valid Perfetto trace ----
import json

from distributed_pytorch_tpu.obs import Tracer

prompts4 = [
    rng.integers(0, 128, int(rng.integers(3, 10))).tolist()
    for _ in range(6)
]

def replay4(tracer=None):
    e = InferenceEngine(
        model, params, max_slots=4, max_seq_len=32, page_size=4,
        token_budget=16, max_prefill_chunk=8, tracer=tracer,
    )
    rids = [e.submit(p, SamplingParams(max_new_tokens=6)) for p in prompts4]
    e.run()
    return [e.poll(r).generated for r in rids], e

untraced_tokens, _ = replay4()
tracer = Tracer()
traced_tokens, eng4 = replay4(tracer=tracer)
assert traced_tokens == untraced_tokens, (
    "tracing changed the generated tokens"
)

trace_path = eng4.save_trace("traces/serving_trace.json")
with open(trace_path) as f:
    doc = json.load(f)  # must be well-formed JSON
events = doc["traceEvents"]
n_done = eng4.metrics.requests_completed
assert n_done == 6
begins = [
    ev for ev in events
    if ev.get("ph") == "b" and ev.get("cat") == "request"
]
ends = [
    ev for ev in events
    if ev.get("ph") == "e" and ev.get("cat") == "request"
]
assert len(begins) == n_done, (
    f"trace has {len(begins)} request spans, engine completed {n_done}"
)
assert len(ends) == n_done, "unclosed request spans in the trace"
assert any(
    ev.get("ph") == "X" and ev.get("name") == "step" for ev in events
), "no engine step slices in the trace"
assert any(
    ev.get("ph") == "X" and ev.get("name") == "schedule" for ev in events
), "no step phase slices in the trace"

snap = eng4.registry.snapshot()
assert snap["counters"]["serving_requests_completed_total"] == n_done
assert snap["counters"]["serving_tokens_generated_total"] == sum(
    len(t) for t in traced_tokens
)
assert snap["gauges"]["serving_pages_referenced"] == 0
assert "serving_tokens_generated_total" in eng4.registry.prometheus_text()

print(
    "[serving_smoke] PASS: tracing scenario, tokens identical, "
    f"{len(begins)} request spans == {n_done} completed, "
    f"{len(events)} trace events -> {trace_path}"
)

# ---- scenario 5: a deliberately-tight SLO must fire its alert counter ----
# Two objectives on the same engine: tpot_tight (threshold 1ns — every
# sample breaches, burn = 1/budget >> both burn thresholds) MUST fire;
# tpot_loose (threshold 1s — a CPU microbench never breaches) MUST stay
# quiet. The alert lands in the registry, so it survives into scrapes.
from distributed_pytorch_tpu.obs import SLObjective

eng5 = InferenceEngine(
    model, params, max_slots=4, max_seq_len=32, page_size=4,
    token_budget=16, max_prefill_chunk=8,
    slo=[
        SLObjective(
            name="tpot_tight", metric="tpot_seconds", quantile=0.5,
            threshold_s=1e-9, fast_window_s=0.5, slow_window_s=2.0,
        ),
        SLObjective(
            name="tpot_loose", metric="tpot_seconds", quantile=0.5,
            threshold_s=1.0, fast_window_s=0.5, slow_window_s=2.0,
        ),
    ],
)
ids5 = [eng5.submit(p, SamplingParams(max_new_tokens=6)) for p in prompts4]
eng5.run()
assert all(eng5.poll(r).finished for r in ids5)

snap5 = eng5.registry.snapshot()["counters"]
assert snap5["serving_slo_tpot_tight_alerts_total"] >= 1, (
    f"tight SLO never fired: {eng5.slo.state()}"
)
assert snap5["serving_slo_tpot_loose_alerts_total"] == 0, (
    f"loose SLO fired spuriously: {eng5.slo.state()}"
)
state5 = eng5.slo.state()
assert state5["tpot_tight"]["firing"], state5
prom5 = eng5.registry.prometheus_text()
assert "# TYPE serving_slo_tpot_tight_alerts_total counter" in prom5

print(
    "[serving_smoke] PASS: SLO scenario, tight objective fired "
    f"{int(snap5['serving_slo_tpot_tight_alerts_total'])} alert(s) "
    f"(burn_fast={state5['tpot_tight']['burn_fast']:.1f}), "
    "loose objective quiet"
)

# ---- scenario 6: the observability wire, scraped over HTTP mid-run ----
# Engine + XLA program ledger + armed recompile sentinel + introspection
# server; a scraper thread GETs /metrics + /healthz + /statusz WHILE the
# engine steps. Asserts: tokens bitwise-identical to the unserved engine
# of scenario 4, /metrics parses under the strict Prometheus grammar,
# /healthz is live during the run and draining after stop_admission(),
# and the sentinel reads ZERO across the fully-warmed steady-state run.
# The final /statusz snapshot lands in traces/ as a CI artifact.
import os
import threading

from distributed_pytorch_tpu.obs import validate_exposition
from distributed_pytorch_tpu.obs.server import scrape

eng6 = InferenceEngine(
    model, params, max_slots=4, max_seq_len=32, page_size=4,
    token_budget=16, max_prefill_chunk=8, xla_ledger=True,
)
# Warm every program the workload needs — one request per power-of-two
# prefill bucket (a prompt of length c+1 prefills exactly one c-chunk)
# plus the shared decode step — then arm: from here a recompile is a
# failure. Warm prompts must share NO prefix: a prefix-cache hit would
# shave pages off the prefill and leave the big chunk uncompiled.
chunk = 1
while chunk <= 8:
    warm = eng6.submit(
        [(37 * chunk + i) % 128 for i in range(chunk + 1)],
        SamplingParams(max_new_tokens=2),
    )
    eng6.run()
    assert eng6.poll(warm).finished
    chunk *= 2
sentinel = eng6.arm_recompile_sentinel()
server = eng6.serve()

stop = threading.Event()
scrapes = {"n": 0, "errors": 0, "live": 0}

def scraper():
    while not stop.is_set():
        try:
            validate_exposition(scrape(server.url, "/metrics", timeout=30.0))
            health = scrape(server.url, "/healthz", timeout=30.0)
            statusz = scrape(server.url, "/statusz", timeout=30.0)
            scrapes["n"] += 1
            if health["status"] == "live" and statusz["health"] == "live":
                scrapes["live"] += 1
        except Exception:
            scrapes["errors"] += 1
        stop.wait(0.02)

thread = threading.Thread(target=scraper, daemon=True)
thread.start()
ids6 = [eng6.submit(p, SamplingParams(max_new_tokens=6)) for p in prompts4]
eng6.run()
stop.set()
thread.join(timeout=30)

served_tokens = [eng6.poll(r).generated for r in ids6]
assert served_tokens == untraced_tokens, (
    "the introspection server changed the generated tokens"
)
assert scrapes["n"] > 0 and scrapes["errors"] == 0, scrapes
assert scrapes["live"] == scrapes["n"], scrapes
assert sentinel.count == 0, (
    f"recompile sentinel tripped at steady state: {sentinel.trips}"
)

statusz = scrape(server.url, "/statusz")
assert statusz["engine"]["steps"] == eng6.metrics.engine_steps
assert statusz["recompile_sentinel"]["armed"]
assert {p["name"] for p in statusz["xla"]["programs"]} >= {"decode_step"}
os.makedirs("traces", exist_ok=True)
with open("traces/statusz_snapshot.json", "w") as f:
    json.dump(statusz, f, indent=1, default=str)

eng6.stop_admission()
assert scrape(server.url, "/healthz")["status"] == "draining"
eng6.close()

print(
    "[serving_smoke] PASS: observability wire, tokens identical with "
    f"server scraped mid-run ({scrapes['n']} scrapes, all valid), "
    f"recompiles_at_steady_state={sentinel.count}, "
    f"{len(statusz['xla']['programs'])} programs ledgered "
    "-> traces/statusz_snapshot.json"
)
EOF
