#!/usr/bin/env bash
# Seeded 2-node chaos drill on CPU: worker kill, hung worker, corrupt
# snapshot, a 2s store partition, and a mid-epoch drain preemption — one
# FaultPlan, one run, deterministic.
#
#   bash tools/chaos_smoke.sh            # training drill (default)
#   bash tools/chaos_smoke.sh serving    # elastic-serving drill
#
# The serving scenario SIGKILLs an inference engine mid-verify under queue
# pressure (6 requests, 2 slots), restores the last rolling snapshot into a
# fresh process, and asserts every admitted request completes with greedy
# output token-identical to an uninterrupted reference run. The engine runs
# with the flight recorder + goodput accounting on: the chaos fault dumps a
# postmortem BEFORE the SIGKILL lands (validated here by replaying it into
# a Chrome trace-event document, then preserved as
# traces/chaos_postmortem.json for the CI artifact upload), and the
# restored engine must attribute nonzero wasted time to restore re-prefill.
#
# What it proves (the full failure-model matrix of docs/ARCHITECTURE.md in
# one pass):
#   * generation 0: worker 1 is SIGKILLed at step 21 -> restart-the-world;
#   * generation 1: process 0's first snapshot write (epochs_run=2) is
#     bit-flipped right after landing on disk, and worker 1 HANGS at step 21
#     (alive but silent) -> the --worker-heartbeat-timeout detector fires;
#   * each agent's store traffic is cut for 2s at t=3 (FaultProxy) -> ridden
#     out inside --store-retry-deadline, no spurious restart;
#   * generation 2: the corrupt latest snapshot is quarantined (.corrupt),
#     resume falls back to <snapshot>.prev — and 5 steps into the replayed
#     epoch worker 1 is DRAIN-preempted: both ranks agree on the stop step
#     (per-batch allgather), take a just-in-time snapshot at (epoch 1,
#     step 5), and exit with the drain code. Both agents classify the exit
#     as a preemption: the restart is FREE (budget already exhausted at
#     2/2 and the run still continues);
#   * generation 3: resumes mid-epoch at the exact batch, completes all 3
#     epochs.
#
# Unlike the <90s pytest drill (tests/test_chaos.py::TestSeededDrill), this
# includes the HANG fault: detecting a hang needs a worker-heartbeat window
# larger than JAX startup, so this script trades the tight wall-clock bound
# for coverage of the hung-worker path.

set -euo pipefail
cd "$(dirname "$0")/.."
REPO="$PWD"
SCENARIO="${1:-training}"

WORK="$(mktemp -d /tmp/chaos_smoke.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT
echo "[chaos_smoke] workdir: $WORK"

# ------------------------------------------------------- serving scenario

if [ "$SCENARIO" = "serving" ]; then
  cat > "$WORK/drill.py" <<'EOF'
"""Serving chaos drill driver: ref | run | restore (see chaos_smoke.sh)."""
import json
import sys

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import FlightRecorder
from distributed_pytorch_tpu.serving import (
    EngineSnapshot,
    InferenceEngine,
    SamplingParams,
    restore_engine,
    snapshot_engine,
)

mode, snap_path = sys.argv[1], sys.argv[2]
PROMPTS = [[5, 7, 11, 2, 9, 3], [5, 7, 11, 2, 1], [1, 4, 8],
           [2, 2, 3, 17, 40], [6, 1, 9, 9], [3, 3, 7]]


def build():
    model = TransformerLM(vocab_size=48, d_model=16, n_layers=2,
                          n_heads=2, d_ff=32, dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    # 2 slots under 6 requests: real queue pressure at the fault. The
    # flight recorder dumps its ring to postmortem.json the instant the
    # chaos fault fires — BEFORE the SIGKILL lands — and goodput
    # accounting attributes the restore-side re-prefill as rework.
    return InferenceEngine(model, params, max_slots=2, max_seq_len=32,
                           page_size=4, token_budget=16,
                           max_prefill_chunk=8, debug=True,
                           flight=FlightRecorder(
                               capacity=2048, path="postmortem.json"
                           ),
                           goodput=True)


eng = build()
if mode in ("ref", "run"):
    ids = [eng.submit(p, SamplingParams(max_new_tokens=8)) for p in PROMPTS]
    if mode == "ref":
        eng.run()
        print(json.dumps(
            {"ref": {str(i): eng.poll(i).generated for i in ids}}
        ))
    else:
        # Print each finish as it lands (the SIGKILL loses everything
        # buffered after it) and write a rolling snapshot every 2 steps —
        # the recovery point a no-notice kill leaves behind.
        steps = 0
        while eng.scheduler.has_work or eng._inflight is not None:
            for i in eng.step():
                print(json.dumps(
                    {"finished": i, "generated": eng.poll(i).generated}
                ), flush=True)
            steps += 1
            if steps % 2 == 0:
                snapshot_engine(eng).save(snap_path)
        print("RUN-COMPLETED")  # must be unreachable: the fault kills us
else:
    snap = EngineSnapshot.load(snap_path)
    assert len(snap.requests) > eng.max_slots, (
        f"no queue pressure at snapshot: {len(snap.requests)} live"
    )
    restored = restore_engine(eng, snap)
    eng.run()
    print(json.dumps(
        {"restored": {str(i): eng.poll(i).generated for i in restored}}
    ))
    # Goodput must charge the snapshot-replay prefill as restore rework.
    print(json.dumps({"goodput": eng.goodput.report()}))
EOF

  SERVE_ENV=("PYTHONPATH=$REPO" "JAX_PLATFORMS=cpu")
  cd "$WORK"

  env "${SERVE_ENV[@]}" python drill.py ref snap.json > ref.log
  rc=0
  env "${SERVE_ENV[@]}" \
    TPURUN_FAULT_PLAN='{"faults":[{"kind":"kill_mid_verify","at_step":4}]}' \
    python drill.py run snap.json > run.log 2>&1 || rc=$?

  fail() { echo "[chaos_smoke] FAIL: $1"; exit 1; }
  [ "$rc" -eq 137 ] || fail "engine not SIGKILLed (rc=$rc, wanted 137)"
  grep -q "SIGKILL self mid-verify" run.log || fail "kill_mid_verify never fired"
  grep -q "RUN-COMPLETED" run.log && fail "engine outlived its kill"
  [ -e snap.json ] || fail "no rolling snapshot left behind"
  [ -e postmortem.json ] || fail "no flight-recorder postmortem dump (the fault observer must dump BEFORE the SIGKILL)"

  # The postmortem must replay into a valid Chrome trace-event document.
  env "${SERVE_ENV[@]}" python - <<'EOF'
import json

from distributed_pytorch_tpu.obs import replay_to_tracer

dump = json.load(open("postmortem.json"))
assert dump["reason"] == "chaos:kill_mid_verify", dump["reason"]
assert dump["events"], "postmortem ring buffer is empty"
kinds = {e["kind"] for e in dump["events"]}
assert "chaos_fault" in kinds, f"no chaos_fault event in dump: {kinds}"
assert "step" in kinds, f"no engine step records in dump: {kinds}"
assert "admit" in kinds, f"no scheduler admit records in dump: {kinds}"
assert "registry" in dump.get("extra", {}), "postmortem lost the registry snapshot"

doc = json.loads(json.dumps(replay_to_tracer(dump).to_perfetto()))
events = doc["traceEvents"]
assert isinstance(events, list) and events, "replay produced no trace events"
assert any(
    ev.get("ph") == "X" and ev.get("name") == "step" for ev in events
), "replayed trace has no step slices"
assert any(
    ev.get("ph") == "i" and ev.get("name") == "chaos_fault" for ev in events
), "replayed trace has no chaos_fault instant"
print(f"[chaos_smoke] postmortem: {len(dump['events'])} events "
      f"(reason={dump['reason']}) -> {len(events)} trace events, replay OK")
EOF

  # Preserve the postmortem for the CI artifact upload (WORK is wiped).
  mkdir -p "$REPO/traces"
  cp postmortem.json "$REPO/traces/chaos_postmortem.json"

  env "${SERVE_ENV[@]}" python drill.py restore snap.json > restore.log
  echo "--- run.log";     cat run.log
  echo "--- restore.log"; cat restore.log

  python - <<'EOF'
import json, sys

ref = {}
for line in open("ref.log"):
    if line.startswith("{"):
        ref = {int(k): v for k, v in json.loads(line)["ref"].items()}
pre_fault, restored, goodput = {}, {}, {}
for line in open("run.log"):
    if line.startswith("{"):
        rec = json.loads(line)
        pre_fault[rec["finished"]] = rec["generated"]
for line in open("restore.log"):
    if line.startswith("{"):
        rec = json.loads(line)
        if "restored" in rec:
            restored = {int(k): v for k, v in rec["restored"].items()}
        if "goodput" in rec:
            goodput = rec["goodput"]
if set(pre_fault) | set(restored) != set(ref):
    sys.exit(f"lost requests: ref={sorted(ref)} pre-fault="
             f"{sorted(pre_fault)} restored={sorted(restored)}")
for i, want in ref.items():
    got = restored.get(i, pre_fault.get(i))
    if got != want:
        sys.exit(f"request {i} diverged: {got} != {want}")
# The restored engine re-prefills every snapshotted request's committed
# tokens — goodput must attribute that wall-clock as restore rework.
reprefill = goodput.get("wasted_s", {}).get("restore_reprefill", 0.0)
if not reprefill > 0.0:
    sys.exit(f"goodput charged no restore_reprefill rework: {goodput}")
print(f"[chaos_smoke] serving: {len(restored)} restored + "
      f"{len(set(pre_fault) - set(restored))} pre-fault finishes, "
      "all token-identical to the uninterrupted run; "
      f"restore_reprefill waste {reprefill * 1e3:.2f} ms")
EOF

  echo "[chaos_smoke] PASS (serving)"
  exit 0
fi

# ------------------------------------------------------ training scenario

PORT=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
EOF
)

cat > "$WORK/worker.py" <<'EOF'
import os, runpy, sys
pid = os.environ["PROCESS_ID"]
restart = os.environ["TPURUN_RESTART_COUNT"]
open(f"gen.{pid}.{restart}", "w").write("ok")
sys.argv = ["multihost_pod.py", "3", "1",
            "--snapshot_path", "smoke.npz", "--fake_devices", "2"]
runpy.run_path(os.environ["POD_EXAMPLE"], run_name="__main__")
EOF

# 16 steps per epoch per process (2048 samples / 2 shards / batch 64):
# step 21 is mid-epoch-2 in gen 0, and mid-epoch-2-again after the gen-1
# resume from the epoch-1 snapshot.
FAULT_PLAN='{
  "seed": 42,
  "faults": [
    {"kind": "kill", "process_id": 1, "restart": 0, "at_step": 21},
    {"kind": "corrupt_snapshot", "process_id": 0, "restart": 1,
     "at_save": 1, "mode": "flip"},
    {"kind": "hang", "process_id": 1, "restart": 1, "at_step": 21,
     "duration": 600},
    {"kind": "store_partition", "restart": null, "at_time": 3.0,
     "duration": 2.0},
    {"kind": "drain_at_step", "process_id": 1, "restart": 2, "at_step": 5}
  ]
}'

COMMON_ENV=(
  "PYTHONPATH=$REPO"
  "POD_EXAMPLE=$REPO/examples/multihost_pod.py"
  "TPURUN_FAULT_PLAN=$FAULT_PLAN"
  "JAX_PLATFORMS=cpu"
  "XLA_FLAGS=--xla_force_host_platform_device_count=2"
)

cd "$WORK"
pids=()
for RANK in 0 1; do
  env "${COMMON_ENV[@]}" python -u -m distributed_pytorch_tpu.elastic \
    --nnodes 2 --node-rank "$RANK" --nproc-per-node 1 \
    --rdzv-endpoint "127.0.0.1:$PORT" \
    --max-restarts 2 \
    --worker-heartbeat-timeout 30 \
    --store-retry-deadline 20 \
    worker.py > "agent$RANK.log" 2>&1 &
  pids+=($!)
done

rc=0
for p in "${pids[@]}"; do
  wait "$p" || rc=$?
done
for RANK in 0 1; do
  echo "--- agent$RANK.log"
  cat "agent$RANK.log"
done
if [ "$rc" -ne 0 ]; then
  echo "[chaos_smoke] FAIL: agent exited with $rc"
  exit 1
fi

fail() { echo "[chaos_smoke] FAIL: $1"; exit 1; }
ALL="$(cat agent0.log agent1.log)"

grep -q "SIGKILL self"              <<<"$ALL" || fail "gen-0 kill never fired"
grep -q "corrupting snapshot write" <<<"$ALL" || fail "snapshot corruption never fired"
grep -q "hanging for"               <<<"$ALL" || fail "gen-1 hang never fired"
grep -q "restart 2/2"               <<<"$ALL" || fail "expected two restarts"
grep -q "fell back to"              <<<"$ALL" || fail "resume did not use the .prev fallback"
grep -q "quarantined"               <<<"$ALL" || fail "corrupt snapshot was not quarantined"
[ -e smoke.npz.corrupt ]                      || fail "no .corrupt quarantine file"
[ -e gen.0.2 ]                                || fail "generation 2 never started"
grep -q "drain request (self)"      <<<"$ALL" || fail "gen-2 drain fault never fired"
grep -q "just-in-time snapshot at epoch 1, step 5" <<<"$ALL" \
                                              || fail "drain snapshot missed the agreed step"
grep -q "preempt detected"          <<<"$ALL" || fail "drain exit misclassified (no preempt log)"
grep -q "restart budget intact"     <<<"$ALL" || fail "preemption spent restart budget"
grep -q "Resuming training from snapshot at Epoch 1, step 5" <<<"$ALL" \
                                              || fail "gen-3 did not resume at the drained batch"
[ -e gen.0.3 ] && [ -e gen.1.3 ]              || fail "generation 3 (the free restart) never started"

# All three epochs trained, exactly once each in the surviving timeline.
python - <<'EOF'
import json, sys
losses = {}
for name in ("agent0.log", "agent1.log"):
    for line in open(name):
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "epoch_loss" in rec:
                losses[int(rec["epoch"])] = rec["epoch_loss"]
if set(losses) != {0, 1, 2}:
    sys.exit(f"epochs trained: {sorted(losses)} (wanted 0,1,2)")
print(f"[chaos_smoke] epochs 0-2 complete; final epoch loss {losses[2]:.6f}")
EOF

echo "[chaos_smoke] PASS"
