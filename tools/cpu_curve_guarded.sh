#!/usr/bin/env bash
# Run the real-data rung's CPU fallback curve (VERDICT r04 item 5) WITHOUT
# polluting any concurrently-recovered chip window: the host has ONE core
# (BASELINE.md round-5 operational lesson), so this wrapper SIGSTOPs the
# training process whenever a chip-day payload is running and SIGCONTs it
# when the chip is idle again. Usage:
#
#   bash tools/cpu_curve_guarded.sh [real_data.py args...] >cpu_curve.log 2>&1 &
source "$(dirname "$0")/_chip_common.sh"

python examples/real_data.py "$@" &
PID=$!
echo "[guard] curve pid=$PID" >&2
# CONT before TERM: a plain TERM to a SIGSTOPped process stays pending
# forever, orphaning the curve in state T. Trap signals too, not just EXIT
# (bash delivers the trap only after the current sleep finishes, <=20s),
# and exit explicitly from the signal path or bash resumes the loop.
cleanup() { kill -CONT "$PID" 2>/dev/null; kill "$PID" 2>/dev/null; }
trap cleanup EXIT
trap 'cleanup; trap - EXIT; exit 143' INT TERM

paused=0
while kill -0 "$PID" 2>/dev/null; do
  # Anything dialing the real chip wins the core: chip-day queues and the
  # bare driver bench. "bash <path>" / "python <path>" with no space in the
  # path survives absolute/relative launch variants, while launcher shells
  # that merely MENTION these scripts in an env assignment (probe_and_fire's
  # PROBE_PAYLOAD=... argv) don't read as a live payload forever.
  if pgrep -f "bash [^ ]*tools/chip_day|python [^ ]*bench\.py|python [^ ]*tools/decode_bench" >/dev/null; then
    if [ "$paused" = 0 ]; then
      echo "[guard $(date +%H:%M:%S)] chip payload active - pausing curve" >&2
      kill -STOP "$PID"; paused=1
    fi
  elif [ "$paused" = 1 ]; then
    echo "[guard $(date +%H:%M:%S)] chip idle - resuming curve" >&2
    kill -CONT "$PID"; paused=0
  fi
  sleep 20
done
wait "$PID"
rc=$?
trap - EXIT
echo "[guard] curve finished rc=$rc" >&2
exit $rc
