#!/usr/bin/env bash
# Run the real-data rung's CPU fallback curve (VERDICT r04 item 5) without
# polluting any concurrently-recovered chip window — the real-data instance
# of tools/host_guarded.sh (see there for the pause/resume mechanics).
#
#   bash tools/cpu_curve_guarded.sh [real_data.py args...] >cpu_curve.log 2>&1 &
exec bash "$(dirname "$0")/host_guarded.sh" python examples/real_data.py "$@"
