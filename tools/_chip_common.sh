# Shared preamble for the serialized chip-day queues (sourced, not run):
# repo-root cwd, package on PYTHONPATH, and a run() helper that logs each
# step's rc AND counts failures — the sourcing script should `exit
# "$FAILED_STEPS"` so probe_and_fire.sh's "finished rc=" line distinguishes
# a window that captured everything from one that captured nothing (the
# round-5 ModuleNotFoundError window reported rc=0 for exactly this reason).
set -u
cd "$(dirname "${BASH_SOURCE[1]}")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

FAILED_STEPS=0

run() {
  echo "=== [$(date +%H:%M:%S)] $*" >&2
  "$@"
  local rc=$?  # capture BEFORE $(date) below resets $?
  if [ "$rc" -ne 0 ]; then
    FAILED_STEPS=$((FAILED_STEPS + 1))
  fi
  echo "=== [$(date +%H:%M:%S)] rc=$rc : $*" >&2
}
