"""Decode throughput A/B: bf16 baseline vs weight-only int8 vs int8 KV
cache (ops/quant.py, Attention(quantized_cache=True)).

Autoregressive decode re-reads every matmul weight once per generated token,
so at small batch it is HBM-bandwidth-bound on parameter bytes and int8
weights approach 2x tokens/s; at long context the KV-cache reads take over,
which the separate long-decode cache pair measures
(``tokens_per_sec_{bf16,int8}_cache`` / ``kv_cache_speedup`` at
``--cache_new_tokens``). This measures it honestly on the real chip:
one compiled fori_loop per variant (generation.generate), value-fetch sync,
per-token greedy agreement reported (exact parity on a trained model is
pinned by tests/test_quant.py; random-init weights have near-tie argmax
margins either rounding can flip, and over long horizons one flip cascades —
every later token differs — so agreement fractions decay with decode length
by construction, not by numeric degradation).

    python tools/decode_bench.py [--d_model 1024] [--n_layers 12] \
        [--batch 8] [--new_tokens 128]
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--d_model", type=int, default=1024)
    p.add_argument("--n_layers", type=int, default=12)
    p.add_argument("--n_heads", type=int, default=8)
    p.add_argument("--n_kv_heads", type=int, default=0,
                   help="grouped-query attention (0 = MHA): the long-decode "
                   "cache pair then measures the GQA cache cut on top of "
                   "int8 — run once with 0 and once with e.g. 2 to A/B")
    p.add_argument("--d_ff", type=int, default=4096)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt_len", type=int, default=16)
    p.add_argument("--new_tokens", type=int, default=128)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--cache_new_tokens", type=int, default=2048,
                   help="decode length for the KV-cache A/B pair")
    p.add_argument("--speculative", action="store_true",
                   help="exclusive mode: speculative-decode speedup A/B - "
                   "distill a small draft against this target on the fly, "
                   "then time plain greedy decode vs speculative_generate "
                   "at --spec_batch (default 1, the latency case)")
    p.add_argument("--gamma", type=int, default=4)
    p.add_argument("--distill_steps", type=int, default=200)
    p.add_argument("--spec_batch", type=int, default=1)
    p.add_argument("--fake_devices", type=int, default=0,
                   help="debug: run on N virtual CPU devices")
    args = p.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import (
            use_fake_cpu_devices,
        )

        use_fake_cpu_devices(args.fake_devices)

    from distributed_pytorch_tpu.generation import generate
    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.ops.quant import (
        quantize_pytree,
        quantized_bytes,
    )

    model = TransformerLM(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        d_ff=args.d_ff,
        dtype=jnp.bfloat16,
    )
    if args.speculative:
        return spec_bench(args, model)

    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, args.vocab, (args.batch, args.prompt_len)), jnp.int32
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    # flax stores params float32 (param_dtype default) regardless of the
    # compute dtype; cast the baseline's weights to bf16 so the A/B compares
    # 2-byte vs 1-byte HBM reads, not 4 vs 1.
    bf16_params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params)
    )
    qparams = quantize_pytree(params)  # once, off the clock
    q_bytes, orig_f32 = quantized_bytes(qparams)

    def run(p, quantize):
        # Warm (compile) + timed repeats; each call is one compiled loop.
        out = generate(model, p, prompt, args.new_tokens, quantize=quantize)
        np.asarray(out)
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            out = generate(
                model, p, prompt, args.new_tokens, quantize=quantize
            )
            np.asarray(out)
            times.append(time.perf_counter() - t0)
        toks = args.batch * args.new_tokens
        return out, toks / min(times)

    out_bf16, tps_bf16 = run(bf16_params, False)
    out_int8, tps_int8 = run(qparams, True)
    # Agreement fraction, not an exact-match assert: these are RANDOM-init
    # weights, whose argmax margins are near-ties that either rounding (bf16
    # or int8) can flip — exact greedy parity on a TRAINED model is pinned
    # by tests/test_quant.py instead.
    a, b = np.asarray(out_bf16), np.asarray(out_int8)
    # Generated tokens only: the prompt prefix is identical by construction
    # and would inflate the fraction.
    a, b = a[:, args.prompt_len :], b[:, args.prompt_len :]
    agreement = float(np.mean(a == b))

    # KV-cache A/B is its own pair at LONG context (the cache is a rounding
    # error next to the weights at the default 144-token length; the cache
    # claim only means anything when cache bytes rival weight bytes).
    def run_cache(quantized_cache):
        kw = dict(quantize=False, quantized_cache=quantized_cache)
        out = generate(
            model, bf16_params, prompt, args.cache_new_tokens, **kw
        )
        np.asarray(out)
        times = []
        for _ in range(args.repeats):  # same methodology as the weight A/B
            t0 = time.perf_counter()
            out = generate(
                model, bf16_params, prompt, args.cache_new_tokens, **kw
            )
            np.asarray(out)
            times.append(time.perf_counter() - t0)
        toks = args.batch * args.cache_new_tokens
        return out, toks / min(times)

    out_c16, tps_c16 = run_cache(False)
    out_c8, tps_c8 = run_cache(True)
    c16 = np.asarray(out_c16)[:, args.prompt_len :]
    c8 = np.asarray(out_c8)[:, args.prompt_len :]
    cache_agreement = float(np.mean(c16 == c8))
    print(
        json.dumps(
            {
                "config": (
                    f"d_model={args.d_model} L={args.n_layers} "
                    f"heads={args.n_heads} kv_heads={args.n_kv_heads or args.n_heads} "
                    f"d_ff={args.d_ff} "
                    f"vocab={args.vocab} B={args.batch} "
                    f"new_tokens={args.new_tokens}"
                ),
                "params_M": round(n_params / 1e6, 1),
                "quantized_weight_MB": round(q_bytes / 1e6, 1),
                "bf16_weight_MB": round(orig_f32 / 2 / 1e6, 1),
                "tokens_per_sec_bf16": round(tps_bf16, 1),
                "tokens_per_sec_int8": round(tps_int8, 1),
                "speedup": round(tps_int8 / tps_bf16, 3),
                "greedy_token_agreement": round(agreement, 4),
                "cache_ab_new_tokens": args.cache_new_tokens,
                "tokens_per_sec_bf16_cache": round(tps_c16, 1),
                "tokens_per_sec_int8_cache": round(tps_c8, 1),
                "kv_cache_speedup": round(tps_c8 / tps_c16, 3),
                "cache_token_agreement": round(cache_agreement, 4),
            }
        )
    )


def spec_bench(args, model):
    """Speculative-decode speedup A/B (``--speculative``): distill a
    quarter-width draft against THIS target on the fly (forward KL on
    random prompts, teacher logits computed per step — no data to stage),
    then time plain greedy decode vs ``speculative_generate`` at
    ``--spec_batch`` (default 1: batched rounds advance by the batch-min
    acceptance, so B=1 is the latency case speculation exists for). The
    acceptance statistic is REPORTED, not assumed — on a random-init
    target it is whatever the distilled draft earns, and the speedup
    column is honest either way."""
    import optax

    from distributed_pytorch_tpu.generation import generate
    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.speculative import speculative_generate
    from distributed_pytorch_tpu.training.distill import make_distill_step

    d_model_d = max(args.d_model // 4, 64)
    n_layers_d = max(args.n_layers // 4, 1)
    draft = TransformerLM(
        vocab_size=args.vocab,
        d_model=d_model_d,
        n_layers=n_layers_d,
        n_heads=max(args.n_heads // 2, 1),
        d_ff=max(args.d_ff // 4, 128),
        dtype=jnp.bfloat16,
    )

    def bf16(tree):
        # Same cast as the weight A/B above: flax stores params float32
        # regardless of compute dtype, and these timings must read 2-byte
        # weights to be comparable with the rest of this file's rows.
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )

    params = bf16(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )
    draft_params = bf16(
        draft.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )

    opt = optax.adam(1e-3)
    opt_state = opt.init(draft_params)
    distill_step = make_distill_step(model, draft, opt)

    rng = np.random.default_rng(0)
    kl = float("nan")
    for i in range(args.distill_steps):
        batch = jnp.asarray(
            rng.integers(0, args.vocab, (8, 32)), jnp.int32
        )
        draft_params, opt_state, loss = distill_step(
            draft_params, opt_state, batch, params
        )
        kl = float(loss)

    prompt = jnp.asarray(
        rng.integers(0, args.vocab, (args.spec_batch, args.prompt_len)),
        jnp.int32,
    )

    def timed(fn):
        out = fn()
        np.asarray(out)
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            out = fn()
            np.asarray(out)
            times.append(time.perf_counter() - t0)
        return out, args.spec_batch * args.new_tokens / min(times)

    plain_out, tps_plain = timed(
        lambda: generate(model, params, prompt, args.new_tokens)
    )
    stats = {}

    def spec():
        nonlocal stats
        toks, stats = speculative_generate(
            model, params, draft, draft_params, prompt, args.new_tokens,
            gamma=args.gamma, return_stats=True,
        )
        return toks

    spec_out, tps_spec = timed(spec)
    a = np.asarray(plain_out)[:, args.prompt_len :]
    b = np.asarray(spec_out)[:, args.prompt_len :]
    rounds = int(stats["rounds"])
    print(
        json.dumps(
            {
                "mode": "speculative",
                "config": (
                    f"target d_model={args.d_model} L={args.n_layers} | "
                    f"draft d_model={d_model_d} "
                    f"L={n_layers_d} | gamma={args.gamma} "
                    f"B={args.spec_batch} new_tokens={args.new_tokens} "
                    f"distill_steps={args.distill_steps}"
                ),
                # kl != kl: distill_steps=0 (the undistilled baseline) left
                # it NaN, which json.dumps would emit as invalid bare NaN.
                "final_distill_kl": round(kl, 4) if kl == kl else None,
                "mean_accepted_chunk": round(
                    int(stats["positions_advanced"]) / max(rounds, 1), 3
                ),
                "tokens_per_sec_plain": round(tps_plain, 1),
                "tokens_per_sec_speculative": round(tps_spec, 1),
                "speedup": round(tps_spec / tps_plain, 3),
                # bf16 random-init ties can flip (same caveat as the int8
                # A/B above); exactness is pinned at f32 by the test suite.
                "greedy_token_agreement": round(float(np.mean(a == b)), 4),
            }
        )
    )


if __name__ == "__main__":
    main()
