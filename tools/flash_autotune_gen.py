"""Generate flash-attention block tables for a new device kind.

The shipped ``DEFAULT_TABLE`` in ``ops/flash_autotune.py`` only covers chips
someone has actually measured (round 4: TPU v5e). This tool is the measuring
workflow for every other chip — run it ONCE on one host of the target kind:

    python tools/flash_autotune_gen.py --export blocks_v5p.json

It sweeps the standard (seq_len, head_dim) grid with the real measured
autotune (one compile + timed fwd+bwd per legal candidate) and emits:

1. a ready-to-paste ``DEFAULT_TABLE`` entry (stdout) — commit it so the
   whole fleet inherits the measurement;
2. a ``FLASH_BLOCKS_TABLE`` JSON (``--export``) — ship it to every pod host
   NOW, without waiting for a release::

       export FLASH_BLOCKS_TABLE=/shared/blocks_v5p.json

   (the explicit table outranks per-host disk caches, so all hosts pick
   identical blocks and trace identical programs — the live sweep stays
   disabled under multi-process SPMD on purpose).

Until a kind is measured, lookups fall back to the VMEM-reasoned
``analytic_default`` — legal everywhere, but measured tables have beaten
analytic guesses by 6-10% on v5e, which is why this tool exists.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from distributed_pytorch_tpu.ops import flash_autotune as fa  # noqa: E402


def main() -> None:
    parser = argparse.ArgumentParser(
        description="measure flash block tables for this host's device kind"
    )
    parser.add_argument("--seq_lens", default="2048,8192,16384")
    parser.add_argument("--head_dims", default="64,128")
    parser.add_argument("--bh", default=16, type=int, help="batch*heads")
    parser.add_argument(
        "--export", default="", help="also write a FLASH_BLOCKS_TABLE JSON"
    )
    args = parser.parse_args()

    kind = fa._device_kind()
    if kind == "unknown":
        raise SystemExit(
            "no JAX backend reachable — run on the target device"
        )
    print(f"device kind: {kind}", flush=True)

    seq_lens = [int(x) for x in args.seq_lens.split(",")]
    head_dims = [int(x) for x in args.head_dims.split(",")]

    entries = {}  # (t, d) -> (bq, bk)
    shipped = {}  # full key -> blocks, for --export
    for t in seq_lens:
        for d in head_dims:
            analytic = fa.analytic_default(t, d)
            blocks = fa.autotune(t, d, bh=args.bh, verbose=True)
            marker = "" if blocks != analytic else "  (= analytic default)"
            print(f"T={t:6d} d={d:4d} -> {blocks}{marker}", flush=True)
            entries[(t, d)] = blocks
            shipped[fa._key(kind, t, d, "bfloat16", True)] = blocks

    print("\n# Paste into ops/flash_autotune.py DEFAULT_TABLE:")
    print(f'    "{kind.lower()}": {{')
    for (t, d), (bq, bk) in sorted(entries.items()):
        print(f"        ({t}, {d}): ({bq}, {bk}),")
    print("    },")

    if args.export:
        with open(args.export, "w") as f:
            json.dump(
                {json.dumps(list(k)): list(v) for k, v in shipped.items()}, f
            )
        print(
            f"\nexported {len(shipped)} entries to {args.export} — deploy "
            "with FLASH_BLOCKS_TABLE=<path> on every pod host"
        )


if __name__ == "__main__":
    main()
