"""Generate flash-attention block tables for a new device kind.

Thin, documented alias of ``python -m distributed_pytorch_tpu.ops
.flash_autotune`` — ONE implementation lives there; this file only fixes the
import path for direct invocation. Run ONCE on one host of the target kind:

    python tools/flash_autotune_gen.py --export blocks_v5p.json [--force]

It sweeps the standard (seq_len, head_dim) grid with the real measured
autotune (one compile + timed fwd+bwd per legal candidate) and emits:

1. a ready-to-paste ``DEFAULT_TABLE`` entry (stdout) — commit it so the
   whole fleet inherits the measurement;
2. a ``FLASH_BLOCKS_TABLE`` JSON (``--export``) — ship it to every pod host
   NOW, without waiting for a release::

       export FLASH_BLOCKS_TABLE=/shared/blocks_v5p.json

   (the explicit table outranks per-host disk caches, so all hosts pick
   identical blocks and trace identical programs — the live sweep stays
   disabled under multi-process SPMD on purpose).

Only measured winners are emitted: ``--force`` re-sweeps past cached
entries, and shapes where no candidate compiles are excluded loudly.
Until a kind is measured, lookups fall back to the VMEM-reasoned
``analytic_default`` — legal everywhere, but measured tables have beaten
analytic guesses by 6-10% on v5e, which is why this tool exists.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from distributed_pytorch_tpu.ops.flash_autotune import main  # noqa: E402

if __name__ == "__main__":
    main()
