"""MFU investigation probe: capture a real-chip trace of a bench workload and
break the step down per-op (the analysis behind BASELINE.md's roofline notes).

Usage::

    python tools/mfu_probe.py resnet --batch 128 --logdir traces/resnet50_b128
    python tools/mfu_probe.py lm --seq 8192 --logdir traces/lm_t8192

Captures ``jax.profiler`` traces of N steady-state steps (matching the
reference's profiled-workload evidence, ``multigpu_profile.py:80-91``), then
parses the XPlane with ``jax.profiler.ProfileData`` and prints:

* the top ops by total device time (name, category, time, share);
* totals per category (convolution / fusion / copy / ...);
* XLA cost-analysis FLOPs + bytes accessed -> arithmetic intensity and the
  bandwidth-bound MFU ceiling for the chip.
"""

import argparse
import collections
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(step, state, batches, logdir, n_steps=5, warmup=5):
    import itertools
    import jax

    it = itertools.cycle(batches)
    loss = None
    for _ in range(warmup):
        state, loss = step(state, next(it))
    float(loss)  # sync (tunnel-safe)
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    for _ in range(n_steps):
        state, loss = step(state, next(it))
    float(loss)
    jax.profiler.stop_trace()
    return logdir


def analyze(logdir, n_steps, flops_per_step, peak_flops, peak_bw, bytes_per_step=None):
    """Aggregate the serialized per-op timeline (device plane, 'XLA Ops' line
    — non-overlapping, so durations sum to real busy time; the 'Async XLA Ops'
    line holds overlapping DMA spans and must NOT be summed)."""
    from jax.profiler import ProfileData

    xplanes = sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    )
    if not xplanes:
        raise SystemExit(f"no .xplane.pb under {logdir}")
    data = ProfileData.from_serialized_xspace(open(xplanes[-1], "rb").read())

    op_time = collections.Counter()
    for plane in data.planes:
        if not plane.name.startswith("/device:"):
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for event in line.events:
                op_time[event.name] += event.duration_ns

    total_ns = sum(op_time.values())
    per_step_ms = total_ns / n_steps / 1e6
    print(f"\ntrace: {xplanes[-1]}")
    print(f"device busy time: {per_step_ms:.3f} ms/step over {n_steps} steps")

    cat_time = collections.Counter()
    for name, ns in op_time.items():
        cat_time[op_category(name)] += ns

    print("\n-- by category (top 12) --")
    for cat, ns in cat_time.most_common(12):
        print(f"{ns / n_steps / 1e6:9.3f} ms/step  {100 * ns / total_ns:5.1f}%  {cat}")

    print("\n-- top 15 ops --")
    for name, ns in op_time.most_common(15):
        print(
            f"{ns / n_steps / 1e6:9.3f} ms/step  {100 * ns / total_ns:5.1f}%  "
            f"[{op_category(name):>12}]  {short_name(name)}"
        )

    if flops_per_step:
        achieved = flops_per_step / (per_step_ms / 1e3)
        print(
            f"\nmodel FLOPs/step {flops_per_step / 1e9:.2f} G -> "
            f"{achieved / 1e12:.1f} TFLOP/s busy-time MFU {achieved / peak_flops:.1%}"
        )
        if bytes_per_step:
            # Bandwidth roofline from XLA's logical bytes (understates reuse
            # the caches capture; the xprof op_profile's measured HBM traffic
            # is the sharper number when available). Same math as the serving
            # observatory's per-program attribution — one source of truth.
            from distributed_pytorch_tpu.obs.roofline import roofline_point

            point = roofline_point(
                flops_per_step, bytes_per_step, peak_flops, peak_bw
            )
            measured_s = per_step_ms / 1e3
            frac = (
                min(1.0, point["floor_s"] / measured_s)
                if point["floor_s"] > 0 and measured_s > 0
                else 0.0
            )
            print(
                f"roofline: intensity "
                f"{point['intensity_flops_per_byte']:.1f} FLOP/B vs ridge "
                f"{point['ridge_flops_per_byte']:.0f} FLOP/B -> "
                f"{point['bound']}-bound, floor "
                f"{point['floor_s'] * 1e3:.3f} ms/step "
                f"(compute {point['compute_floor_s'] * 1e3:.3f} / memory "
                f"{point['memory_floor_s'] * 1e3:.3f}), achieved "
                f"{frac:.1%} of the roofline at peak HBM "
                f"({peak_bw / 1e9:.0f} GB/s)"
            )
    return op_time, cat_time, per_step_ms


def op_category(name: str) -> str:
    """Family from the HLO instruction text (`%n = type opcode(...)`).

    Event names in the trace are truncated, so the opcode after a long tuple
    result type may be cut off — fall back to the op-name family (the name
    before `` = `` with the trailing instance number stripped), which the
    compiler derives from the fused ops and is never truncated."""
    import re

    base = re.sub(r"\.\d+$", "", name.split(" = ")[0].lstrip("%"))
    m = re.search(r"= (?:\([^)]*\)|\S+) ([\w-]+)\(", name)
    opcode = m.group(1) if m else None
    if " convolution(" in name:
        return "convolution"
    if opcode == "dot":
        return "matmul"
    if (opcode and "copy" in opcode) or base.startswith(("copy", "slice-start")):
        return "copy/layout"
    if opcode and ("all-reduce" in opcode or "collective" in opcode or "permute" in opcode):
        return "collective"
    if opcode == "fusion" or base.endswith("fusion"):
        return f"fusion:{base}" if base != "fusion" else "fusion(unnamed)"
    return opcode or base


def short_name(name: str) -> str:
    return name.split(" = ")[0].lstrip("%")[:80]


def cost_summary(compiled, label):
    try:
        a = compiled.cost_analysis()
        if isinstance(a, list):
            a = a[0]
        flops = float(a.get("flops", 0.0))
        byac = float(a.get("bytes accessed", 0.0))
        print(
            f"{label}: cost_analysis flops={flops / 1e9:.2f}G "
            f"bytes={byac / 1e9:.3f}GB intensity={flops / max(byac, 1):.1f} flop/B"
        )
        return flops, byac
    except Exception as e:
        print(f"{label}: no cost analysis ({e})")
        return None, None


def probe_resnet(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bench import compile_with_flops
    from distributed_pytorch_tpu.models import ResNet50
    from distributed_pytorch_tpu.obs.goodput import (
        peak_flops_per_chip,
        resnet50_train_flops,
    )
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    batch = args.batch
    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, 224, 224, 3)).astype(np.float32)
    y = rng.integers(0, 1000, size=(batch,)).astype(np.int32)
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    optimizer = optax.sgd(1e-3, momentum=0.9)
    state = create_train_state(model, optimizer, x[:1])
    step_fn = make_train_step(model.apply, optimizer, softmax_cross_entropy_loss)
    device_batch = jax.device_put((x, y))
    compiled, flops = compile_with_flops(step_fn, state, device_batch)
    flops = flops or resnet50_train_flops(batch)
    _, nbytes = cost_summary(compiled, f"resnet50_b{batch}")

    logdir = args.logdir or f"traces/resnet50_b{batch}"
    capture(compiled, state, [device_batch], logdir, n_steps=args.steps)
    peak = peak_flops_per_chip(jax.devices()[0])
    analyze(logdir, args.steps, flops, peak, args.peak_bw, bytes_per_step=nbytes)


def probe_lm(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bench import compile_with_flops
    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.obs.goodput import (
        count_params,
        peak_flops_per_chip,
        transformer_train_flops,
    )
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    vocab, d_model, n_layers, n_heads, d_ff = 32768, 512, 6, 8, 2048
    seq = args.seq
    batch = max(1, 16384 // seq)
    rng = np.random.default_rng(0)
    x = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
    y = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers, n_heads=n_heads,
        d_ff=d_ff, dtype=jnp.bfloat16, remat=args.remat,
        fused_head_chunk=8192 if args.fused else 0,
    )
    optimizer = optax.adam(1e-4)
    state = create_train_state(model, optimizer, x[:1])
    if args.fused:
        step_fn = make_train_step(
            model.apply, optimizer, lambda out, _: out, apply_takes_targets=True
        )
    else:
        step_fn = make_train_step(model.apply, optimizer, softmax_cross_entropy_loss)
    device_batch = jax.device_put((x, y))
    compiled, flops = compile_with_flops(step_fn, state, device_batch)
    # When XLA won't report a cost analysis, fall back to the same analytic
    # PaLM-style formula bench.py and the serving engine use (obs.goodput is
    # the single source of truth for the FLOPs model).
    flops = flops or transformer_train_flops(
        n_params=count_params(state.params),
        embed_params=vocab * d_model,
        n_layers=n_layers,
        n_heads=n_heads,
        head_dim=d_model // n_heads,
        seq_len=seq,
        batch=batch,
    )
    _, nbytes = cost_summary(compiled, f"lm_t{seq}")

    logdir = args.logdir or f"traces/lm_t{seq}"
    capture(compiled, state, [device_batch], logdir, n_steps=args.steps)
    peak = peak_flops_per_chip(jax.devices()[0])
    analyze(logdir, args.steps, flops, peak, args.peak_bw, bytes_per_step=nbytes)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("workload", choices=["resnet", "lm"])
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--seq", type=int, default=8192)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--fused", action="store_true")
    p.add_argument("--remat", action="store_true", default=None)
    p.add_argument("--no-remat", dest="remat", action="store_false")
    p.add_argument("--logdir", default=None)
    p.add_argument(
        "--peak_bw", type=float, default=None,
        help="HBM bandwidth B/s for the roofline (default: by device kind "
        "from obs.roofline.HBM_BYTES_PER_SEC; v5e-class 819 GB/s fallback)",
    )
    args = p.parse_args()
    if args.peak_bw is None:
        import jax

        from distributed_pytorch_tpu.obs.roofline import hbm_bandwidth_per_chip

        args.peak_bw = hbm_bandwidth_per_chip(jax.devices()[0])
    if args.workload == "lm" and args.remat is None:
        args.remat = False  # bench default: flash keeps activations linear in T
    if args.workload == "resnet":
        probe_resnet(args)
    else:
        probe_lm(args)


if __name__ == "__main__":
    main()
