#!/usr/bin/env python
"""Per-request latency waterfalls from fleet trace files or live engines.

Feed it any mix of Perfetto trace JSON files (saved by ``Tracer.save`` or
the smoke scenarios) and ``--url`` base URLs whose ``/trace`` endpoint is
scraped live; the sources are clock-aligned and merged
(``obs.disttrace.merge_traces``), then either listed (all trace_ids seen)
or decomposed into one request's exact-partition waterfall.

Usage:
    python tools/trace_waterfall.py traces/fleet_trace.json
    python tools/trace_waterfall.py door.json router.json r0.json --id d000003
    python tools/trace_waterfall.py --url http://127.0.0.1:8321 --id r00000a
    python tools/trace_waterfall.py merged.json --id d000000 --json

With ``--id``, prints the waterfall table (or the raw dict with
``--json``) and exits 0; without, lists every trace_id. Exits 2 when the
id is not in the merged trace. ``load_sources`` / ``run`` are pure of
argv parsing, so tests drive them directly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from distributed_pytorch_tpu.obs.disttrace import (
    format_waterfall,
    merge_traces,
    request_waterfall,
    trace_ids,
)
from distributed_pytorch_tpu.obs.server import scrape


def load_sources(paths: List[str], urls: List[str]) -> List[dict]:
    """Trace documents from files and live ``/trace`` endpoints, in the
    order given (files first — merge labels follow source order)."""
    docs: List[dict] = []
    for path in paths:
        with open(path) as f:
            docs.append(json.load(f))
    for url in urls:
        doc = scrape(url, "/trace")
        if not isinstance(doc, dict):
            raise ValueError(f"{url}/trace did not return a trace document")
        docs.append(doc)
    return docs


def run(
    paths: List[str],
    urls: List[str],
    trace_id: Optional[str],
    as_json: bool = False,
    out=sys.stdout,
) -> int:
    if not paths and not urls:
        print("no trace sources given", file=sys.stderr)
        return 2
    merged = merge_traces(*load_sources(paths, urls))
    if trace_id is None:
        ids = trace_ids(merged)
        print(
            f"{len(ids)} trace id(s) across "
            f"{len(merged['metadata']['sources'])} source(s): "
            f"{merged['metadata']['sources']}",
            file=out,
        )
        for tid in ids:
            print(f"  {tid}", file=out)
        return 0
    try:
        waterfall = request_waterfall(merged, trace_id)
    except KeyError:
        print(f"trace_id {trace_id!r} not found in merged trace",
              file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(waterfall, default=list), file=out)
    else:
        print(format_waterfall(waterfall), file=out)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "traces", nargs="*", help="Perfetto trace JSON files to merge"
    )
    parser.add_argument(
        "--url",
        action="append",
        default=[],
        help="engine/door base URL whose /trace endpoint to scrape "
        "(repeatable)",
    )
    parser.add_argument(
        "--id", dest="trace_id", help="trace_id to decompose; omit to list"
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit the waterfall dict as JSON instead of the table",
    )
    args = parser.parse_args(argv)
    return run(args.traces, args.url, args.trace_id, as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
