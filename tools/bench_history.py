#!/usr/bin/env python
"""Perf-trajectory record for ``bench.py --serving``.

Every serving bench run collapses to one JSONL row — headline tokens/sec
and TPOT p50 per engine config, plus the obs-parity numbers — appended to
``BENCH_HISTORY.jsonl``. CI replays the gate on every PR: extract a fresh
candidate row from the just-produced ``BENCH_SERVING.json`` and diff it
against the LAST COMMITTED history row with a +/-10 percent tolerance —
tokens/sec may not drop, TPOT p50 may not rise, beyond the gate. The
history file is the repo's perf memory; the gate is what turns "the bench
exists" into "regressions fail CI".

Usage:
    python tools/bench_history.py append [--bench BENCH_SERVING.json]
                                         [--history BENCH_HISTORY.jsonl]
    python tools/bench_history.py check  [--bench BENCH_SERVING.json]
                                         [--history BENCH_HISTORY.jsonl]
                                         [--tolerance 0.10]

``check`` exits 1 on any gated regression, 0 otherwise (including when
the history is empty — the first row has nothing to regress against).
Stdlib only; importable (``extract_row`` / ``compare_rows``) so the gate
logic is unit-tested without running the bench.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional

GATED_HIGHER_IS_BETTER = ("tokens_per_sec",)
GATED_LOWER_IS_BETTER = ("tpot_s_p50",)


def _config_key(row: dict) -> str:
    """Stable label for one bench row's engine config, e.g.
    ``prefix=on,spec=off`` (plus ``mesh=2x4`` when the row is meshed)."""
    parts = [
        f"prefix={'on' if row.get('prefix_caching') else 'off'}",
        f"spec={'on' if row.get('speculative') else 'off'}",
    ]
    if row.get("mesh"):
        parts.append(f"mesh={row['mesh']}")
    return ",".join(parts)


def _git_rev() -> Optional[str]:
    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True,
                text=True,
                timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
            or None
        )
    except Exception:
        return None


def extract_row(bench: dict) -> dict:
    """Collapse one BENCH_SERVING.json document into one history row."""
    configs: Dict[str, dict] = {}
    for row in bench.get("rows", []):
        stats = row.get("stats", {})
        configs[_config_key(row)] = {
            "tokens_per_sec": stats.get("tokens_per_sec"),
            "tpot_s_p50": stats.get("tpot_s_p50"),
            "ttft_s_p50": stats.get("ttft_s_p50"),
            "requests_completed": stats.get("requests_completed"),
        }
    out = {
        "recorded_at": datetime.datetime.now(
            datetime.timezone.utc
        ).isoformat(timespec="seconds"),
        "git_rev": _git_rev(),
        "platform": bench.get("platform"),
        "device_kind": bench.get("device_kind"),
        "configs": configs,
    }
    obs = bench.get("obs")
    if obs:
        out["obs"] = {
            key: obs.get(key)
            for key in (
                "tokens_per_sec_obs_on",
                "tokens_per_sec_obs_off",
                "tpot_p50_obs_overhead",
                "greedy_tokens_identical_with_tracing",
                "greedy_tokens_identical_with_server",
                "recompiles_at_steady_state",
                "scrapes_mid_run",
            )
            if key in obs
        }
    fleet = bench.get("fleet")
    if fleet:
        # Un-gated (a seeded kill mid-run makes aggregate tok/s too noisy
        # for the +/-10% gate), but recorded: the failover-cost trajectory
        # is the point of running the fleet drill in CI at all.
        out["fleet"] = {
            key: fleet.get(key)
            for key in (
                "n_replicas",
                "aggregate_tokens_per_sec",
                "requests_failed_over",
                "detection_latency_s",
                "failover_ttft_s_p50",
                "failover_ttft_spike_x",
                "greedy_tokens_match_single_engine",
                "pages_leaked_on_survivors",
            )
            if key in fleet
        }
    fleet_procs = bench.get("fleet_procs")
    if fleet_procs:
        # Un-gated like the in-process fleet row (same drill-shaped
        # noise, plus subprocess spawn jitter), but recorded: the
        # cross-process isolation tax — aggregate tok/s vs the in-process
        # row, detection latency over a real SIGKILL, and the failover
        # TTFT spike — is the trajectory this row exists to track.
        out["fleet_procs"] = {
            key: fleet_procs.get(key)
            for key in (
                "n_replicas",
                "aggregate_tokens_per_sec",
                "requests_failed_over",
                "detection_latency_s",
                "failover_ttft_s_p50",
                "failover_ttft_spike_x",
                "greedy_tokens_match_single_engine",
                "pages_leaked_on_survivors",
            )
            if key in fleet_procs
        }
    fleet_router = bench.get("fleet_router")
    if fleet_router:
        # Un-gated like the other drill rows (a mid-run router crash
        # makes the wall-clock numbers drill-shaped), but recorded: the
        # control-plane-dark-time trajectory — recovery wall time,
        # resume-TTFT spike, reconciliation counts — is what the row
        # exists to track.
        out["fleet_router"] = {
            key: fleet_router.get(key)
            for key in (
                "transport",
                "n_replicas",
                "recovery_s",
                "re_adopted",
                "re_admitted",
                "lost",
                "finished_tails",
                "resume_ttft_s_p50",
                "resume_ttft_spike_x",
                "aggregate_tokens_per_sec",
                "greedy_tokens_match_single_engine",
                "pages_leaked",
            )
            if key in fleet_router
        }
    frontdoor = bench.get("frontdoor")
    if frontdoor:
        # Un-gated like the fleet section (open-loop streaming wall time
        # is too arrival-jitter-noisy for the +/-10% gate) but recorded:
        # the streaming-overhead and per-tenant SLO-compliance trajectory
        # is what the row is for.
        out["frontdoor"] = {
            key: frontdoor.get(key)
            for key in (
                "tokens_per_sec",
                "polled_tokens_per_sec",
                "streaming_overhead_x",
                "streamed_tokens_bitwise_identical_polled",
                "backpressure_stalls",
                "tenants",
            )
            if key in frontdoor
        }
    perfwatch = bench.get("perfwatch")
    if perfwatch:
        # Un-gated like the fleet/frontdoor sections (a seeded mid-run
        # stall makes the wall-clock numbers drill-shaped, not
        # load-shaped) but recorded: the observatory-overhead and
        # detection-latency trajectory is what the row is for.
        out["perfwatch"] = {
            key: perfwatch.get(key)
            for key in (
                "tokens_bitwise_identical",
                "tokens_bitwise_identical_under_stall",
                "detector_fired",
                "detection_latency_steps",
                "detection_latency_decode_samples",
                "detection_within_budget",
                "attributed_phase",
                "attribution_correct",
                "false_positive_alerts_clean_pass",
                "timeseries_series",
                "timeseries_memory_bytes",
                "tpot_p50_perfwatch_overhead",
                "tokens_per_sec_on",
            )
            if key in perfwatch
        }
    hostkv = bench.get("hostkv")
    if hostkv:
        # Un-gated like the fleet/frontdoor/perfwatch sections (open-loop
        # TTFT percentiles are too arrival-jitter-noisy for the +/-10%
        # gate) but recorded: the parity/hit-rate/byte-cross-check
        # acceptance rows and the tier's TTFT trajectory are what the
        # row is for.
        out["hostkv"] = {
            key: hostkv.get(key)
            for key in (
                "tokens_bitwise_identical",
                "hit_rate_strictly_higher",
                "prefix_hit_rate_off",
                "prefix_hit_rate_on",
                "host_hit_tokens",
                "ttft_s_p50_off",
                "ttft_s_p50_on",
                "ttft_p50_speedup_hostkv",
                "ttft_p50_lower_with_tier",
                "hostkv_spills",
                "hostkv_fetches",
                "spill_bytes_match_ledger",
                "fetch_bytes_match_ledger",
                "device_pages_leaked",
                "tokens_per_sec_on",
            )
            if key in hostkv
        }
    return out


def compare_rows(
    prev: dict, cur: dict, tolerance: float = 0.10
) -> List[str]:
    """Diff two history rows under the gate; returns regression messages
    (empty = pass). Only configs present in BOTH rows are gated — a brand
    new config has no baseline, a retired one no candidate. Comparable
    platforms only: a device_kind change voids the gate (CPU numbers say
    nothing about TPU numbers)."""
    if prev.get("device_kind") != cur.get("device_kind"):
        return []
    failures: List[str] = []
    prev_configs = prev.get("configs", {})
    for key, cur_stats in cur.get("configs", {}).items():
        prev_stats = prev_configs.get(key)
        if prev_stats is None:
            continue
        for metric in GATED_HIGHER_IS_BETTER:
            old, new = prev_stats.get(metric), cur_stats.get(metric)
            if old and new is not None and new < old * (1 - tolerance):
                failures.append(
                    f"[{key}] {metric} fell {old:.4g} -> {new:.4g} "
                    f"(> {tolerance:.0%} drop)"
                )
        for metric in GATED_LOWER_IS_BETTER:
            old, new = prev_stats.get(metric), cur_stats.get(metric)
            if old and new is not None and new > old * (1 + tolerance):
                failures.append(
                    f"[{key}] {metric} rose {old:.4g} -> {new:.4g} "
                    f"(> {tolerance:.0%} rise)"
                )
    return failures


def load_history(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("command", choices=("append", "check"))
    parser.add_argument("--bench", default="BENCH_SERVING.json")
    parser.add_argument("--history", default="BENCH_HISTORY.jsonl")
    parser.add_argument("--tolerance", type=float, default=0.10)
    args = parser.parse_args(argv)

    with open(args.bench) as f:
        bench = json.load(f)
    row = extract_row(bench)

    if args.command == "append":
        with open(args.history, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
        print(f"[bench_history] appended to {args.history}:")
        print(json.dumps(row, indent=2, sort_keys=True))
        return 0

    history = load_history(args.history)
    if not history:
        print(
            f"[bench_history] {args.history} is empty — nothing to gate "
            "against (first row passes by definition)"
        )
        return 0
    prev = history[-1]
    failures = compare_rows(prev, row, tolerance=args.tolerance)
    if failures:
        print(
            f"[bench_history] FAIL: perf regressed beyond "
            f"+/-{args.tolerance:.0%} vs last committed row "
            f"({prev.get('recorded_at')}, rev {prev.get('git_rev')}):"
        )
        for failure in failures:
            print(f"  {failure}")
        return 1
    print(
        f"[bench_history] PASS: within +/-{args.tolerance:.0%} of last "
        f"committed row ({prev.get('recorded_at')}, "
        f"rev {prev.get('git_rev')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
