"""Benchmark harness: throughput + MFU on the reference's workloads and ours.

Default run = the headline workload (reference profiled workload,
``multigpu_profile.py:16-27,104-106``: ResNet-50, synthetic 224x224, batch
32/replica, bf16). Batches are assembled by ``NativeShardedLoader`` (the C++
prefetch pool) and pre-staged to the device, then the timed loop cycles
through the distinct device batches — a real epoch's variety without paying
the axon tunnel's WAN-grade H2D cost per step (on a real TPU VM the host
feeds HBM over local DMA; through the tunnel a per-step device_put measures
the tunnel, not the framework — see the ``h2d_on_clock`` matrix entry, which
keeps that honest number). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

``--matrix`` additionally measures the full workload matrix (toy MLP,
ResNet-50 @ 32/64/128, TransformerLM @ 2k/8k with the fused LM head on/off)
and writes it to ``BENCH_MATRIX.json``; the printed line stays the headline.

MFU = measured model FLOP/s divided by the chip's peak bf16 FLOP/s. Model
FLOPs per step come from XLA's own compiled cost analysis when available
(exact, includes backward), else from analytic formulas. The reference
publishes no numbers (BASELINE.md), so ``vs_baseline`` is the ratio against
the round-1 recorded value in ``BENCH_BASELINE.json`` when present, else 1.0.

Timing note: remote-tunnel backends treat ``block_until_ready`` as a no-op;
every measurement below synchronizes by fetching the loss VALUE.
"""

import argparse
import itertools
import json
import math
import os
import threading
import time

# The analytic FLOPs model (peak table, transformer/ResNet formulas) lives
# in obs/goodput.py — ONE source of truth shared with tools/mfu_probe.py
# and the serving engine's MFU accounting; re-exported here for existing
# importers.
from distributed_pytorch_tpu.obs.goodput import (  # noqa: F401
    DEFAULT_PEAK,
    PEAK_BF16_FLOPS,
    peak_flops_per_chip,
    resnet50_train_flops,
    transformer_train_flops,
)


def compile_with_flops(step_fn, *args):
    """AOT-compile the jitted step ONCE; return ``(callable, flops)`` where
    flops is XLA's own cost analysis of that same executable (includes
    backward + optimizer; None when the backend won't say). Reusing the
    compiled object for the timed loop avoids compiling every workload
    twice (jit's dispatch cache doesn't see AOT compiles)."""
    flops = None
    try:
        compiled = step_fn.lower(*args).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # older jax returns [dict]
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0)) or None
        return compiled, flops
    except Exception:
        return step_fn, None


def timed_steps(step, state, batches, n_steps, *, warmup=4):
    """Run ``warmup`` then ``n_steps`` steps, cycling through ``batches``;
    sync by fetching the final loss value."""
    it = itertools.cycle(batches)
    loss = None
    for _ in range(warmup):
        state, loss = step(state, next(it))
    float(loss)
    start = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, next(it))
    float(loss)
    elapsed = time.perf_counter() - start
    return state, elapsed


def bench_resnet(
    per_chip_batch: int,
    n_steps: int = 20,
    dataset_size: int = 256,
    h2d_on_clock: bool = False,
):
    """ResNet-50 bf16 train. Batches come off ``NativeShardedLoader``;
    ``h2d_on_clock`` additionally pays the host->device transfer per step
    (tunnel-bound on this rig — see module docstring)."""
    import jax
    import numpy as np
    import optax

    from distributed_pytorch_tpu.models import ResNet50
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    from distributed_pytorch_tpu.parallel.sharding import (
        put_global_batch,
        replicated_sharding,
    )
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from distributed_pytorch_tpu.utils.data import ArrayDataset, NativeShardedLoader

    n_chips = jax.device_count()
    batch = per_chip_batch * n_chips

    rng = np.random.default_rng(0)
    data = ArrayDataset(
        rng.standard_normal((dataset_size, 224, 224, 3)).astype(np.float32),
        rng.integers(0, 1000, size=(dataset_size,)).astype(np.int32),
    )
    loader = NativeShardedLoader(
        data, batch, pad_final_batch=True, num_workers=4, prefetch_depth=4
    )

    import jax.numpy as jnp

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    optimizer = optax.sgd(1e-3, momentum=0.9)
    state = create_train_state(model, optimizer, data.inputs[:1])
    mesh = make_mesh() if n_chips > 1 else None
    if mesh is not None:
        state = jax.device_put(state, replicated_sharding(mesh))
        put = lambda b: put_global_batch(mesh, b)  # noqa: E731
    else:
        put = jax.device_put
    step_fn = make_train_step(
        model.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh
    )
    compiled, flops = compile_with_flops(step_fn, state, put(next(iter(loader))))
    if flops is None:
        # ~4.09 GFLOP fwd per 224x224 image (2 * 2.05 GMAC); train ~ 3x fwd.
        flops = resnet50_train_flops(batch)

    if h2d_on_clock:
        step = lambda s, b: compiled(s, put(b))  # noqa: E731
        batches = list(loader)
    else:
        step = compiled
        batches = [put(b) for b in loader]
    _, elapsed = timed_steps(step, state, batches, n_steps)
    tag = "_h2d" if h2d_on_clock else ""
    return {
        "workload": f"resnet50_bf16_b{per_chip_batch}{tag}",
        "steps_per_sec": n_steps / elapsed,
        "images_per_sec": n_steps * batch / elapsed,
        "flops_per_step": flops,
        "n_chips": n_chips,
    }


def bench_vit(per_chip_batch: int = 32, n_steps: int = 10, dataset_size: int = 128):
    """ViT-L/32 bf16 train (the reference's alternative real model,
    ``multigpu_profile.py:24``; ``--model vit`` in examples/multichip_profile.py).
    At 50 tokens the attention runs the dense XLA path, so cost analysis sees
    every FLOP."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_pytorch_tpu.models import ViT_L32
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    from distributed_pytorch_tpu.parallel.sharding import (
        put_global_batch,
        replicated_sharding,
    )
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from distributed_pytorch_tpu.utils.data import ArrayDataset, NativeShardedLoader

    n_chips = jax.device_count()
    batch = per_chip_batch * n_chips
    rng = np.random.default_rng(0)
    data = ArrayDataset(
        rng.standard_normal((dataset_size, 224, 224, 3)).astype(np.float32),
        rng.integers(0, 1000, size=(dataset_size,)).astype(np.int32),
    )
    loader = NativeShardedLoader(
        data, batch, pad_final_batch=True, num_workers=4, prefetch_depth=2
    )
    model = ViT_L32(num_classes=1000, dtype=jnp.bfloat16)
    optimizer = optax.sgd(1e-3, momentum=0.9)
    state = create_train_state(model, optimizer, data.inputs[:1])
    mesh = make_mesh() if n_chips > 1 else None
    if mesh is not None:
        state = jax.device_put(state, replicated_sharding(mesh))
        put = lambda b: put_global_batch(mesh, b)  # noqa: E731
    else:
        put = jax.device_put
    step_fn = make_train_step(
        model.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh
    )
    compiled, flops = compile_with_flops(step_fn, state, put(next(iter(loader))))
    if flops is None:
        n_params = sum(
            int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(state.params)
        )
        tokens_per_image = (224 // 32) ** 2 + 1  # 49 patches + cls = 50
        flops = 6.0 * n_params * batch * tokens_per_image
    batches = [put(b) for b in loader]
    _, elapsed = timed_steps(compiled, state, batches, n_steps, warmup=3)
    return {
        "workload": f"vit_l32_bf16_b{per_chip_batch}",
        "steps_per_sec": n_steps / elapsed,
        "images_per_sec": n_steps * batch / elapsed,
        "flops_per_step": flops,
        "n_chips": n_chips,
    }


def bench_toy_mlp(n_steps: int = 200):
    """The reference toy rung: Linear(20,1), batch 32, SGD (single_gpu.py)."""
    import jax
    import numpy as np
    import optax

    from distributed_pytorch_tpu.models import ToyRegressor
    from distributed_pytorch_tpu.training.losses import mse_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader

    data = MaterializedDataset(2048)
    loader = ShardedLoader(data, 32)
    optimizer = optax.sgd(1e-3)
    state = create_train_state(ToyRegressor(), optimizer, data.inputs[:1])
    step_fn = make_train_step(ToyRegressor().apply, optimizer, mse_loss)
    step = lambda s, b: step_fn(s, jax.device_put(b))  # noqa: E731
    _, elapsed = timed_steps(step, state, list(loader), n_steps, warmup=8)
    return {
        "workload": "toy_mlp_b32",
        "steps_per_sec": n_steps / elapsed,
        "flops_per_step": 6.0 * 20 * 1 * 32,  # negligible by design
        "n_chips": jax.device_count(),
    }


def bench_lm(
    seq_len: int,
    fused: bool,
    n_steps: int = 10,
    d_model: int = 512,
    n_layers: int = 6,
    n_heads: int = 8,
    d_ff: int = 2048,
    window: int = 0,
):
    """TransformerLM bf16 train: vocab 32k, 6 layers, d_model 512. The fused
    LM head (``fused_head_chunk``) is the measured variable: at vocab 32k the
    [N, V] logits tensor is the largest activation by far.

    The non-default dims measure the d_head=128 scale-up rows: BASELINE.md's
    roofline shows d_head=64 caps the MXU's 128-wide contraction at 50%, so
    MFU at the reference-ladder size (d_model 512) understates what the
    framework sustains when the model shape fills the array."""
    import jax
    import numpy as np
    import optax

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from distributed_pytorch_tpu.utils.data import ArrayDataset, NativeShardedLoader

    vocab = 32768
    batch = max(1, 16384 // seq_len)  # ~16k tokens per step
    n_chips = jax.device_count()

    rng = np.random.default_rng(0)
    n_samples = batch * 8
    data = ArrayDataset(
        rng.integers(0, vocab, (n_samples, seq_len)).astype(np.int32),
        rng.integers(0, vocab, (n_samples, seq_len)).astype(np.int32),
    )
    loader = NativeShardedLoader(data, batch, num_workers=2, prefetch_depth=2)

    import jax.numpy as jnp

    # No remat at these sizes: with the flash kernel, activations are linear
    # in T and fit HBM through T=16k+; full-block remat re-runs the attention
    # forward in backward (measured 18% step-time tax at T=8192 on v5e, see
    # BASELINE.md). remat / remat_policy="mlp" remain for beyond-HBM runs.
    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers, n_heads=n_heads,
        d_ff=d_ff, dtype=jnp.bfloat16, remat=False,
        attention_window=window,
        fused_head_chunk=8192 if fused else 0,
    )
    optimizer = optax.adam(1e-4)
    state = create_train_state(model, optimizer, data.inputs[:1])
    if fused:
        step_fn = make_train_step(
            model.apply, optimizer, lambda out, _: out, apply_takes_targets=True
        )
    else:
        step_fn = make_train_step(
            model.apply, optimizer, softmax_cross_entropy_loss
        )
    compiled, _ = compile_with_flops(
        step_fn, state, jax.device_put(next(iter(loader)))
    )
    step = lambda s, b: compiled(s, jax.device_put(b))  # noqa: E731

    # MFU denominator: ANALYTIC model FLOPs, not XLA cost analysis — XLA
    # cannot count inside the Pallas attention custom-call and undercounts
    # the scan-chunked fused head, which made fused/long-T rows read as
    # artificially low MFU (the round-2 "15.2% at T=8192" was this artifact).
    # Same basis for dense and fused rows, so their MFUs compare honestly.
    # PaLM-style: fwd = 2 * P_matmul * tokens + causal attention matmuls;
    # train = 3 * fwd (backward counted as 2x forward, no remat/recompute).
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(state.params)
    )
    embed_params = vocab * d_model  # lookup, not a matmul
    head_dim = d_model // n_heads
    flops = transformer_train_flops(
        n_params=n_params, embed_params=embed_params, n_layers=n_layers,
        n_heads=n_heads, head_dim=head_dim, seq_len=seq_len, batch=batch,
        window=window,
    )
    _, elapsed = timed_steps(step, state, list(loader), n_steps, warmup=3)
    tag = "fused" if fused else "dense"
    default_dims = (d_model, n_layers, n_heads, d_ff) == (512, 6, 8, 2048)
    size = "" if default_dims else f"_{round(n_params / 1e6)}M_dhead{head_dim}"
    win = f"_win{window}" if window else ""
    return {
        "workload": f"transformer_lm{size}_t{seq_len}{win}_{tag}_head",
        "steps_per_sec": n_steps / elapsed,
        "tokens_per_sec": n_steps * batch * seq_len / elapsed,
        "flops_per_step": flops,
        "n_chips": n_chips,
    }


def bench_scaling(n_steps: int = 10, per_chip_batch: int = 8, seq_len: int = 512):
    """DP weak-scaling efficiency: fixed per-chip work, growing device count.

    The BASELINE.json north star (>=90% per-chip efficiency at 1->8->32) needs
    a harness before it needs hardware: this measures steps/s on submeshes of
    1, 2, 4, ..., N devices with the SAME per-chip batch. Ideal weak scaling
    keeps steps/s flat, so ``efficiency = steps_per_sec(n) / steps_per_sec(1)``.
    On the 8-virtual-CPU rig this exercises the real DP code path (sharded
    batch, replicated state, XLA gradient all-reduce); on a real slice the
    identical command reports ICI-backed numbers. Single-device rigs (the one
    tunneled chip) report n=1 only, marked ``awaiting_hardware``.

    Workload: a small TransformerLM — enough matmul work per step that the
    all-reduce is a realistic fraction, small enough to run on CPU devices.
    """
    import jax
    import numpy as np
    import optax

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    from distributed_pytorch_tpu.parallel.sharding import (
        put_global_batch,
        replicated_sharding,
    )
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    import jax.numpy as jnp

    devices = jax.devices()
    counts = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= len(devices)]
    on_cpu = devices[0].platform == "cpu"
    if on_cpu:
        # The virtual-device rig shares one host's cores across all N
        # "devices" and emulates bf16 — keep per-device work tiny so the
        # n=8 leg (8x total host FLOPs under weak scaling) stays fast.
        seq_len = 128
        vocab = 2048
        model = TransformerLM(
            vocab_size=vocab, d_model=128, n_layers=2, n_heads=4, d_ff=512,
            dtype=jnp.float32,
        )
    else:
        vocab = 8192
        model = TransformerLM(
            vocab_size=vocab, d_model=256, n_layers=4, n_heads=8, d_ff=1024,
            dtype=jnp.bfloat16,
        )
    optimizer = optax.adam(1e-4)
    rng = np.random.default_rng(0)

    # On shared-host virtual devices the per-chip "efficiency" measures
    # host-core saturation (~1/N by construction), not the interconnect —
    # name the row key accordingly so the file cannot be misread as a
    # scaling result (VERDICT r04 item 8). Real hardware keeps the real key.
    meaningful = not on_cpu and len(devices) > 1
    eff_key = (
        "per_chip_efficiency" if meaningful
        else "per_chip_ratio_shared_host_cores"
    )
    rows = []
    base_sps = None
    for n in counts:
        mesh = make_mesh({"data": n}, devices=devices[:n])
        batch = per_chip_batch * n
        inputs = rng.integers(0, vocab, (batch, seq_len)).astype(np.int32)
        targets = rng.integers(0, vocab, (batch, seq_len)).astype(np.int32)
        state = create_train_state(model, optimizer, inputs[:1])
        state = jax.device_put(state, replicated_sharding(mesh))
        step_fn = make_train_step(
            model.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh
        )
        gbatch = put_global_batch(mesh, (inputs, targets))
        _, elapsed = timed_steps(step_fn, state, [gbatch], n_steps, warmup=2)
        sps = n_steps / elapsed
        if base_sps is None:
            base_sps = sps
        rows.append(
            {
                "n_devices": n,
                "per_chip_batch": per_chip_batch,
                "steps_per_sec": round(sps, 4),
                "tokens_per_sec": round(sps * batch * seq_len, 1),
                eff_key: round(sps / base_sps, 4),
            }
        )
    return {
        "mode": "weak_scaling_dp",
        "workload": f"transformer_lm_small_t{seq_len}_b{per_chip_batch}_per_chip",
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        # True until this runs on a real multi-chip slice: a single tunneled
        # chip can't scale, and N virtual CPU "devices" share one host's
        # cores, so their weak-scaling "efficiency" measures host-core
        # saturation (expected ~1/N), not the interconnect. The harness is
        # validated here; the number waits for hardware.
        "awaiting_hardware": on_cpu or len(devices) == 1,
        # False => the per-chip ratio is host-core saturation, NOT scaling
        # efficiency; the north-star >=90% must never be read off this file
        # unless this flag is true.
        "efficiency_meaningful": meaningful,
        "efficiency_key": eff_key,
        "rows": rows,
    }


def bench_serving(
    n_requests: int = 24,
    arrival_rate_hz: float = 20.0,
    seed: int = 0,
    shared_prefix_len: int = 24,
    speculative: bool = False,
    gamma: int = 4,
    mesh_shapes: str = "",
):
    """Continuous-batching serving benchmark: Poisson arrivals against the
    ``serving.InferenceEngine``, reporting throughput plus TTFT/TPOT/e2e
    percentiles (the reservoirs in ``ServingMetrics``). The model is small
    on purpose — the measurement is the ENGINE (scheduler overhead, slot
    churn, compile-once decode), not the matmuls.

    Every prompt shares a ``shared_prefix_len``-token system prefix (the
    prefix-heavy fleet shape; 0 disables). The SAME workload — identical
    prompts and arrival times — runs twice, prefix caching off then on, so
    the before/after rows in ``BENCH_SERVING.json`` isolate the cache: hit
    rate, TTFT split by hit/miss, and the cached-vs-cold TTFT p50 ratio.

    ``speculative=True`` instead holds prefix caching on and toggles
    SPECULATIVE decoding off-vs-on over the identical workload, reporting
    acceptance rate and the TPOT p50/p95 delta. Untrained random weights
    admit no correlated small draft (any truncation decorrelates the
    logits to chance, ~1/vocab acceptance), so the proxy drafts with the
    target itself — acceptance exactly 1.0, measuring the ENGINE's
    per-round amortization ceiling at this gamma: host scheduling, staging
    and dispatch are paid once per round instead of once per token. With a
    real (distilled) draft, the reported acceptance rate scales that win.

    ``mesh_shapes`` (comma-separated ``DxM``, e.g. ``"1x1,1x8,2x4"``) adds
    one pass per mesh geometry over the IDENTICAL workload — engine
    sharded via ``make_serving_mesh`` — and appends per-shape tokens/sec +
    TPOT p50/p95 rows under ``mesh_rows``. On the virtual-CPU rig these
    are regression-tracking numbers (N "devices" share one host's cores),
    not speedup claims; the row that matters everywhere is
    ``greedy_tokens_match_unsharded``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.obs import (
        FlightRecorder,
        SLObjective,
        Tracer,
    )
    from distributed_pytorch_tpu.serving import (
        InferenceEngine,
        SamplingParams,
        make_serving_mesh,
    )
    from distributed_pytorch_tpu.serving.admission import ServingMetrics

    on_cpu = jax.devices()[0].platform == "cpu"
    # n_heads 8 (head_dim 8) so every head dim divides a model axis up to
    # 8 — the same model serves the unsharded rows and every mesh row.
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, d_ff=256,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    # One fixed workload for both passes.
    rng = np.random.default_rng(seed)
    shared = (
        rng.integers(0, 256, shared_prefix_len).tolist()
        if shared_prefix_len else []
    )
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        shared + rng.integers(0, 256, int(rng.integers(4, 17))).tolist()
        for _ in range(n_requests)
    ]
    warm_rng = np.random.default_rng(seed + 1)

    def run_pass(prefix_caching: bool, spec: bool = False,
                 trace: bool = False, obs_full: bool = False,
                 serve: bool = False, mesh=None):
        kw = {}
        if spec:
            kw.update(
                draft_model=model, draft_params=params, gamma=gamma
            )
        if serve:
            # The observability WIRE on top of the stack: XLA program
            # ledger + recompile sentinel in-engine, the introspection
            # server scraped from another thread mid-run.
            kw.update(xla_ledger=True)
        tracer = Tracer() if (trace or obs_full) else None
        if obs_full:
            # The full production-observability stack: flight recorder,
            # goodput/MFU accounting, and an SLO monitor with deliberately
            # LOOSE objectives (seconds-scale thresholds a CPU microbench
            # never breaches) — the row measures overhead, not alerts.
            kw.update(
                flight=FlightRecorder(capacity=8192),
                goodput=True,
                slo=[
                    SLObjective(
                        name="ttft_p95", metric="ttft_seconds",
                        quantile=0.95, threshold_s=5.0,
                        fast_window_s=2.0, slow_window_s=10.0,
                    ),
                    SLObjective(
                        name="tpot_p50", metric="tpot_seconds",
                        quantile=0.5, threshold_s=1.0,
                        fast_window_s=2.0, slow_window_s=10.0,
                    ),
                    SLObjective(
                        name="expired_rate",
                        bad_counter="requests_expired_total",
                        total_counter="admission_accepted_total",
                        budget=0.05,
                        fast_window_s=2.0, slow_window_s=10.0,
                    ),
                ],
            )
        eng = InferenceEngine(
            model, params, max_slots=8, max_seq_len=64, page_size=8,
            token_budget=64, max_prefill_chunk=32, max_queue=n_requests,
            prefix_cache=prefix_caching, tracer=tracer, mesh=mesh, **kw,
        )
        # Warm the compile caches off the clock — one request per
        # power-of-two prefill bucket (a prompt of length c+1 prefills
        # exactly one c-chunk) plus the shared decode step — then reset the
        # accounting: TTFT must measure scheduling, not XLA compilation.
        chunk = 1
        n_warm = 0
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            n_warm += 1
            chunk *= 2
        if serve and prefix_caching:
            # copy_page compiles lazily on the first CoW; warm it too so
            # the armed sentinel sees a fully-compiled steady state. Two
            # continuations of one retired history share its partial page
            # — extending both forces the copy.
            base = warm_rng.integers(0, 256, 5).tolist()
            first = eng.submit(base, SamplingParams(max_new_tokens=2))
            eng.run()
            hist = base + [eng.poll(first).generated[0]]
            cont = [
                eng.submit(hist + [t], SamplingParams(max_new_tokens=4))
                for t in (3, 17)
            ]
            eng.run()
            assert all(eng.poll(r).finished for r in cont)
            n_warm += 3
        eng.metrics = ServingMetrics(speculative=eng.speculative)
        eng.admission.accepted = 0
        eng.admission.cached_tokens_admitted = 0
        if eng.goodput is not None:
            # Warm-up steps were compile-bound; measure the workload only.
            eng.goodput.reset()
        if eng.prefix_cache is not None:
            # Warm-request prompts were random; zero the hit accounting so
            # the row reports the measured workload only.
            eng.prefix_cache.lookups = eng.prefix_cache.hits = 0
            eng.prefix_cache.tokens_hit = eng.prefix_cache.tokens_missed = 0
        server = None
        scraper = None
        scrape_stop = None
        scrapes = {"n": 0, "valid": 0}
        if serve:
            from distributed_pytorch_tpu.obs import validate_exposition
            from distributed_pytorch_tpu.obs.server import scrape as _scrape

            # Every program the workload needs is compiled; from here any
            # new XLA compilation is a bug the sentinel must catch.
            eng.arm_recompile_sentinel()
            server = eng.serve()
            scrape_stop = threading.Event()

            def _scrape_loop():
                while not scrape_stop.is_set():
                    try:
                        body = _scrape(server.url, "/metrics")
                        validate_exposition(body)
                        statusz = _scrape(server.url, "/statusz")
                        health = _scrape(server.url, "/healthz")
                        scrapes["n"] += 1
                        if (
                            statusz.get("health") == "live"
                            and health.get("status") == "live"
                        ):
                            scrapes["valid"] += 1
                    except Exception:
                        pass
                    scrape_stop.wait(0.05)

            scraper = threading.Thread(
                target=_scrape_loop, name="bench-scraper", daemon=True
            )
            scraper.start()

        start = time.perf_counter()
        submitted = 0
        ids = []
        while submitted < n_requests or eng.scheduler.has_work:
            now = time.perf_counter() - start
            while submitted < n_requests and arrivals[submitted] <= now:
                ids.append(
                    eng.submit(
                        prompts[submitted], SamplingParams(max_new_tokens=16)
                    )
                )
                submitted += 1
            if eng.scheduler.has_work or eng._inflight is not None:
                eng.step()
            elif submitted < n_requests:
                time.sleep(min(arrivals[submitted] - now, 0.01))
        assert all(eng.poll(r).finished for r in ids)
        if serve:
            scrape_stop.set()
            scraper.join(timeout=10)
        stats = eng.stats()
        row = {
            "prefix_caching": prefix_caching,
            "speculative": spec,
            "stats": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in stats.items()
            },
            # The unified-registry view of the same run — one source of
            # truth (ServingMetrics + admission + allocator) rendered
            # through MetricsRegistry.snapshot().
            "registry": eng.registry.snapshot(),
        }
        if mesh is not None:
            row["mesh"] = eng.mesh_fingerprint
            row["sharded_programs"] = eng._sharded_programs
        if tracer is not None:
            # The tracer sees EVERY request the engine completed, including
            # the n_warm compile-warm-up ones submitted before the metrics
            # reset — the engine-truth span count is warm-up + measured.
            row["trace_request_spans"] = tracer.spans_closed
            row["trace_spans_expected"] = (
                n_warm + stats["requests_completed"]
            )
        if eng.goodput is not None:
            rep = eng.goodput.report()
            row["goodput"] = {
                k: (
                    {kk: round(vv, 6) for kk, vv in v.items()}
                    if isinstance(v, dict)
                    else (round(v, 6) if isinstance(v, float) else v)
                )
                for k, v in rep.items()
            }
        if eng.flight.enabled:
            row["flight_events_recorded"] = eng.flight.recorded
            row["flight_events_dropped"] = eng.flight.dropped
        if eng.slo is not None:
            row["slo"] = eng.slo.state()
        if serve:
            row["scrapes_mid_run"] = scrapes["n"]
            row["scrapes_valid"] = scrapes["valid"]
            row["recompiles_at_steady_state"] = eng.sentinel.count
            row["recompile_trips"] = list(eng.sentinel.trips)
            row["xla_programs"] = len(eng.xla.programs)
            eng.sentinel.disarm()
            server.stop()
            eng._server = None
        tokens = [eng.poll(r).generated for r in ids]
        return row, tokens

    row_off, _ = run_pass(False)
    row_on, tokens_on = run_pass(True)
    rows = [row_off, row_on]
    off, on = rows[0]["stats"], rows[1]["stats"]
    out = {
        "mode": "serving_poisson_prefix",
        "workload": (
            f"serving_lm64_poisson{arrival_rate_hz:g}hz_n{n_requests}"
            f"_prefix{shared_prefix_len}"
        ),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "arrival_rate_hz": arrival_rate_hz,
        "n_requests": n_requests,
        "shared_prefix_len": shared_prefix_len,
        "rows": rows,
        "prefix_hit_rate": on.get("prefix_hit_rate", 0.0),
        "ttft_s_p50_caching_off": off.get("ttft_s_p50"),
        "ttft_s_p50_caching_on": on.get("ttft_s_p50"),
        "ttft_p50_speedup_cached": (
            round(off["ttft_s_p50"] / on["ttft_s_p50"], 4)
            if on.get("ttft_s_p50") else None
        ),
    }
    # Observability-parity pass: the IDENTICAL prefix-cached workload with
    # the FULL production-observability stack enabled — request tracing +
    # step timeline, flight recorder, SLO burn-rate monitor, goodput/MFU
    # accounting. The acceptance record: tokens must be bitwise-identical
    # to the all-off pass, the per-request span count must equal completed
    # requests, and the all-on TPOT p50 sits next to the all-off one so the
    # overhead is measured, not asserted (<2% regression is the gate).
    row_traced, tokens_traced = run_pass(
        True, trace=True, obs_full=True, serve=True
    )
    # A single paired pass cannot resolve a 2% TPOT delta here: p50 over
    # n_requests samples on a shared CPU swings tens of percent run to
    # run (and sometimes lands NEGATIVE). Measure the overhead as the
    # median over interleaved off/on passes instead; the token-parity
    # check stays pinned to the first traced pass above.
    tpots_off = [on.get("tpot_s_p50")]
    tpots_on = [row_traced["stats"].get("tpot_s_p50")]
    for _ in range(4):
        r_off_x, _ = run_pass(True)
        r_on_x, _ = run_pass(True, trace=True, obs_full=True)
        tpots_off.append(r_off_x["stats"].get("tpot_s_p50"))
        tpots_on.append(r_on_x["stats"].get("tpot_s_p50"))
    tpots_off = sorted(t for t in tpots_off if t)
    tpots_on = sorted(t for t in tpots_on if t)
    tpot_off = tpots_off[len(tpots_off) // 2] if tpots_off else None
    tpot_on = tpots_on[len(tpots_on) // 2] if tpots_on else None
    out["obs"] = {
        "greedy_tokens_identical_with_tracing": tokens_traced == tokens_on,
        # The traced pass now ALSO runs the introspection server (scraped
        # from another thread every 50ms), the XLA program ledger, and the
        # armed recompile sentinel — so the same token comparison pins the
        # whole wire: scraping mid-run must not perturb generation, and a
        # fully-warmed engine must never recompile at steady state.
        "greedy_tokens_identical_with_server": tokens_traced == tokens_on,
        "scrapes_mid_run": row_traced.get("scrapes_mid_run"),
        "scrapes_valid": row_traced.get("scrapes_valid"),
        "recompiles_at_steady_state": row_traced.get(
            "recompiles_at_steady_state"
        ),
        "recompile_trips": row_traced.get("recompile_trips"),
        "xla_programs_ledgered": row_traced.get("xla_programs"),
        "trace_request_spans": row_traced["trace_request_spans"],
        "trace_spans_expected": row_traced["trace_spans_expected"],
        "trace_spans_match": (
            row_traced["trace_request_spans"]
            == row_traced["trace_spans_expected"]
        ),
        "requests_completed": row_traced["stats"]["requests_completed"],
        "tpot_s_p50_obs_off": tpot_off,
        "tpot_s_p50_obs_on": tpot_on,
        "tpot_p50_obs_overhead": (
            round(tpot_on / tpot_off - 1.0, 4)
            if tpot_off and tpot_on else None
        ),
        "tpot_p50_obs_passes": len(tpots_on),
        "tpot_obs_overhead_abs_s": (
            round(tpot_on - tpot_off, 6)
            if tpot_off and tpot_on else None
        ),
        # Context for the ratio: the absolute cost is Python-side event
        # emission per step (tracer slices + counter tracks dominate; the
        # SLO/goodput/flight additions profile at ~15us). Against this
        # CPU microbench's ~1.4ms steps that reads as ~10%; against a
        # real accelerator's tens-of-ms serving steps the same absolute
        # cost is <1%.
        "tokens_per_sec_obs_off": on.get("tokens_per_sec"),
        "tokens_per_sec_obs_on": row_traced["stats"].get("tokens_per_sec"),
        "goodput": row_traced.get("goodput"),
        "flight_events_recorded": row_traced.get("flight_events_recorded"),
        "flight_events_dropped": row_traced.get("flight_events_dropped"),
        "slo": row_traced.get("slo"),
        "slo_alerts_fired": sum(
            s.get("alerts", 0)
            for s in (row_traced.get("slo") or {}).values()
        ),
    }
    if speculative:
        # Third pass: the prefix-cached workload again with speculative
        # rounds. Row [1] (prefix on, spec off) is the control — same
        # engine config, same workload, only the draft toggled.
        row_spec, _ = run_pass(True, spec=True)
        rows.append(row_spec)
        spec_on = rows[2]["stats"]
        out["mode"] = "serving_poisson_prefix_spec"
        out["workload"] += f"_gamma{gamma}"
        out["gamma"] = gamma
        out["spec_acceptance_rate"] = spec_on.get("spec_acceptance_rate")
        out["spec_tokens_per_verify_mean"] = spec_on.get(
            "spec_tokens_per_verify_mean"
        )
        out["tpot_s_p50_spec_off"] = on.get("tpot_s_p50")
        out["tpot_s_p50_spec_on"] = spec_on.get("tpot_s_p50")
        out["tpot_s_p95_spec_off"] = on.get("tpot_s_p95")
        out["tpot_s_p95_spec_on"] = spec_on.get("tpot_s_p95")
        out["tpot_p50_speedup_spec"] = (
            round(on["tpot_s_p50"] / spec_on["tpot_s_p50"], 4)
            if spec_on.get("tpot_s_p50") else None
        )
    if mesh_shapes:
        # One pass per mesh geometry over the IDENTICAL workload (prefix
        # caching on, spec off — same config as the headline row), greedy
        # tokens cross-checked against the unsharded prefix-on pass.
        mesh_rows = []
        n_devices = len(jax.devices())
        for shape in mesh_shapes.split(","):
            d, m = (int(x) for x in shape.strip().split("x"))
            if d * m > n_devices:
                mesh_rows.append(
                    {"mesh": f"{d}x{m}", "skipped": True,
                     "reason": f"needs {d * m} devices, have {n_devices}"}
                )
                continue
            row_mesh, tokens_mesh = run_pass(
                True, mesh=make_serving_mesh(d, m)
            )
            s = row_mesh["stats"]
            mesh_rows.append(
                {
                    "mesh": row_mesh["mesh"],
                    "tokens_per_sec": s.get("tokens_per_sec"),
                    "tpot_s_p50": s.get("tpot_s_p50"),
                    "tpot_s_p95": s.get("tpot_s_p95"),
                    "ttft_s_p50": s.get("ttft_s_p50"),
                    "prefix_hit_rate": s.get("prefix_hit_rate"),
                    "sharded_programs": row_mesh["sharded_programs"],
                    "greedy_tokens_match_unsharded": (
                        tokens_mesh == tokens_on
                    ),
                }
            )
        out["mesh_rows"] = mesh_rows
        out["mesh_greedy_parity"] = all(
            r.get("greedy_tokens_match_unsharded", True) for r in mesh_rows
        )
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def bench_fleet(
    n_replicas: int = 3,
    n_requests: int = 24,
    arrival_rate_hz: float = 20.0,
    seed: int = 0,
    shared_prefix_len: int = 24,
    kill_round: int = 12,
    procs: bool = False,
):
    """Routed-fleet benchmark with a mid-run replica kill: the SAME Poisson
    workload as ``bench_serving``, routed across ``n_replicas`` in-process
    engines by the ``FleetRouter``, with a seeded ``kill_replica`` chaos
    fault SIGKILLing (in-process: abandoning) the replica that affinity
    routing loaded — chosen as the rendezvous target of the shared prefix,
    so the kill provably lands on a replica holding decodes.

    Reported into the ``fleet`` section of ``BENCH_SERVING.json``:
    aggregate tokens/sec across the fleet, the router's dead-replica
    detection latency, and the failover TTFT spike — time from failover
    re-admission to the next committed token on the survivor, against the
    single-engine baseline TTFT p50. The acceptance row is
    ``greedy_tokens_match_single_engine``: every request (including the
    failed-over ones) must emit byte-identical greedy tokens to one
    uninterrupted engine.

    ``procs=True`` runs the identical drill against ``n_replicas`` worker
    SUBPROCESSES behind ``ProcessReplicaClient`` — the fault becomes a
    real SIGKILL, detection a failed control call, and every metric rides
    the localhost control plane. Reported as the ``fleet_procs`` section
    so the in-process ``fleet`` row stays the baseline to compare the
    process-isolation tax and failover spike against."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu import chaos
    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.serving import (
        FleetRouter,
        InferenceEngine,
        SamplingParams,
        prefix_affinity_key,
    )
    from distributed_pytorch_tpu.serving.admission import ServingMetrics
    from distributed_pytorch_tpu.serving.fleet import _rendezvous

    on_cpu = jax.devices()[0].platform == "cpu"
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, d_ff=256,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(seed)
    shared = (
        rng.integers(0, 256, shared_prefix_len).tolist()
        if shared_prefix_len else []
    )
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        shared + rng.integers(0, 256, int(rng.integers(4, 17))).tolist()
        for _ in range(n_requests)
    ]
    warm_rng = np.random.default_rng(seed + 1)
    page_size = 8

    def mk_engine():
        eng = InferenceEngine(
            model, params, max_slots=4, max_seq_len=64, page_size=page_size,
            token_budget=64, max_prefill_chunk=32, max_queue=n_requests,
            prefix_cache=True,
        )
        # Same off-the-clock compile warm-up as bench_serving: one request
        # per prefill bucket, then reset accounting so TTFT measures
        # scheduling (and failover), not XLA compilation.
        chunk = 1
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            chunk *= 2
        eng.metrics = ServingMetrics(speculative=False)
        eng.admission.accepted = 0
        eng.admission.cached_tokens_admitted = 0
        eng.prefix_cache.lookups = eng.prefix_cache.hits = 0
        eng.prefix_cache.tokens_hit = eng.prefix_cache.tokens_missed = 0
        return eng

    def drive(submit, step, has_work, poll):
        start = time.perf_counter()
        submitted = 0
        handles = []
        while submitted < n_requests or has_work():
            now = time.perf_counter() - start
            while submitted < n_requests and arrivals[submitted] <= now:
                handles.append(
                    submit(
                        prompts[submitted],
                        SamplingParams(max_new_tokens=16),
                    )
                )
                submitted += 1
            if has_work():
                step()
            elif submitted < n_requests:
                time.sleep(min(arrivals[submitted] - now, 0.01))
        elapsed = time.perf_counter() - start
        tokens = [poll(h).generated for h in handles]
        return tokens, elapsed

    # Single-engine reference: uninterrupted run of the identical workload
    # — the token-parity oracle and the baseline TTFT for the spike ratio.
    ref = mk_engine()
    ref_tokens, _ = drive(
        ref.submit,
        ref.step,
        lambda: ref.scheduler.has_work or ref._inflight is not None,
        ref.poll,
    )
    baseline_ttft_p50 = ref.stats().get("ttft_s_p50")
    ref.close()

    # The kill lands on the replica the shared prefix routes to, so it is
    # holding decodes when it dies (all affinity traffic is there).
    names = [f"r{i}" for i in range(n_replicas)]
    key = prefix_affinity_key(prompts[0], page_size)
    victim = _rendezvous(key, names) if key is not None else names[0]
    victim_idx = int(victim[1:])

    prev_plan = os.environ.get(chaos.ENV_VAR)
    os.environ[chaos.ENV_VAR] = json.dumps({
        "seed": seed,
        "faults": [
            {"kind": (
                "kill_replica_process" if procs else "kill_replica"
            ), "replica": victim_idx, "at_step": kill_round}
        ],
    })
    chaos._reset()
    if procs:
        from distributed_pytorch_tpu.serving import spawn_replica_clients

        on_cpu_dtype = "float32" if on_cpu else "bfloat16"
        worker_specs = [
            {
                "name": f"r{i}",
                "model": dict(
                    vocab_size=256, d_model=64, n_layers=2, n_heads=8,
                    d_ff=256, dtype=on_cpu_dtype,
                ),
                "init_seed": 0,
                "engine": dict(
                    max_slots=4, max_seq_len=64, page_size=page_size,
                    token_budget=64, max_prefill_chunk=32,
                    max_queue=n_requests, prefix_cache=True,
                ),
                # Same off-the-clock warm-up as mk_engine: one request
                # per prefill bucket (lengths 2..33), compiled before the
                # clock starts.
                "warm_chunks": [2, 3, 5, 9, 17, 33],
            }
            for i in range(n_replicas)
        ]
        members = spawn_replica_clients(worker_specs)
    else:
        members = [mk_engine() for _ in range(n_replicas)]
    router = FleetRouter(members, probe_every=4)
    try:
        fleet_tokens, elapsed = drive(
            router.submit,
            router.step,
            lambda: any(
                not s.finished for s in router._shadows.values()
            ),
            router.poll,
        )
        total_tokens = sum(len(t) for t in fleet_tokens)
        detection_s = router.registry.read_gauge(
            "dead_replica_detection_seconds"
        )
        failover_p50 = router.registry.read_quantile(
            "failover_ttft_seconds", 0.5
        )
        failover_max = router._failover_ttft.max
        failed_over = router.registry.read_counter(
            "requests_failed_over_total"
        )
        leaked = sum(
            int(rep.client.read_gauge("pages_referenced"))
            for rep in router.replicas()
            if rep.state != "dead"
        )
        fleet_doc = {
            "transport": "process" if procs else "in_process",
            "n_replicas": n_replicas,
            "workload": (
                f"fleet{n_replicas}_poisson{arrival_rate_hz:g}hz"
                f"_n{n_requests}_prefix{shared_prefix_len}"
            ),
            "kill_round": kill_round,
            "victim": victim,
            "victim_dead": any(
                r.name == victim and r.state == "dead"
                for r in router.replicas()
            ),
            "aggregate_tokens_per_sec": round(total_tokens / elapsed, 2),
            "requests_completed": len(fleet_tokens),
            "requests_failed_over": int(failed_over),
            "detection_latency_s": round(detection_s, 6),
            "failover_ttft_s_p50": (
                round(failover_p50, 6)
                if failover_p50 == failover_p50 else None  # NaN guard
            ),
            "failover_ttft_s_max": (
                round(failover_max, 6)
                if failover_max > -math.inf else None
            ),
            "baseline_ttft_s_p50": baseline_ttft_p50,
            # The spike: failover-TTFT p50 over baseline TTFT p50 — how
            # much worse a failed-over request's next token is than a
            # fresh request's first.
            "failover_ttft_spike_x": (
                round(failover_p50 / baseline_ttft_p50, 4)
                if failover_p50 == failover_p50 and baseline_ttft_p50
                else None
            ),
            "greedy_tokens_match_single_engine": (
                fleet_tokens == ref_tokens
            ),
            "pages_leaked_on_survivors": leaked,
            "routed_affinity": int(
                router.registry.read_counter("routed_affinity_total")
            ),
            "routed_least_loaded": int(
                router.registry.read_counter("routed_least_loaded_total")
            ),
        }
    finally:
        router.close()
        if prev_plan is None:
            os.environ.pop(chaos.ENV_VAR, None)
        else:
            os.environ[chaos.ENV_VAR] = prev_plan
        chaos._reset()

    # Merge into BENCH_SERVING.json: the fleet section rides next to the
    # single-engine rows (bench_history records it un-gated).
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = {
            "mode": "serving_fleet_only",
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind,
            "rows": [],
        }
    doc["fleet_procs" if procs else "fleet"] = fleet_doc
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return fleet_doc


def bench_fleet_router(
    n_replicas: int = 3,
    n_requests: int = 24,
    arrival_rate_hz: float = 20.0,
    seed: int = 0,
    shared_prefix_len: int = 24,
    kill_round: int = 12,
    procs: bool = False,
):
    """Durable-control-plane benchmark: the ROUTER dies mid-run. The same
    Poisson workload as ``bench_fleet``, but the seeded fault is a
    raise-mode ``kill_router`` at a step boundary — the router object is
    abandoned with shadows, streams and route state in memory, exactly as
    a SIGKILL leaves them, and ``FleetRouter.recover`` rebuilds a
    successor from the write-ahead journal.

    Reported into the ``fleet_router`` section of ``BENCH_SERVING.json``:
    recovery wall time (journal replay + worker re-adoption + shadow
    reconciliation), the resume-TTFT spike — time from recovery start to
    each re-adopted request's next committed token, against the baseline
    single-engine TTFT p50 — and the reconciliation counts
    (re_adopted / re_admitted / lost / finished_tails). The acceptance
    row is greedy token parity with one uninterrupted engine across the
    crash, and zero leaked pages fleet-wide (the workers all survive the
    router, so there is no SIGKILL exemption).

    ``procs=True`` runs the workers as registry-tracked SUBPROCESSES:
    recovery re-adopts them via ``ProcessReplicaClient.attach`` from the
    on-disk worker registry, and reconciliation polls ride the localhost
    control plane."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu import chaos
    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.serving import (
        FleetRouter,
        InferenceEngine,
        LocalReplicaClient,
        SamplingParams,
    )
    from distributed_pytorch_tpu.serving.admission import ServingMetrics

    on_cpu = jax.devices()[0].platform == "cpu"
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, d_ff=256,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(seed)
    shared = (
        rng.integers(0, 256, shared_prefix_len).tolist()
        if shared_prefix_len else []
    )
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        shared + rng.integers(0, 256, int(rng.integers(4, 17))).tolist()
        for _ in range(n_requests)
    ]
    warm_rng = np.random.default_rng(seed + 1)
    page_size = 8

    def mk_engine():
        eng = InferenceEngine(
            model, params, max_slots=4, max_seq_len=64, page_size=page_size,
            token_budget=64, max_prefill_chunk=32, max_queue=n_requests,
            prefix_cache=True,
        )
        chunk = 1
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            chunk *= 2
        eng.metrics = ServingMetrics(speculative=False)
        eng.admission.accepted = 0
        eng.admission.cached_tokens_admitted = 0
        eng.prefix_cache.lookups = eng.prefix_cache.hits = 0
        eng.prefix_cache.tokens_hit = eng.prefix_cache.tokens_missed = 0
        return eng

    # Single-engine reference: the token-parity oracle and the baseline
    # TTFT the resume spike is measured against.
    ref = mk_engine()
    start = time.perf_counter()
    submitted, ref_ids = 0, []
    while submitted < n_requests or (
        ref.scheduler.has_work or ref._inflight is not None
    ):
        now = time.perf_counter() - start
        while submitted < n_requests and arrivals[submitted] <= now:
            ref_ids.append(
                ref.submit(
                    prompts[submitted], SamplingParams(max_new_tokens=16)
                )
            )
            submitted += 1
        if ref.scheduler.has_work or ref._inflight is not None:
            ref.step()
        elif submitted < n_requests:
            time.sleep(min(arrivals[submitted] - now, 0.01))
    ref_tokens = [ref.poll(i).generated for i in ref_ids]
    baseline_ttft_p50 = ref.stats().get("ttft_s_p50")
    ref.close()

    jdir = tempfile.mkdtemp(prefix="bench_router_journal.")
    prev_plan = os.environ.get(chaos.ENV_VAR)
    os.environ[chaos.ENV_VAR] = json.dumps({
        "seed": seed,
        "faults": [
            {"kind": "kill_router", "mode": "raise", "at_step": kill_round}
        ],
    })
    chaos._reset()
    members = None
    try:
        if procs:
            from distributed_pytorch_tpu.serving import (
                spawn_replica_clients,
            )

            env = dict(os.environ)
            env.pop(chaos.ENV_VAR, None)  # the fault is the router's
            env["TPURUN_ORPHAN_GRACE"] = "300"
            worker_specs = [
                {
                    "name": f"r{i}",
                    "model": dict(
                        vocab_size=256, d_model=64, n_layers=2, n_heads=8,
                        d_ff=256,
                        dtype="float32" if on_cpu else "bfloat16",
                    ),
                    "init_seed": 0,
                    "engine": dict(
                        max_slots=4, max_seq_len=64, page_size=page_size,
                        token_budget=64, max_prefill_chunk=32,
                        max_queue=n_requests, prefix_cache=True,
                    ),
                    "warm_chunks": [2, 3, 5, 9, 17, 33],
                }
                for i in range(n_replicas)
            ]
            members = spawn_replica_clients(
                worker_specs, run_dir=jdir, env=env
            )
        else:
            members = [
                LocalReplicaClient(mk_engine()) for _ in range(n_replicas)
            ]
        router = FleetRouter(members, probe_every=4, journal_dir=jdir)

        # Incarnation 1: pump the Poisson schedule until the armed fault
        # "kills" the router (raise-mode: the object is abandoned with all
        # its state in memory, never stepped or closed again).
        start = time.perf_counter()
        submitted = 0
        handles: list = [None] * n_requests
        crashed = False
        while submitted < n_requests or any(
            not s.finished for s in router._shadows.values()
        ):
            now = time.perf_counter() - start
            while submitted < n_requests and arrivals[submitted] <= now:
                handles[submitted] = router.submit(
                    prompts[submitted], SamplingParams(max_new_tokens=16)
                )
                submitted += 1
            try:
                if any(not s.finished for s in router._shadows.values()):
                    router.step()
                elif submitted < n_requests:
                    time.sleep(min(arrivals[submitted] - now, 0.01))
            except chaos.InjectedFault:
                crashed = True
                break
        assert crashed, (
            f"kill_router at step {kill_round} never fired (workload "
            "drained first — raise kill_round or n_requests)"
        )
        del router  # crash: no close(), no journal flush beyond the WAL

        # Disarm before the successor steps, or it would crash too.
        os.environ.pop(chaos.ENV_VAR, None)
        chaos._reset()

        # Incarnation 2: replay the journal, re-adopt the workers,
        # reconcile. This is the headline number — how long the control
        # plane is dark.
        t_rec = time.perf_counter()
        router = FleetRouter.recover(
            jdir,
            replicas=(
                None if procs
                else {f"r{i}": members[i] for i in range(n_replicas)}
            ),
            probe_every=4,
        )
        recovery_s = time.perf_counter() - t_rec
        summary = dict(router.last_recovery)

        # Resume TTFT: recovery start -> next committed token, per
        # re-adopted (still unfinished) request.
        pre_lens = {
            fid: len(s.generated)
            for fid, s in router._shadows.items()
            if not s.finished
        }
        resume_ttft: dict = {}
        while submitted < n_requests or any(
            not s.finished for s in router._shadows.values()
        ):
            now = time.perf_counter() - start
            while submitted < n_requests and arrivals[submitted] <= now:
                handles[submitted] = router.submit(
                    prompts[submitted], SamplingParams(max_new_tokens=16)
                )
                submitted += 1
            if any(not s.finished for s in router._shadows.values()):
                router.step()
            elif submitted < n_requests:
                time.sleep(min(arrivals[submitted] - now, 0.01))
            t_now = time.perf_counter()
            for fid, pre in pre_lens.items():
                if fid not in resume_ttft and len(
                    router._shadows[fid].generated
                ) > pre:
                    resume_ttft[fid] = t_now - t_rec
        elapsed = time.perf_counter() - start

        fleet_tokens = [
            router.poll(handles[i]).generated for i in range(n_requests)
        ]
        total_tokens = sum(len(t) for t in fleet_tokens)
        leaked = sum(
            int(rep.client.read_gauge("pages_referenced"))
            for rep in router.replicas()
        )
        resumes = sorted(resume_ttft.values())
        resume_p50 = (
            resumes[len(resumes) // 2] if resumes else None
        )
        fleet_doc = {
            "transport": "process" if procs else "in_process",
            "n_replicas": n_replicas,
            "workload": (
                f"fleet{n_replicas}_poisson{arrival_rate_hz:g}hz"
                f"_n{n_requests}_prefix{shared_prefix_len}"
                "_kill_router"
            ),
            "kill_round": kill_round,
            "recovery_s": round(recovery_s, 6),
            "re_adopted": summary["re_adopted"],
            "re_admitted": summary["re_admitted"],
            "lost": summary["lost"],
            "finished_tails": summary["finished_tails"],
            "re_adopted_workers": summary["re_adopted_workers"],
            "records_replayed": summary["records_replayed"],
            "aggregate_tokens_per_sec": round(total_tokens / elapsed, 2),
            "requests_completed": len(fleet_tokens),
            "resume_ttft_s_p50": (
                round(resume_p50, 6) if resume_p50 is not None else None
            ),
            "resume_ttft_s_max": (
                round(resumes[-1], 6) if resumes else None
            ),
            "baseline_ttft_s_p50": baseline_ttft_p50,
            # The spike: resume-TTFT p50 over baseline TTFT p50 — how much
            # worse a request's next token is for having lived through a
            # router crash than a fresh request's first.
            "resume_ttft_spike_x": (
                round(resume_p50 / baseline_ttft_p50, 4)
                if resume_p50 is not None and baseline_ttft_p50
                else None
            ),
            "greedy_tokens_match_single_engine": (
                fleet_tokens == ref_tokens
            ),
            "pages_leaked": leaked,
        }
        router.close()
        if procs:
            # The recovered router closed the ATTACHED clients; the
            # original spawner objects still hold the pipes and the (now
            # zombie) children — reap them.
            for m in members:
                try:
                    m.abandon()
                except Exception:
                    pass
            members = None
    finally:
        if procs and members is not None:
            for m in members:
                try:
                    m.abandon()
                except Exception:
                    pass
        if prev_plan is None:
            os.environ.pop(chaos.ENV_VAR, None)
        else:
            os.environ[chaos.ENV_VAR] = prev_plan
        chaos._reset()
        shutil.rmtree(jdir, ignore_errors=True)

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = {
            "mode": "serving_fleet_only",
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind,
            "rows": [],
        }
    doc["fleet_router"] = fleet_doc
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return fleet_doc


def bench_frontdoor(
    n_requests: int = 24,
    arrival_rate_hz: float = 20.0,
    seed: int = 0,
):
    """Front-door benchmark: the mixed-tenant streaming gateway over the
    same open-loop Poisson workload as ``bench_serving``.

    Two tenant classes share one engine — ``gold`` (weight 3, tighter
    SLOs) and ``bronze`` (weight 1) — with requests assigned
    pseudo-randomly 1:2 gold:bronze. The identical workload first runs
    POLLED against a bare engine (the reference pass), then STREAMED
    through :class:`~.serving.frontdoor.FrontDoor` with every stream
    consumed token-by-token as it is produced. Reported into the
    ``frontdoor`` section of ``BENCH_SERVING.json``:

    * ``streamed_tokens_bitwise_identical_polled`` — the acceptance row:
      per-token delivery must not change a single greedy token;
    * ``streaming_overhead_x`` — streamed wall time over polled wall time
      for the whole workload (the door's scheduling + delivery tax);
    * per-tenant TTFT/TPOT percentiles as the DOOR measures them (client
      visibility, not engine internals) and per-tenant SLO compliance —
      the fraction of finished requests inside that tenant's declared
      thresholds, plus whether the tenant's burn-rate alert fired.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.serving import (
        FrontDoor,
        InferenceEngine,
        SamplingParams,
        TenantConfig,
    )

    on_cpu = jax.devices()[0].platform == "cpu"
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, d_ff=256,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        rng.integers(0, 256, int(rng.integers(4, 17))).tolist()
        for _ in range(n_requests)
    ]
    tenant_of = [
        "gold" if rng.random() < 1 / 3 else "bronze"
        for _ in range(n_requests)
    ]
    sp = SamplingParams(max_new_tokens=16)
    # Loose-on-CPU thresholds: the compliance fractions are the tracked
    # numbers; alerts firing on a microbench would measure the rig.
    tenants = {
        "gold": TenantConfig(
            weight=3.0, ttft_slo_s=2.0, tpot_slo_s=0.5
        ),
        "bronze": TenantConfig(
            weight=1.0, ttft_slo_s=5.0, tpot_slo_s=1.0
        ),
    }

    def make_eng():
        eng = InferenceEngine(
            model, params, max_slots=8, max_seq_len=64, page_size=8,
            token_budget=64, max_prefill_chunk=32, max_queue=n_requests,
        )
        # Same off-the-clock compile warm-up as bench_serving.
        warm_rng = np.random.default_rng(seed + 1)
        chunk = 1
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            chunk *= 2
        return eng

    # ---- reference pass: bare engine, polled --------------------------
    eng = make_eng()
    t0 = time.perf_counter()
    ids = []
    next_i = 0
    while next_i < n_requests or not all(
        eng.requests[r].done for r in ids
    ):
        now = time.perf_counter() - t0
        while next_i < n_requests and arrivals[next_i] <= now:
            ids.append(eng.submit(prompts[next_i], sp))
            next_i += 1
        eng.step()
    polled_wall = time.perf_counter() - t0
    polled_tokens = [list(eng.requests[r].generated) for r in ids]
    eng.close()

    # ---- streamed pass: same workload through the door ----------------
    eng = make_eng()
    door = FrontDoor(eng, tenants=tenants)
    t0 = time.perf_counter()
    streams = []
    delivered = [[] for _ in range(n_requests)]
    next_i = 0
    while next_i < n_requests or not all(s.done for s in streams):
        now = time.perf_counter() - t0
        while next_i < n_requests and arrivals[next_i] <= now:
            streams.append(
                door.open_stream(
                    prompts[next_i], tenant_of[next_i], params=sp
                )
            )
            next_i += 1
        door.pump()
        # Consume every stream as far as it has committed tokens — the
        # per-token delivery path is exactly what this pass measures.
        for i, s in enumerate(streams):
            while s.backlog() > 0:
                delivered[i].append(next(s))
    for i, s in enumerate(streams):
        delivered[i].extend(s.drain())
    streamed_wall = time.perf_counter() - t0

    n_gen = sum(len(t) for t in delivered)
    per_tenant = {}
    for tenant, cfg in tenants.items():
        ss = [s for i, s in enumerate(streams) if tenant_of[i] == tenant]
        ttfts = sorted(
            s.first_token_t - s.submit_t
            for s in ss
            if s.first_token_t is not None
        )
        tpots = sorted(
            (s.last_token_t - s.first_token_t) / (s.seen - 1)
            for s in ss
            if s.last_token_t is not None and s.seen > 1
        )

        def pct(xs, q):
            return round(float(np.quantile(xs, q)), 4) if xs else None

        ok = sum(
            1
            for s in ss
            if s.first_token_t is not None
            and s.first_token_t - s.submit_t <= cfg.ttft_slo_s
            and (
                s.seen <= 1
                or (s.last_token_t - s.first_token_t) / (s.seen - 1)
                <= cfg.tpot_slo_s
            )
        )
        per_tenant[tenant] = {
            "requests": len(ss),
            "ttft_s_p50": pct(ttfts, 0.5),
            "ttft_s_p95": pct(ttfts, 0.95),
            "tpot_s_p50": pct(tpots, 0.5),
            "tpot_s_p95": pct(tpots, 0.95),
            "slo_compliance": round(ok / len(ss), 4) if ss else None,
            "slo_alert_fired": bool(
                door.registry.read_counter(
                    f"slo_ttft_{tenant}_alerts_total"
                )
                + door.registry.read_counter(
                    f"slo_tpot_{tenant}_alerts_total"
                )
            ),
        }
    eng.close()

    fd_doc = {
        "n_requests": n_requests,
        "arrival_rate_hz": arrival_rate_hz,
        "tokens_generated": n_gen,
        "tokens_per_sec": round(n_gen / streamed_wall, 2),
        "polled_tokens_per_sec": round(n_gen / polled_wall, 2),
        "streaming_overhead_x": round(streamed_wall / polled_wall, 3),
        "streamed_tokens_bitwise_identical_polled": (
            delivered == polled_tokens
        ),
        "backpressure_stalls": int(
            door.registry.read_counter("backpressure_stalls_total")
        ),
        "tenants": per_tenant,
    }

    # Merge like the fleet section: the frontdoor row rides next to the
    # single-engine rows and bench_history records it un-gated.
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = {
            "mode": "serving_frontdoor_only",
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind,
            "rows": [],
        }
    doc["frontdoor"] = fd_doc
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return fd_doc


def bench_disttrace(
    n_requests: int = 16,
    arrival_rate_hz: float = 20.0,
    seed: int = 0,
):
    """Distributed-tracing benchmark: the front-door Poisson workload with
    the fleet-tracing stack off vs fully on.

    The ON pass runs everything the disttrace layer adds — a door-lane
    tracer (pid 3), an engine tracer (request spans + step timeline), the
    head+tail :class:`~.obs.disttrace.TraceSampler` at ``head_rate=1.0``,
    the XLA ledger with the recompile sentinel armed after warm-up — and
    then merges the per-layer documents and decomposes every trace into
    its waterfall. Reported into the ``disttrace`` section of
    ``BENCH_SERVING.json``:

    * ``tokens_bitwise_identical`` — the acceptance row: tracing every
      hop must not change a single greedy token;
    * ``recompiles_at_steady_state`` — the armed sentinel must read ZERO
      across the traced pass (span emission never re-traces jit);
    * ``tpot_p50_disttrace_overhead`` — TPOT p50 ratio measured as the
      median over interleaved off/on passes (a single pair cannot
      resolve a few-percent delta on a shared CPU; same idiom as the
      ``obs`` section);
    * waterfall integrity over every finished trace: components must sum
      to the measured e2e within 5% (they are an exact partition by
      construction — the row proves it on real data, not toy events).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.obs import (
        TraceSampler,
        Tracer,
        merge_traces,
        request_waterfall,
        trace_ids,
    )
    from distributed_pytorch_tpu.serving import (
        FrontDoor,
        InferenceEngine,
        SamplingParams,
        TenantConfig,
    )

    on_cpu = jax.devices()[0].platform == "cpu"
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, d_ff=256,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        rng.integers(0, 256, int(rng.integers(4, 17))).tolist()
        for _ in range(n_requests)
    ]
    tenant_of = [
        "gold" if rng.random() < 1 / 3 else "bronze"
        for _ in range(n_requests)
    ]
    sp = SamplingParams(max_new_tokens=16)
    tenants = {
        "gold": TenantConfig(weight=3.0, ttft_slo_s=2.0, tpot_slo_s=0.5),
        "bronze": TenantConfig(weight=1.0, ttft_slo_s=5.0, tpot_slo_s=1.0),
    }

    def run_pass(traced: bool):
        eng = InferenceEngine(
            model, params, max_slots=8, max_seq_len=64, page_size=8,
            token_budget=64, max_prefill_chunk=32, max_queue=n_requests,
            tracer=Tracer() if traced else None,
            xla_ledger=traced,
        )
        # Same off-the-clock compile warm-up as bench_frontdoor, then arm
        # the sentinel so any tracing-induced recompile becomes a counted
        # failure of the traced pass.
        warm_rng = np.random.default_rng(seed + 1)
        chunk = 1
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            chunk *= 2
        if traced:
            eng.arm_recompile_sentinel()
        door = FrontDoor(
            eng, tenants=tenants,
            tracer=Tracer() if traced else None,
            sampler=(
                TraceSampler(head_rate=1.0, max_kept=2 * n_requests)
                if traced else None
            ),
        )
        t0 = time.perf_counter()
        streams = []
        delivered = [[] for _ in range(n_requests)]
        next_i = 0
        while next_i < n_requests or not all(s.done for s in streams):
            now = time.perf_counter() - t0
            while next_i < n_requests and arrivals[next_i] <= now:
                streams.append(
                    door.open_stream(
                        prompts[next_i], tenant_of[next_i], params=sp
                    )
                )
                next_i += 1
            door.pump()
            for i, s in enumerate(streams):
                while s.backlog() > 0:
                    delivered[i].append(next(s))
        for i, s in enumerate(streams):
            delivered[i].extend(s.drain())
        wall = time.perf_counter() - t0

        tpots = sorted(
            (s.last_token_t - s.first_token_t) / (s.seen - 1)
            for s in streams
            if s.last_token_t is not None and s.seen > 1
        )
        row = {
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(
                sum(len(t) for t in delivered) / wall, 2
            ),
            "tpot_s_p50": (
                round(float(np.quantile(tpots, 0.5)), 6) if tpots else None
            ),
        }
        if traced:
            row["recompiles_at_steady_state"] = eng.sentinel.count
            row["recompile_trips"] = list(eng.sentinel.trips)
            eng.sentinel.disarm()
            # Merge the door + engine documents and decompose EVERY kept
            # trace — the integrity row covers the whole pass, not one
            # cherry-picked request.
            merged = merge_traces(*door.trace_documents())
            ids = trace_ids(merged)
            errs = []
            for tid in ids:
                wf = request_waterfall(merged, tid)
                total = sum(wf["components"].values())
                errs.append(
                    abs(total - wf["e2e_s"]) / wf["e2e_s"]
                    if wf["e2e_s"] > 0 else 0.0
                )
            row["trace_ids"] = len(ids)
            row["waterfall_max_sum_err"] = (
                round(max(errs), 6) if errs else None
            )
            row["waterfalls_sum_within_5pct"] = bool(
                errs and max(errs) <= 0.05
            )
            row["sampler"] = door.sampler.counters()
        eng.close()
        return row, delivered

    row_off, tokens_off = run_pass(False)
    row_on, tokens_on = run_pass(True)
    # Median-over-interleaved-passes overhead, exactly like the obs row:
    # the parity + waterfall checks stay pinned to the first traced pass.
    tpots_off = [row_off["tpot_s_p50"]]
    tpots_on = [row_on["tpot_s_p50"]]
    for _ in range(2):
        r_off_x, _ = run_pass(False)
        r_on_x, _ = run_pass(True)
        tpots_off.append(r_off_x["tpot_s_p50"])
        tpots_on.append(r_on_x["tpot_s_p50"])
    tpots_off = sorted(t for t in tpots_off if t)
    tpots_on = sorted(t for t in tpots_on if t)
    tpot_off = tpots_off[len(tpots_off) // 2] if tpots_off else None
    tpot_on = tpots_on[len(tpots_on) // 2] if tpots_on else None

    dt_doc = {
        "n_requests": n_requests,
        "arrival_rate_hz": arrival_rate_hz,
        "tokens_bitwise_identical": tokens_on == tokens_off,
        "recompiles_at_steady_state": row_on["recompiles_at_steady_state"],
        "recompile_trips": row_on["recompile_trips"],
        "trace_ids": row_on["trace_ids"],
        "waterfall_max_sum_err": row_on["waterfall_max_sum_err"],
        "waterfalls_sum_within_5pct": row_on["waterfalls_sum_within_5pct"],
        "sampler": row_on["sampler"],
        "tokens_per_sec_off": row_off["tokens_per_sec"],
        "tokens_per_sec_on": row_on["tokens_per_sec"],
        "tpot_s_p50_disttrace_off": tpot_off,
        "tpot_s_p50_disttrace_on": tpot_on,
        "tpot_p50_disttrace_overhead": (
            round(tpot_on / tpot_off - 1.0, 4)
            if tpot_off and tpot_on else None
        ),
        # Context for the ratio, same as the obs row: the cost is
        # Python-side event emission per token/step (door span events +
        # engine decode_token instants + counter tracks), an absolute
        # per-step price. Against this CPU microbench's ~1.5ms TPOT it
        # reads large; against a real accelerator's tens-of-ms serving
        # steps the same absolute cost is a few percent.
        "tpot_disttrace_overhead_abs_s": (
            round(tpot_on - tpot_off, 6)
            if tpot_off and tpot_on else None
        ),
        "tpot_p50_disttrace_passes": len(tpots_on),
    }

    # Merge like the frontdoor section: rides next to the single-engine
    # rows and bench_history records it un-gated.
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = {
            "mode": "serving_disttrace_only",
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind,
            "rows": [],
        }
    doc["disttrace"] = dt_doc
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return dt_doc


def bench_perfwatch(
    n_requests: int = 16,
    arrival_rate_hz: float = 20.0,
    seed: int = 0,
    stall_phase: str = "dispatch",
    stall_s: float = 0.05,
    stall_after_steps: int = 20,
    detect_budget_steps: int = 12,
):
    """Performance-observatory benchmark: the front-door Poisson workload
    with the TSDB + roofline + regression detector off vs on, plus a
    seeded ``slow_program`` chaos drill.

    Three questions, answered into the ``perfwatch`` section of
    ``BENCH_SERVING.json``:

    * does the observatory COST anything? — bitwise greedy-token parity
      observed-vs-off, plus TPOT p50 overhead as a median over
      interleaved passes (same idiom as the ``obs``/``disttrace`` rows);
    * does the detector WORK? — a chaos ``slow_program`` fault armed
      mid-run stalls one engine phase persistently; the CUSUM must fire
      within ``detect_budget_steps`` COMPARABLE samples (pure-decode
      steps of the firing stratum — budget covers a fresh stratum's
      median/MAD warm-up plus the CUSUM crossing) AND blame the stalled
      phase (the stall is also asserted token-invariant — a sleep must
      never change a greedy token). The drill pass runs CLOSED-LOOP
      (all arrivals at t=0) so the decode stratum being regressed is
      warm before injection: a stratum first seen mid-stall anchors its
      baseline on stalled samples and honestly reports "normal";
    * is it HONEST at steady state? — the clean observed pass must end
      with zero alerts (false-positive row), and the TSDB memory bound
      is recorded so the history shows it never grows.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu import chaos
    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.serving import (
        FrontDoor,
        InferenceEngine,
        SamplingParams,
        TenantConfig,
    )

    on_cpu = jax.devices()[0].platform == "cpu"
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, d_ff=256,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        rng.integers(0, 256, int(rng.integers(4, 17))).tolist()
        for _ in range(n_requests)
    ]
    tenant_of = [
        "gold" if rng.random() < 1 / 3 else "bronze"
        for _ in range(n_requests)
    ]
    sp = SamplingParams(max_new_tokens=16)
    tenants = {
        "gold": TenantConfig(weight=3.0, ttft_slo_s=2.0, tpot_slo_s=0.5),
        "bronze": TenantConfig(weight=1.0, ttft_slo_s=5.0, tpot_slo_s=1.0),
    }

    def run_pass(observed: bool, drill: bool = False):
        # A leaked plan from a previous pass would stall the clean
        # passes; clear BEFORE engine construction, not just after.
        os.environ.pop(chaos.ENV_VAR, None)
        chaos._reset()
        eng = InferenceEngine(
            model, params, max_slots=8, max_seq_len=64, page_size=8,
            token_budget=64, max_prefill_chunk=32, max_queue=n_requests,
            timeseries=observed, xla_ledger=observed,
        )
        # Off-the-clock compile warm-up (same ladder as bench_frontdoor).
        # For the observed pass this doubles as the detector's median/MAD
        # warm-up: the compile-dominated steps land inside the robust
        # window, so "normal" anchors at the steady-state level.
        warm_rng = np.random.default_rng(seed + 1)
        chunk = 1
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            chunk *= 2

        injected = {}
        observer = None
        if drill:
            # Arm the stall AFTER warm-up so `at_step` counts Poisson
            # steps: the detector gets a steady-state lead-in, then the
            # level shifts mid-run. The observer pins the injection point
            # in DETECTOR step coordinates: it fires inside the first
            # stalled step, before that step's observe(), so the first
            # regressed sample is regress.steps + 1.
            os.environ[chaos.ENV_VAR] = json.dumps({
                "faults": [{
                    "kind": "slow_program",
                    "phase": stall_phase,
                    "duration": stall_s,
                    "at_step": stall_after_steps,
                }],
            })
            chaos._reset()

            def observer(kind, step, mode):
                if kind == "slow_program" and "regress_step" not in injected:
                    injected["regress_step"] = eng.regress.steps + 1
                    # Per-stratum sample counts at injection: the fire
                    # event's stratum_samples minus this is detection
                    # latency in COMPARABLE samples (prefill-mixed steps
                    # are invisible to the detector by design).
                    injected["stratum_n"] = {
                        rows: s.n
                        for (rows, name), s in eng.regress._watch.items()
                        if name == "step_wall_seconds"
                    }

            chaos.add_fault_observer(observer)

        # The overhead passes replay the Poisson tape; the drill runs
        # CLOSED-LOOP (every request enqueued at t=0). A stratum born
        # mid-stall anchors its median/MAD warm-up on stalled samples —
        # it honestly believes the stall is normal and can never fire —
        # so detection requires the batch shape being regressed to exist
        # BEFORE injection. Closed-loop arrivals reach the steady-state
        # decode stratum well before ``stall_after_steps``.
        sched = np.zeros(n_requests) if drill else arrivals
        door = FrontDoor(eng, tenants=tenants)
        try:
            t0 = time.perf_counter()
            streams = []
            delivered = [[] for _ in range(n_requests)]
            next_i = 0
            while next_i < n_requests or not all(s.done for s in streams):
                now = time.perf_counter() - t0
                while next_i < n_requests and sched[next_i] <= now:
                    streams.append(
                        door.open_stream(
                            prompts[next_i], tenant_of[next_i], params=sp
                        )
                    )
                    next_i += 1
                door.pump()
                for i, s in enumerate(streams):
                    while s.backlog() > 0:
                        delivered[i].append(next(s))
            for i, s in enumerate(streams):
                delivered[i].extend(s.drain())
            wall = time.perf_counter() - t0
        finally:
            if observer is not None:
                chaos.remove_fault_observer(observer)
            os.environ.pop(chaos.ENV_VAR, None)
            chaos._reset()

        tpots = sorted(
            (s.last_token_t - s.first_token_t) / (s.seen - 1)
            for s in streams
            if s.last_token_t is not None and s.seen > 1
        )
        row = {
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(
                sum(len(t) for t in delivered) / wall, 2
            ),
            "tpot_s_p50": (
                round(float(np.quantile(tpots, 0.5)), 6) if tpots else None
            ),
        }
        if observed:
            ts = eng.timeseries.status()
            row["timeseries_series"] = ts["series"]
            row["timeseries_memory_bytes"] = ts["memory_bytes"]
            row["alerts"] = eng.regress.alerts
            if drill:
                row["injected_at_regress_step"] = injected.get("regress_step")
                row["injected_stratum_n"] = injected.get("stratum_n", {})
                row["events"] = list(eng.regress.events)
                row["attributed_phase"] = eng.regress.last_attribution
            if eng.roofline is not None:
                rep = eng.roofline.report()
                row["roofline"] = {
                    "dominant_bound": rep["dominant_bound"],
                    "achieved_fraction": rep["achieved_fraction"],
                    "step_floor_s": rep["step_floor_s"],
                }
        eng.close()
        return row, delivered

    row_off, tokens_off = run_pass(False)
    row_on, tokens_on = run_pass(True)
    row_drill, tokens_drill = run_pass(True, drill=True)

    # Detection latency two ways. Raw engine steps from injection to fire
    # tell the operator how long the slowdown ran; but under an open-loop
    # arrival ramp most of those steps mix prefill (invisible to the
    # stratified detector by design), so the BUDGET is asserted in
    # comparable samples: pure-decode steps of the firing stratum between
    # injection and fire (1 = fired on the very first regressed sample a
    # fresh stratum could even compare).
    injected_step = row_drill.get("injected_at_regress_step")
    events = row_drill.get("events") or []
    fire_event = next(
        (e for e in events
         if injected_step is not None and e["step"] >= injected_step),
        None,
    )
    detection_latency = (
        fire_event["step"] - injected_step + 1
        if fire_event is not None else None
    )
    detection_latency_samples = None
    if fire_event is not None and "stratum_samples" in fire_event:
        pre = row_drill.get("injected_stratum_n", {}).get(
            fire_event["decode_rows"], 0
        )
        detection_latency_samples = fire_event["stratum_samples"] - pre

    # Median-over-interleaved-passes overhead, exactly like the
    # obs/disttrace rows; parity + drill rows stay pinned to the first
    # passes above.
    tpots_off = [row_off["tpot_s_p50"]]
    tpots_on = [row_on["tpot_s_p50"]]
    for _ in range(2):
        r_off_x, _ = run_pass(False)
        r_on_x, _ = run_pass(True)
        tpots_off.append(r_off_x["tpot_s_p50"])
        tpots_on.append(r_on_x["tpot_s_p50"])
    tpots_off = sorted(t for t in tpots_off if t)
    tpots_on = sorted(t for t in tpots_on if t)
    tpot_off = tpots_off[len(tpots_off) // 2] if tpots_off else None
    tpot_on = tpots_on[len(tpots_on) // 2] if tpots_on else None

    pw_doc = {
        "n_requests": n_requests,
        "arrival_rate_hz": arrival_rate_hz,
        # Acceptance row 1: the observatory must not change a token —
        # and neither may the injected stall (a sleep is not a sample).
        "tokens_bitwise_identical": tokens_on == tokens_off,
        "tokens_bitwise_identical_under_stall": tokens_drill == tokens_off,
        # Acceptance row 2: the seeded drill.
        "stall_phase": stall_phase,
        "stall_s": stall_s,
        "stall_after_steps": stall_after_steps,
        "detector_fired": fire_event is not None,
        "detection_latency_steps": detection_latency,
        "detection_latency_decode_samples": detection_latency_samples,
        "detect_budget_steps": detect_budget_steps,
        # Budget in comparable samples: prefill-mixed ramp steps are
        # invisible to the stratified detector by design, so they can't
        # count against it (raw step latency is still reported above).
        "detection_within_budget": (
            detection_latency_samples is not None
            and detection_latency_samples <= detect_budget_steps
        ),
        "attributed_phase": (
            fire_event["attributed_phase"] if fire_event else None
        ),
        "attribution_correct": bool(
            fire_event and fire_event["attributed_phase"] == stall_phase
        ),
        # Acceptance row 3: quiet when nothing is wrong, bounded memory.
        "false_positive_alerts_clean_pass": row_on["alerts"],
        "timeseries_series": row_on["timeseries_series"],
        "timeseries_memory_bytes": row_on["timeseries_memory_bytes"],
        "roofline": row_on.get("roofline"),
        # Steady-state cost (same caveat as the obs/disttrace rows: an
        # absolute per-step Python price reads large against a ~1.5ms
        # CPU TPOT, small against real accelerator steps).
        "tokens_per_sec_off": row_off["tokens_per_sec"],
        "tokens_per_sec_on": row_on["tokens_per_sec"],
        "tpot_s_p50_perfwatch_off": tpot_off,
        "tpot_s_p50_perfwatch_on": tpot_on,
        "tpot_p50_perfwatch_overhead": (
            round(tpot_on / tpot_off - 1.0, 4)
            if tpot_off and tpot_on else None
        ),
        "tpot_perfwatch_overhead_abs_s": (
            round(tpot_on - tpot_off, 6)
            if tpot_off and tpot_on else None
        ),
        "tpot_p50_perfwatch_passes": len(tpots_on),
    }

    # Merge next to the obs/fleet/frontdoor/disttrace sections;
    # bench_history records it un-gated.
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = {
            "mode": "serving_perfwatch_only",
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind,
            "rows": [],
        }
    doc["perfwatch"] = pw_doc
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return pw_doc


def bench_hostkv(
    n_requests: int = 30,
    arrival_rate_hz: float = 30.0,
    seed: int = 0,
    n_prefixes: int = 10,
    prefix_len: int = 48,
    device_pages: int = 41,
    host_pages: int = 128,
):
    """Hierarchical-KV benchmark: a Poisson workload whose warm-prefix
    working set EXCEEDS the device page pool, run twice over identical
    prompts and arrival times — host tier off, then on.

    ``n_prefixes`` distinct system prefixes of ``prefix_len`` tokens are
    reused round-robin across requests; the prefix working set
    (``n_prefixes * prefix_len / page_size`` pages) deliberately
    overflows the device pool, so by the time a prefix recurs its pages
    have been evicted. Tier-off pays full re-prefill; tier-on recovers
    them by h2d fetch from the spilled host copies.

    The ``hostkv`` section of ``BENCH_SERVING.json`` records the
    acceptance rows: bitwise greedy-token parity tier-on vs -off,
    strictly higher total cache hit rate and lower TTFT p50 with the
    tier on, spill/fetch byte counters matching the XLA transfer
    ledger's tagged d2h/h2d rows EXACTLY (double-entry bookkeeping),
    and zero leaked device or host pages at close()."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.serving import (
        InferenceEngine,
        SamplingParams,
    )
    from distributed_pytorch_tpu.serving.admission import ServingMetrics

    on_cpu = jax.devices()[0].platform == "cpu"
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, d_ff=256,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    page_size = 8
    # One fixed workload for both passes: request j reuses prefix
    # j % n_prefixes, so every prefix recurs only after the other
    # n_prefixes-1 prefixes' traffic has churned the device pool.
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(0, 256, prefix_len).tolist() for _ in range(n_prefixes)
    ]
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        prefixes[j % n_prefixes]
        + rng.integers(0, 256, int(rng.integers(2, 9))).tolist()
        for j in range(n_requests)
    ]
    warm_rng = np.random.default_rng(seed + 1)

    def run_pass(tier_pages):
        eng = InferenceEngine(
            model, params, max_slots=4, max_seq_len=64,
            page_size=page_size, num_pages=device_pages, token_budget=64,
            max_prefill_chunk=32, max_queue=n_requests, xla_ledger=True,
            host_pages=tier_pages,
        )
        # Compile warm-up off the clock (same ladder as bench_serving).
        chunk = 1
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            chunk *= 2
        if eng.hostkv is not None:
            # Warm the spill gather and the batched-fetch buckets too:
            # page 0 is the NULL page, so gathering it and writing it
            # back (at any bucket width) is content-neutral.
            fetch = eng._fetch_pages
            per_pool = isinstance(fetch, dict)
            null_chunk = {
                name: jax.tree_util.tree_map(np.asarray, chunk_arr)
                for name, chunk_arr in eng._gather_page(0).items()
            }
            for bucket in (1, 2, 4, 8, 16):
                dsts = jnp.zeros((bucket,), jnp.int32)
                for name, chunk_arr in null_chunk.items():
                    stacked = jax.tree_util.tree_map(
                        lambda x: np.broadcast_to(
                            x, (bucket,) + x.shape
                        ).copy(),
                        chunk_arr,
                    )
                    run = fetch[name] if per_pool else fetch
                    eng.pools[name] = run(eng.pools[name], stacked, dsts)
        # Reset accounting: measure the workload, not the warm-up. The
        # tier/ledger byte counters are NOT reset — they move in lockstep
        # from construction, and the cross-check is over lifetime totals.
        eng.metrics = ServingMetrics(speculative=eng.speculative)
        eng.admission.accepted = 0
        eng.admission.cached_tokens_admitted = 0
        pc = eng.prefix_cache
        pc.lookups = pc.hits = 0
        pc.tokens_hit = pc.tokens_missed = pc.tokens_hit_host = 0

        start = time.perf_counter()
        submitted = 0
        ids = []
        while submitted < n_requests or eng.scheduler.has_work:
            now = time.perf_counter() - start
            while submitted < n_requests and arrivals[submitted] <= now:
                ids.append(
                    eng.submit(
                        prompts[submitted], SamplingParams(max_new_tokens=8)
                    )
                )
                submitted += 1
            if eng.scheduler.has_work or eng._inflight is not None:
                eng.step()
            elif submitted < n_requests:
                time.sleep(min(arrivals[submitted] - now, 0.01))
        wall = time.perf_counter() - start
        assert all(eng.poll(r).finished for r in ids)
        stats = eng.stats()
        tokens = [eng.poll(r).generated for r in ids]
        leaked = stats["pages_allocated"]
        eng.allocator.check_invariants()
        # close() drains trailing spills into the tagged ledger row and
        # asserts BOTH tiers quiescent — reaching the return statement is
        # the zero-leak acceptance.
        eng.close()
        row = {
            "host_pages": tier_pages,
            "wall_s": round(wall, 4),
            "stats": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in stats.items()
            },
        }
        if eng.hostkv is not None:
            md = eng.xla.metadata()
            row["ledger_spill_bytes"] = md["bytes_d2h_by_tag"].get(
                "hostkv_spill", 0
            )
            row["ledger_fetch_bytes"] = md["bytes_h2d_by_tag"].get(
                "hostkv_fetch", 0
            )
            row["tier"] = eng.hostkv.counters()
        return row, tokens, leaked

    row_off, tokens_off, leaked_off = run_pass(None)
    row_on, tokens_on, leaked_on = run_pass(host_pages)
    off, on = row_off["stats"], row_on["stats"]
    tier = row_on["tier"]

    hk_doc = {
        "workload": (
            f"hostkv_lm64_poisson{arrival_rate_hz:g}hz_n{n_requests}"
            f"_{n_prefixes}x{prefix_len}prefix"
        ),
        "n_requests": n_requests,
        "arrival_rate_hz": arrival_rate_hz,
        "n_prefixes": n_prefixes,
        "prefix_len": prefix_len,
        "device_pages": device_pages - 1,  # page 0 is the NULL page
        "prefix_working_set_pages": n_prefixes * (prefix_len // page_size),
        "host_pages": host_pages,
        "rows": [row_off, row_on],
        # Acceptance row 1: the tier must not change a token.
        "tokens_bitwise_identical": tokens_on == tokens_off,
        # Acceptance row 2: strictly better cache economics under a
        # working set the device pool cannot hold.
        "prefix_hit_rate_off": off.get("prefix_hit_rate_total", 0.0),
        "prefix_hit_rate_on": on.get("prefix_hit_rate_total", 0.0),
        "hit_rate_strictly_higher": (
            on.get("prefix_hit_rate_total", 0.0)
            > off.get("prefix_hit_rate_total", 0.0)
        ),
        "host_hit_tokens": on.get("prefix_tokens_hit_host", 0),
        "ttft_s_p50_off": off.get("ttft_s_p50"),
        "ttft_s_p50_on": on.get("ttft_s_p50"),
        "ttft_p50_speedup_hostkv": (
            round(off["ttft_s_p50"] / on["ttft_s_p50"], 4)
            if on.get("ttft_s_p50") else None
        ),
        "ttft_p50_lower_with_tier": bool(
            off.get("ttft_s_p50") and on.get("ttft_s_p50")
            and on["ttft_s_p50"] < off["ttft_s_p50"]
        ),
        # Acceptance row 3: double-entry byte bookkeeping, exact.
        "hostkv_spills": tier["hostkv_spills"],
        "hostkv_fetches": tier["hostkv_fetches"],
        "hostkv_spill_bytes": tier["hostkv_spill_bytes"],
        "hostkv_fetch_bytes": tier["hostkv_fetch_bytes"],
        "spill_bytes_match_ledger": (
            tier["hostkv_spill_bytes"] == row_on["ledger_spill_bytes"]
        ),
        "fetch_bytes_match_ledger": (
            tier["hostkv_fetch_bytes"] == row_on["ledger_fetch_bytes"]
        ),
        # Acceptance row 4: nothing leaked on either tier (close()
        # additionally asserted host-tier quiescence in-process).
        "device_pages_leaked": leaked_off + leaked_on,
        "host_pages_pinned_at_close": 0,
        "tokens_per_sec_off": off.get("tokens_per_sec"),
        "tokens_per_sec_on": on.get("tokens_per_sec"),
    }

    # Merge next to the obs/fleet/frontdoor/disttrace/perfwatch sections;
    # bench_history records it un-gated.
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = {
            "mode": "serving_hostkv_only",
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind,
            "rows": [],
        }
    doc["hostkv"] = hk_doc
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return hk_doc


def bench_paged_kernel(
    n_requests: int = 24,
    arrival_rate_hz: float = 40.0,
    seed: int = 0,
    max_new_tokens: int = 24,
):
    """Paged-attention kernel benchmark: the SAME decode-heavy Poisson
    workload run three times — block-table gather (kernel off), the fused
    ``ops/paged_attention`` read path (``paged_kernel=True``: Pallas on
    TPU, its XLA reference elsewhere), and the fused path over
    int8-quantized KV pages.

    The ``paged_kernel`` section of ``BENCH_SERVING.json`` records the
    acceptance rows: greedy tokens on the fp path (gather vs kernel),
    tokens/sec and TPOT p50/p95 per pass, the roofline
    ``achieved_fraction`` before/after with the decode program row tagged
    ``fused_kernel`` by ``obs/roofline.py``, and the per-pool KV bytes
    showing int8 cutting the streamed pool in half (int8 payload + the
    small float32 scale pool)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.serving import (
        InferenceEngine,
        SamplingParams,
    )
    from distributed_pytorch_tpu.serving.admission import ServingMetrics

    on_cpu = jax.devices()[0].platform == "cpu"
    # GQA (8 query / 4 KV heads) so the kernel's grouped-head mapping is
    # on the measured path, not just in unit tests.
    model = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=256, dtype=jnp.float32 if on_cpu else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_hz, n_requests))
    prompts = [
        rng.integers(0, 256, int(rng.integers(4, 17))).tolist()
        for _ in range(n_requests)
    ]
    warm_rng = np.random.default_rng(seed + 1)

    def run_pass(label, **ekw):
        eng = InferenceEngine(
            model, params, max_slots=4, max_seq_len=64, page_size=8,
            token_budget=64, max_prefill_chunk=32, max_queue=n_requests,
            xla_ledger=True, timeseries=True, **ekw,
        )
        chunk = 1
        while chunk <= 32:
            warm = eng.submit(
                warm_rng.integers(0, 256, chunk + 1).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            eng.run()
            assert eng.poll(warm).finished
            chunk *= 2
        eng.metrics = ServingMetrics(speculative=eng.speculative)

        start = time.perf_counter()
        submitted = 0
        ids = []
        while submitted < n_requests or eng.scheduler.has_work:
            now = time.perf_counter() - start
            while submitted < n_requests and arrivals[submitted] <= now:
                ids.append(
                    eng.submit(
                        prompts[submitted],
                        SamplingParams(max_new_tokens=max_new_tokens),
                    )
                )
                submitted += 1
            if eng.scheduler.has_work or eng._inflight is not None:
                eng.step()
            elif submitted < n_requests:
                time.sleep(min(arrivals[submitted] - now, 0.01))
        wall = time.perf_counter() - start
        assert all(eng.poll(r).finished for r in ids)
        stats = eng.stats()
        tokens = [eng.poll(r).generated for r in ids]
        roof = eng.roofline.report()
        decode_rows = [
            r for r in roof["programs"]
            if r["name"].startswith("decode_step")
        ]
        # Per-token streamed KV bytes: the target pool the decode program
        # re-reads every step (int8 pays int8 payload + f32 scales).
        pool_bytes = sum(
            leaf.nbytes
            for leaf in jax.tree_util.tree_leaves(eng.pools["target"])
        )
        leaked = stats["pages_allocated"]
        eng.allocator.check_invariants()
        eng.close()
        return {
            "pass": label,
            "wall_s": round(wall, 4),
            "tokens_per_sec": stats.get("tokens_per_sec"),
            "tpot_s_p50": stats.get("tpot_s_p50"),
            "tpot_s_p95": stats.get("tpot_s_p95"),
            "kv_pool_bytes": int(pool_bytes),
            "achieved_fraction": roof["achieved_fraction"],
            "dominant_bound": roof["dominant_bound"],
            "decode_programs": [
                {
                    "name": r["name"],
                    "fused_kernel": r["fused_kernel"],
                    "hbm_bytes": r["hbm_bytes"],
                    "bound": r["bound"],
                    "floor_s": r["floor_s"],
                }
                for r in decode_rows
            ],
        }, tokens, leaked

    row_gather, tok_gather, leak_g = run_pass("gather")
    row_kernel, tok_kernel, leak_k = run_pass("kernel", paged_kernel=True)
    row_int8, tok_int8, leak_q = run_pass(
        "kernel_int8", paged_kernel=True, kv_quant="int8"
    )

    def speedup(a, b):
        return round(a / b, 4) if a and b else None

    pk_doc = {
        "workload": (
            f"pagedkernel_lm64gqa_poisson{arrival_rate_hz:g}hz_"
            f"n{n_requests}_new{max_new_tokens}"
        ),
        "n_requests": n_requests,
        "arrival_rate_hz": arrival_rate_hz,
        "max_new_tokens": max_new_tokens,
        "rows": [row_gather, row_kernel, row_int8],
        # Acceptance row 1: fp-path greedy parity, gather vs kernel. On
        # non-TPU backends paged_kernel=True resolves to the XLA
        # reference, which reproduces the gather math bitwise; on TPU the
        # Pallas kernel's online softmax may reorder float accumulation.
        "tokens_bitwise_identical_fp": tok_kernel == tok_gather,
        "tokens_bitwise_identical_int8": tok_int8 == tok_gather,
        # Acceptance row 2: the roofline attributes the delta — the
        # kernel passes run a program tagged fused_kernel.
        "achieved_fraction_gather": row_gather["achieved_fraction"],
        "achieved_fraction_kernel": row_kernel["achieved_fraction"],
        "achieved_fraction_int8": row_int8["achieved_fraction"],
        "fused_program_present": any(
            r["fused_kernel"] for r in row_kernel["decode_programs"]
        ),
        # Acceptance row 3: int8 shrinks the streamed KV pool to payload/
        # itemsize plus the f32 scale pool (one scale per D-row): 0.375 of
        # an fp32 pool at D=8, 0.5625 of a bf16 pool.
        "kv_pool_bytes_fp": row_gather["kv_pool_bytes"],
        "kv_pool_bytes_int8": row_int8["kv_pool_bytes"],
        "kv_pool_ratio_int8": round(
            row_int8["kv_pool_bytes"] / row_gather["kv_pool_bytes"], 4
        ),
        "tpot_p50_speedup_kernel": speedup(
            row_gather["tpot_s_p50"], row_kernel["tpot_s_p50"]
        ),
        "tpot_p50_speedup_int8": speedup(
            row_gather["tpot_s_p50"], row_int8["tpot_s_p50"]
        ),
        "pages_leaked": leak_g + leak_k + leak_q,
    }

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVING.json"
    )
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = {
            "mode": "serving_paged_kernel_only",
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind,
            "rows": [],
        }
    doc["paged_kernel"] = pk_doc
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return pk_doc


def attach_mfu(result: dict, peak: float) -> dict:
    per_chip = result["flops_per_step"] * result["steps_per_sec"] / result["n_chips"]
    result["model_tflops_per_sec_per_chip"] = round(per_chip / 1e12, 2)
    result["mfu"] = round(per_chip / peak, 4)
    result["steps_per_sec"] = round(result["steps_per_sec"], 4)
    for k in ("images_per_sec", "tokens_per_sec"):
        if k in result:
            result[k] = round(result[k], 1)
    return result


def init_backend_with_retry(
    retries: int = 3, base_delay: float = 10.0, attempt_timeout: float = 180.0
):
    """Initialize the JAX backend with bounded retry + backoff + watchdog.

    The round-3 driver run lost its entire perf record because one wedged
    tunnel turned ``jax.devices()`` into a 40-line traceback — and a wedged
    relay can also make it HANG forever (observed: 2.5h+ with zero CPU).
    So each attempt runs in a daemon thread under ``attempt_timeout``; a
    hang is converted into a reportable failure instead of an eternal
    driver stall. Transient unavailability (tunnel reconnect, TPU runtime
    restart) gets a few patient retries; persistent failure must surface
    as a structured result, not a stack trace. Returns ``(device, None)``
    on success or ``(None, last_error_string)`` after exhausting retries.
    A timed-out attempt leaves its thread parked inside the C++ client —
    callers should exit via ``os._exit`` after printing, which ``main``
    does.
    """
    import queue
    import threading

    import jax

    last_err = None
    for attempt in range(retries):
        result_q: "queue.Queue" = queue.Queue()

        def probe(q=result_q):
            try:
                q.put(("ok", jax.devices()[0]))
            except Exception as e:  # RuntimeError / JaxRuntimeError
                q.put(("err", f"{type(e).__name__}: {e}"))

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        try:
            kind, payload = result_q.get(timeout=attempt_timeout)
        except queue.Empty:
            kind, payload = "err", (
                f"TimeoutError: backend init hung > {attempt_timeout:.0f}s "
                "(wedged tunnel?)"
            )
            # The probe thread is stuck inside backend init; a fresh attempt
            # in this process would just join the same wedged dial. Stop
            # retrying and report.
            return None, payload
        if kind == "ok":
            return payload, None
        last_err = payload
        # Drop the cached failed-backend state so the next attempt
        # actually re-dials instead of replaying the cached error. The
        # private API may move between jax versions — say so when it does,
        # because without the clear every retry silently replays the cached
        # error and the loop only pretends to retry (ADVICE r04).
        try:
            from jax._src import xla_bridge as _xb

            _xb._clear_backends()
        except Exception as clear_err:
            print(
                "# WARNING: could not clear cached jax backends "
                f"({type(clear_err).__name__}: {clear_err}); retries may "
                "replay the same cached init error",
                flush=True,
            )
        if attempt < retries - 1:
            delay = base_delay * (2**attempt)
            print(
                f"# backend init failed (attempt {attempt + 1}/{retries}), "
                f"retrying in {delay:.0f}s: {last_err.splitlines()[0]}",
                flush=True,
            )
            time.sleep(delay)
    return None, last_err


def emit_failure(
    error: str,
    detail: str,
    stage: str,
    metric: str = "resnet50_bf16_train_steps_per_sec",
    unit: str = "steps/s",
) -> None:
    """One parseable JSON line for the driver — never a bare traceback.
    ``metric`` names the measurement that FAILED so records keyed by metric
    name don't log a spurious headline failure for e.g. a --scaling run."""
    print(
        json.dumps(
            {
                "metric": metric,
                "value": None,
                "unit": unit,
                "vs_baseline": None,
                "error": error,
                "stage": stage,
                "detail": detail.splitlines()[-1][:400] if detail else "",
            }
        )
    )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--matrix", action="store_true",
        help="run the full workload matrix and write BENCH_MATRIX.json",
    )
    parser.add_argument(
        "--scaling", action="store_true",
        help="measure DP scaling efficiency over all local devices and "
        "write BENCH_SCALING.json",
    )
    parser.add_argument(
        "--window_sweep", action="store_true",
        help="measure LM step time vs sliding-window size at T=8192 "
        "(the flash kernel skips out-of-band tiles; compute should fall "
        "toward O(T x W)) and write BENCH_WINDOW.json",
    )
    parser.add_argument(
        "--serving", action="store_true",
        help="benchmark the continuous-batching inference engine under "
        "Poisson arrivals (throughput + TTFT/TPOT/e2e percentiles, "
        "prefix-caching off-vs-on rows) and write BENCH_SERVING.json",
    )
    parser.add_argument(
        "--fleet", type=int, default=0, metavar="N",
        help="benchmark the N-replica routed fleet under the --serving "
        "Poisson workload with a seeded mid-run replica kill (aggregate "
        "tok/s, dead-replica detection latency, failover TTFT spike, "
        "greedy-parity vs one uninterrupted engine); merges a 'fleet' "
        "section into BENCH_SERVING.json",
    )
    parser.add_argument(
        "--procs", action="store_true",
        help="with --fleet N: run the replicas as worker SUBPROCESSES "
        "behind ProcessReplicaClient — the kill becomes a real SIGKILL "
        "and every metric rides the localhost control plane; merges a "
        "'fleet_procs' section into BENCH_SERVING.json and appends a "
        "BENCH_HISTORY.jsonl row (un-gated: the first row seeds the "
        "cross-process baseline)",
    )
    parser.add_argument(
        "--kill-router", action="store_true",
        help="with --fleet N: kill the ROUTER instead of a replica — a "
        "seeded raise-mode kill_router fault abandons the router object "
        "mid-run and FleetRouter.recover rebuilds a successor from the "
        "write-ahead journal (recovery wall time, resume-TTFT spike, "
        "re_adopted/re_admitted counts, greedy parity across the crash); "
        "merges a 'fleet_router' section into BENCH_SERVING.json and "
        "appends an un-gated BENCH_HISTORY.jsonl row; pair with --procs "
        "for registry-tracked worker subprocesses re-adopted over the "
        "localhost control plane",
    )
    parser.add_argument(
        "--frontdoor", action="store_true",
        help="benchmark the multi-tenant streaming front door under a "
        "mixed-tenant Poisson workload (streamed-vs-polled bitwise "
        "parity, streaming overhead, per-tenant TTFT/TPOT + SLO "
        "compliance); merges a 'frontdoor' section into "
        "BENCH_SERVING.json and appends a BENCH_HISTORY.jsonl row",
    )
    parser.add_argument(
        "--disttrace", action="store_true",
        help="benchmark the fleet-tracing stack: the --frontdoor Poisson "
        "workload with door+engine tracers, head+tail sampler, and armed "
        "recompile sentinel all on vs all off (bitwise token parity, "
        "TPOT p50 overhead as a median over interleaved passes, "
        "waterfall sum integrity over every trace); merges a 'disttrace' "
        "section into BENCH_SERVING.json and appends a BENCH_HISTORY"
        ".jsonl row",
    )
    parser.add_argument(
        "--perfwatch", action="store_true",
        help="benchmark the performance observatory: the --frontdoor "
        "Poisson workload with the TSDB + roofline + regression detector "
        "off vs on (bitwise token parity, TPOT p50 overhead over "
        "interleaved passes) plus a seeded slow_program chaos drill "
        "asserting the CUSUM fires within budget and blames the stalled "
        "phase; merges a 'perfwatch' section into BENCH_SERVING.json and "
        "appends a BENCH_HISTORY.jsonl row",
    )
    parser.add_argument(
        "--hostkv", action="store_true",
        help="benchmark the hierarchical KV host tier: a Poisson workload "
        "whose prefix working set exceeds the device page pool, host tier "
        "off vs on over identical prompts (bitwise token parity, cache "
        "hit rate and TTFT p50 deltas, spill/fetch bytes cross-checked "
        "against the XLA transfer ledger); merges a 'hostkv' section into "
        "BENCH_SERVING.json and appends a BENCH_HISTORY.jsonl row",
    )
    parser.add_argument(
        "--paged-kernel", action="store_true", dest="paged_kernel",
        help="benchmark the fused paged-attention decode path: the same "
        "decode-heavy Poisson workload with the block-table gather, the "
        "ops/paged_attention kernel, and the kernel over int8 KV pages "
        "(fp greedy parity, TPOT p50/p95, roofline achieved_fraction "
        "before/after with the fused program tagged, int8 pool byte "
        "ratio); merges a 'paged_kernel' section into BENCH_SERVING.json "
        "and appends a BENCH_HISTORY.jsonl row",
    )
    parser.add_argument(
        "--shared-prefix-len", type=int, default=24, metavar="L",
        help="length of the system-prompt prefix every --serving request "
        "shares (0 = fully distinct prompts)",
    )
    parser.add_argument(
        "--speculative", action="store_true",
        help="add a speculative-decoding pass to --serving (identical "
        "workload, spec off-vs-on rows: acceptance rate + TPOT p50/p95 "
        "delta)",
    )
    parser.add_argument(
        "--gamma", type=int, default=4, metavar="G",
        help="speculative chunk width for --speculative (draft proposals "
        "per verify round)",
    )
    parser.add_argument(
        "--mesh", type=str, default="", metavar="SHAPES",
        help="comma-separated DxM serving-mesh shapes (e.g. 1x1,1x8,2x4) "
        "to additionally run the --serving workload on; appends per-shape "
        "tokens/sec + TPOT rows to BENCH_SERVING.json (pair with "
        "--fake_devices 8 on a single-device rig)",
    )
    parser.add_argument(
        "--fake_devices", type=int, default=0, metavar="N",
        help="run on N virtual CPU devices instead of the real backend "
        "(the --scaling rig until a multi-chip slice exists)",
    )
    args = parser.parse_args()

    if args.fake_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.fake_devices}"
        ).strip()
        import jax

        # Env-var JAX_PLATFORMS is overridden by tunnel platform plugins
        # (which then dial a possibly-dead relay); the config update after
        # import is authoritative.
        jax.config.update("jax_platforms", "cpu")

    if sum(
        (args.scaling, args.window_sweep, args.serving, bool(args.fleet),
         args.frontdoor, args.disttrace, args.perfwatch, args.hostkv,
         args.paged_kernel)
    ) > 1:
        # All are exclusive whole-run modes; silently preferring one would
        # burn a chip window on the wrong measurement (the queue scripts
        # run these as separate precious steps).
        parser.error("--scaling, --window_sweep, --serving, --fleet, "
                     "--frontdoor, --disttrace, --perfwatch, --hostkv "
                     "and --paged-kernel are exclusive modes; run them as "
                     "separate invocations")
    scaling_metric = "dp_weak_scaling_efficiency"
    if args.scaling:
        metric, unit = scaling_metric, "ratio_vs_1dev"
    elif args.window_sweep:
        metric, unit = "window1024_speedup_vs_full_t8192", "ratio"
    elif args.serving:
        metric, unit = "serving_throughput_tok_per_sec", "tok/s"
    elif args.fleet:
        metric, unit = "fleet_aggregate_tok_per_sec", "tok/s"
    elif args.frontdoor:
        metric, unit = "frontdoor_tok_per_sec", "tok/s"
    elif args.disttrace:
        metric, unit = "disttrace_tpot_p50_overhead", "ratio"
    elif args.perfwatch:
        metric, unit = "perfwatch_tpot_p50_overhead", "ratio"
    elif args.hostkv:
        metric, unit = "hostkv_ttft_p50_speedup", "ratio"
    elif args.paged_kernel:
        metric, unit = "paged_kernel_tpot_p50_speedup", "ratio"
    else:
        metric, unit = "resnet50_bf16_train_steps_per_sec", "steps/s"

    dev, err = init_backend_with_retry()
    if dev is None:
        emit_failure(
            "backend_unavailable", err or "", stage="init",
            metric=metric, unit=unit,
        )
        # A timed-out probe thread may still be parked inside the C++
        # client; don't let interpreter teardown hang on it.
        import sys

        sys.stdout.flush()
        os._exit(0)
    peak = peak_flops_per_chip(dev)

    try:
        run_benches(args, dev, peak)
    except Exception as e:
        import traceback

        traceback.print_exc()
        emit_failure(
            "bench_failed", f"{type(e).__name__}: {e}", stage="measure",
            metric=metric, unit=unit,
        )


def run_benches(args, dev, peak):
    if args.scaling:
        # Exclusive mode (one JSON line per invocation): measure DP weak
        # scaling over every local device and stop — the 8-virtual-CPU rig
        # runs this without paying for the ResNet headline on CPU.
        scaling = bench_scaling()
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_SCALING.json"
        )
        with open(path, "w") as f:
            json.dump(scaling, f, indent=1)
        last = scaling["rows"][-1]
        ratio = last[scaling["efficiency_key"]]
        print(
            json.dumps(
                {
                    # Same metric name as the failure path emits, so a
                    # driver keying records by metric associates both.
                    "metric": "dp_weak_scaling_efficiency",
                    "value": ratio,
                    "unit": "ratio_vs_1dev",
                    "vs_baseline": ratio,
                    "n_devices": last["n_devices"],
                    "awaiting_hardware": scaling["awaiting_hardware"],
                    "efficiency_meaningful": scaling["efficiency_meaningful"],
                }
            )
        )
        return

    if args.serving:
        # Exclusive mode: the continuous-batching engine under open-loop
        # Poisson load, prefix caching off then on over the identical
        # workload. One JSON line (the caching-on row is the headline);
        # full before/after percentiles in the file.
        result = bench_serving(
            shared_prefix_len=args.shared_prefix_len,
            speculative=args.speculative, gamma=args.gamma,
            mesh_shapes=args.mesh,
        )
        s = result["rows"][1]["stats"]
        line = {
            "metric": "serving_throughput_tok_per_sec",
            "value": round(s["tokens_per_sec"], 2),
            "unit": "tok/s",
            "vs_baseline": 1.0,
            "requests_completed": s["requests_completed"],
            "ttft_s_p50": s["ttft_s_p50"],
            "ttft_s_p95": s["ttft_s_p95"],
            "tpot_s_p50": s["tpot_s_p50"],
            "e2e_s_p95": s["e2e_s_p95"],
            "preemptions": s["preemptions"],
            "prefix_hit_rate": result["prefix_hit_rate"],
            "ttft_p50_speedup_cached": result[
                "ttft_p50_speedup_cached"
            ],
        }
        if args.speculative:
            line["spec_acceptance_rate"] = result["spec_acceptance_rate"]
            line["tpot_p50_speedup_spec"] = result["tpot_p50_speedup_spec"]
        if args.mesh:
            line["mesh_shapes"] = [
                r["mesh"] for r in result["mesh_rows"]
            ]
            line["mesh_greedy_parity"] = result["mesh_greedy_parity"]
        print(json.dumps(line))
        return

    if args.fleet and args.kill_router:
        # Exclusive mode: the durable-control-plane drill — the ROUTER is
        # the victim. The headline is recovery wall time; the acceptance
        # row is greedy token parity with one uninterrupted engine across
        # the router crash.
        fr = bench_fleet_router(
            n_replicas=args.fleet,
            shared_prefix_len=args.shared_prefix_len,
            procs=args.procs,
        )
        print(
            json.dumps(
                {
                    "metric": "fleet_router_recovery_s",
                    "value": fr["recovery_s"],
                    "unit": "s",
                    "vs_baseline": 1.0,
                    "transport": fr["transport"],
                    "n_replicas": fr["n_replicas"],
                    "re_adopted": fr["re_adopted"],
                    "re_admitted": fr["re_admitted"],
                    "lost": fr["lost"],
                    "resume_ttft_s_p50": fr["resume_ttft_s_p50"],
                    "resume_ttft_spike_x": fr["resume_ttft_spike_x"],
                    "greedy_tokens_match_single_engine": fr[
                        "greedy_tokens_match_single_engine"
                    ],
                    "pages_leaked": fr["pages_leaked"],
                }
            )
        )
        # The mode's contract includes the history row (un-gated — the
        # first row seeds the router-recovery baseline).
        import importlib.util

        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "bench_history",
            os.path.join(here, "tools", "bench_history.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main([
            "append",
            "--bench", os.path.join(here, "BENCH_SERVING.json"),
            "--history", os.path.join(here, "BENCH_HISTORY.jsonl"),
        ])
        return

    if args.fleet:
        # Exclusive mode: the routed replica fleet under the same Poisson
        # workload, with a seeded kill_replica fault landing on the
        # affinity-loaded replica mid-decode. The headline is aggregate
        # fleet tok/s; the acceptance row is greedy token parity with one
        # uninterrupted engine despite the kill.
        fleet = bench_fleet(
            n_replicas=args.fleet,
            shared_prefix_len=args.shared_prefix_len,
            procs=args.procs,
        )
        print(
            json.dumps(
                {
                    "metric": (
                        "fleet_procs_aggregate_tok_per_sec"
                        if args.procs else "fleet_aggregate_tok_per_sec"
                    ),
                    "value": fleet["aggregate_tokens_per_sec"],
                    "unit": "tok/s",
                    "vs_baseline": 1.0,
                    "n_replicas": fleet["n_replicas"],
                    "victim": fleet["victim"],
                    "requests_failed_over": fleet["requests_failed_over"],
                    "detection_latency_s": fleet["detection_latency_s"],
                    "failover_ttft_s_p50": fleet["failover_ttft_s_p50"],
                    "failover_ttft_spike_x": fleet["failover_ttft_spike_x"],
                    "greedy_tokens_match_single_engine": fleet[
                        "greedy_tokens_match_single_engine"
                    ],
                    "pages_leaked_on_survivors": fleet[
                        "pages_leaked_on_survivors"
                    ],
                }
            )
        )
        if args.procs:
            # The --procs contract includes the history row (un-gated —
            # the first row seeds the cross-process baseline): load the
            # gate module by path (tools/ is not a package) and append
            # the fresh BENCH_SERVING.json to BENCH_HISTORY.jsonl.
            import importlib.util

            here = os.path.dirname(os.path.abspath(__file__))
            spec = importlib.util.spec_from_file_location(
                "bench_history",
                os.path.join(here, "tools", "bench_history.py"),
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            mod.main([
                "append",
                "--bench", os.path.join(here, "BENCH_SERVING.json"),
                "--history", os.path.join(here, "BENCH_HISTORY.jsonl"),
            ])
        return

    if args.frontdoor:
        # Exclusive mode: the multi-tenant streaming front door over a
        # mixed gold/bronze Poisson workload. The headline is streamed
        # tok/s; the acceptance row is bitwise streamed-vs-polled parity.
        fd = bench_frontdoor()
        print(
            json.dumps(
                {
                    "metric": "frontdoor_tok_per_sec",
                    "value": fd["tokens_per_sec"],
                    "unit": "tok/s",
                    "vs_baseline": 1.0,
                    "streaming_overhead_x": fd["streaming_overhead_x"],
                    "streamed_tokens_bitwise_identical_polled": fd[
                        "streamed_tokens_bitwise_identical_polled"
                    ],
                    "backpressure_stalls": fd["backpressure_stalls"],
                    "slo_compliance": {
                        t: row["slo_compliance"]
                        for t, row in fd["tenants"].items()
                    },
                }
            )
        )
        # The mode's contract includes the history row: load the gate
        # module by path (tools/ is not a package) and append the fresh
        # BENCH_SERVING.json — with its new frontdoor section — to
        # BENCH_HISTORY.jsonl.
        import importlib.util

        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "bench_history", os.path.join(here, "tools", "bench_history.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main([
            "append",
            "--bench", os.path.join(here, "BENCH_SERVING.json"),
            "--history", os.path.join(here, "BENCH_HISTORY.jsonl"),
        ])
        return

    if args.disttrace:
        # Exclusive mode: the fleet-tracing stack all-on vs all-off over
        # the front-door Poisson workload. The headline is the TPOT p50
        # overhead ratio; the acceptance rows are bitwise token parity,
        # a zero armed-sentinel count, and every waterfall summing to
        # its trace's e2e.
        dt = bench_disttrace()
        print(
            json.dumps(
                {
                    "metric": "disttrace_tpot_p50_overhead",
                    "value": dt["tpot_p50_disttrace_overhead"],
                    "unit": "ratio",
                    "vs_baseline": 1.0,
                    "tokens_bitwise_identical": dt[
                        "tokens_bitwise_identical"
                    ],
                    "recompiles_at_steady_state": dt[
                        "recompiles_at_steady_state"
                    ],
                    "trace_ids": dt["trace_ids"],
                    "waterfalls_sum_within_5pct": dt[
                        "waterfalls_sum_within_5pct"
                    ],
                    "tokens_per_sec_on": dt["tokens_per_sec_on"],
                }
            )
        )
        # Same history contract as --frontdoor: record the refreshed
        # BENCH_SERVING.json (new disttrace section) un-gated.
        import importlib.util

        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "bench_history", os.path.join(here, "tools", "bench_history.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main([
            "append",
            "--bench", os.path.join(here, "BENCH_SERVING.json"),
            "--history", os.path.join(here, "BENCH_HISTORY.jsonl"),
        ])
        return

    if args.perfwatch:
        # Exclusive mode: the performance observatory off vs on over the
        # front-door Poisson workload, plus the seeded slow_program
        # drill. The headline is the TPOT p50 overhead ratio; the
        # acceptance rows are bitwise token parity (observed AND under
        # stall), in-budget detection with correct phase blame, and a
        # zero-alert clean pass.
        pw = bench_perfwatch()
        print(
            json.dumps(
                {
                    "metric": "perfwatch_tpot_p50_overhead",
                    "value": pw["tpot_p50_perfwatch_overhead"],
                    "unit": "ratio",
                    "vs_baseline": 1.0,
                    "tokens_bitwise_identical": pw[
                        "tokens_bitwise_identical"
                    ],
                    "tokens_bitwise_identical_under_stall": pw[
                        "tokens_bitwise_identical_under_stall"
                    ],
                    "detector_fired": pw["detector_fired"],
                    "detection_latency_steps": pw["detection_latency_steps"],
                    "detection_latency_decode_samples": pw[
                        "detection_latency_decode_samples"
                    ],
                    "detection_within_budget": pw["detection_within_budget"],
                    "attributed_phase": pw["attributed_phase"],
                    "attribution_correct": pw["attribution_correct"],
                    "false_positive_alerts": pw[
                        "false_positive_alerts_clean_pass"
                    ],
                    "timeseries_memory_bytes": pw["timeseries_memory_bytes"],
                    "tokens_per_sec_on": pw["tokens_per_sec_on"],
                }
            )
        )
        # Same history contract as --frontdoor/--disttrace: record the
        # refreshed BENCH_SERVING.json (new perfwatch section) un-gated.
        import importlib.util

        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "bench_history", os.path.join(here, "tools", "bench_history.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main([
            "append",
            "--bench", os.path.join(here, "BENCH_SERVING.json"),
            "--history", os.path.join(here, "BENCH_HISTORY.jsonl"),
        ])
        return

    if args.hostkv:
        # Exclusive mode: the hierarchical-KV host tier off vs on over a
        # Poisson workload whose prefix working set exceeds device pages.
        # The headline is the TTFT p50 speedup; the acceptance rows are
        # bitwise token parity, a strictly higher hit rate, exact
        # spill/fetch byte agreement with the transfer ledger, and zero
        # leaked pages on either tier.
        hk = bench_hostkv()
        print(
            json.dumps(
                {
                    "metric": "hostkv_ttft_p50_speedup",
                    "value": hk["ttft_p50_speedup_hostkv"],
                    "unit": "ratio",
                    "vs_baseline": 1.0,
                    "tokens_bitwise_identical": hk[
                        "tokens_bitwise_identical"
                    ],
                    "hit_rate_strictly_higher": hk[
                        "hit_rate_strictly_higher"
                    ],
                    "prefix_hit_rate_on": hk["prefix_hit_rate_on"],
                    "prefix_hit_rate_off": hk["prefix_hit_rate_off"],
                    "ttft_p50_lower_with_tier": hk[
                        "ttft_p50_lower_with_tier"
                    ],
                    "host_hit_tokens": hk["host_hit_tokens"],
                    "spill_bytes_match_ledger": hk[
                        "spill_bytes_match_ledger"
                    ],
                    "fetch_bytes_match_ledger": hk[
                        "fetch_bytes_match_ledger"
                    ],
                    "device_pages_leaked": hk["device_pages_leaked"],
                    "tokens_per_sec_on": hk["tokens_per_sec_on"],
                }
            )
        )
        # Same history contract as --frontdoor/--disttrace/--perfwatch:
        # record the refreshed BENCH_SERVING.json (new hostkv section)
        # un-gated.
        import importlib.util

        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "bench_history", os.path.join(here, "tools", "bench_history.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main([
            "append",
            "--bench", os.path.join(here, "BENCH_SERVING.json"),
            "--history", os.path.join(here, "BENCH_HISTORY.jsonl"),
        ])
        return

    if args.paged_kernel:
        # Exclusive mode: fused paged-attention decode, gather vs kernel
        # vs kernel+int8 over one workload. Headline is the TPOT p50
        # speedup kernel-vs-gather (reported, not asserted — on a CPU rig
        # the kernel resolves to the XLA reference and the delta is
        # noise); the acceptance rows are fp greedy parity, the roofline's
        # fused-program attribution, the int8 pool byte ratio, and zero
        # leaked pages across all three passes.
        pk = bench_paged_kernel()
        print(
            json.dumps(
                {
                    "metric": "paged_kernel_tpot_p50_speedup",
                    "value": pk["tpot_p50_speedup_kernel"],
                    "unit": "ratio",
                    "vs_baseline": 1.0,
                    "tokens_bitwise_identical_fp": pk[
                        "tokens_bitwise_identical_fp"
                    ],
                    "tokens_bitwise_identical_int8": pk[
                        "tokens_bitwise_identical_int8"
                    ],
                    "achieved_fraction_gather": pk[
                        "achieved_fraction_gather"
                    ],
                    "achieved_fraction_kernel": pk[
                        "achieved_fraction_kernel"
                    ],
                    "achieved_fraction_int8": pk["achieved_fraction_int8"],
                    "fused_program_present": pk["fused_program_present"],
                    "kv_pool_ratio_int8": pk["kv_pool_ratio_int8"],
                    "tpot_p50_speedup_int8": pk["tpot_p50_speedup_int8"],
                    "pages_leaked": pk["pages_leaked"],
                }
            )
        )
        import importlib.util

        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "bench_history", os.path.join(here, "tools", "bench_history.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main([
            "append",
            "--bench", os.path.join(here, "BENCH_SERVING.json"),
            "--history", os.path.join(here, "BENCH_HISTORY.jsonl"),
        ])
        return

    if args.window_sweep:
        # Exclusive mode: step time vs band width at T=8192, fused head.
        # Speedup is steps/s vs the full-causal row (same model, less
        # compute); the per-row MFU uses the BANDED analytic FLOP basis, so
        # it reads as kernel efficiency on the smaller work, not speedup.
        rows = []
        # No w=4096 row: its compile RPC is what wedged the relay on
        # 2026-07-31 (BASELINE.md round 5) and the trend is already visible
        # by 2048 (band cost rising toward the full-causal floor); do not
        # re-risk a recovered tunnel on the least informative row.
        for w in (0, 512, 1024, 2048):
            row = attach_mfu(bench_lm(8192, True, window=w), peak)
            rows.append(row)
            print(f"# window={w or 'full'}: {row['steps_per_sec']} steps/s",
                  flush=True)
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_WINDOW.json"
        )
        with open(path, "w") as f:
            json.dump(
                {"mode": "sliding_window_sweep", "seq_len": 8192,
                 "device_kind": dev.device_kind, "rows": rows},
                f, indent=1,
            )
        full_sps = rows[0]["steps_per_sec"]
        w1024 = next(r for r in rows if "_win1024_" in r["workload"])
        speedup = round(w1024["steps_per_sec"] / full_sps, 4)
        print(
            json.dumps(
                {
                    "metric": "window1024_speedup_vs_full_t8192",
                    "value": speedup,
                    "unit": "ratio",
                    "vs_baseline": speedup,
                }
            )
        )
        return

    headline = attach_mfu(bench_resnet(32), peak)

    if args.matrix:
        matrix = [headline]
        for b in (64, 128):
            matrix.append(attach_mfu(bench_resnet(b), peak))
        # The honest-but-tunnel-bound number: H2D transfer per step.
        matrix.append(attach_mfu(bench_resnet(32, h2d_on_clock=True), peak))
        matrix.append(attach_mfu(bench_vit(32), peak))
        matrix.append(attach_mfu(bench_toy_mlp(), peak))
        for seq in (2048, 8192):
            for fused in (False, True):
                matrix.append(attach_mfu(bench_lm(seq, fused), peak))
        # d_head=128 scale-ups: the MFU the framework sustains once the model
        # shape fills the MXU's 128-wide contraction (see bench_lm docstring).
        matrix.append(attach_mfu(
            bench_lm(8192, True, d_model=1024, n_layers=12, d_ff=4096), peak
        ))
        matrix.append(attach_mfu(
            bench_lm(8192, True, d_model=2048, n_layers=6, n_heads=16,
                     d_ff=8192), peak
        ))
        # Sliding-window row: default dims, T=8192, 1024 band — its
        # full-causal twin is the transformer_lm_t8192_fused_head row from
        # the seq loop above (SAME dims; not the d_head=128 scale-ups just
        # before this line); the step-time delta between those two rows is
        # the kernel's tile-skipping payoff (round-4 feature, BASELINE.md).
        matrix.append(attach_mfu(bench_lm(8192, True, window=1024), peak))
        out = {
            "device_kind": dev.device_kind,
            "peak_bf16_tflops": peak / 1e12,
            "flops_basis": (
                "resnet/vit rows: XLA cost analysis of the compiled step "
                "(complete - no custom calls). transformer_lm rows: analytic "
                "model FLOPs, 3*(2*P_matmul*tokens + causal attention "
                "matmuls), identical for dense and fused head - XLA cannot "
                "count Pallas custom-call FLOPs and undercounts the chunked "
                "fused head, so cost analysis would misrank those rows "
                "(BASELINE.md round 3). _winW rows: the attention term is "
                "the BANDED analytic count (only in-band k columns), so "
                "their MFU denominator is smaller than the full-causal "
                "twins' - compare step time across rows, not MFU."
            ),
            "workloads": matrix,
        }
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_MATRIX.json"
        )
        with open(path, "w") as f:
            json.dump(out, f, indent=1)

    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json"
    )
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            recorded = json.load(f).get("value")
        if recorded:
            vs_baseline = headline["steps_per_sec"] / recorded

    print(
        json.dumps(
            {
                "metric": (
                    f"resnet50_bf16_train_steps_per_sec (batch 32/chip, "
                    f"{headline['n_chips']} chip, loader-assembled batches)"
                ),
                "value": headline["steps_per_sec"],
                "unit": "steps/s",
                "vs_baseline": round(vs_baseline, 4),
                "mfu": headline["mfu"],
                "model_tflops_per_sec_per_chip": headline[
                    "model_tflops_per_sec_per_chip"
                ],
            }
        )
    )


if __name__ == "__main__":
    main()
