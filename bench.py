"""Benchmark harness: ResNet-50 training throughput on the available chip(s).

Measures steps/sec/chip on the reference's profiled workload
(``multigpu_profile.py:16-27,104-106``: ResNet-50, synthetic 224x224 images,
batch 32 per replica) using the framework's own jitted train step, bfloat16
compute. Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is the
ratio against the round-1 recorded value in BENCH_BASELINE.json when present,
else 1.0.
"""

import json
import os
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_pytorch_tpu.models import ResNet50
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    from distributed_pytorch_tpu.parallel.sharding import (
        put_global_batch,
        replicated_sharding,
    )
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    n_chips = jax.device_count()
    per_chip_batch = 32
    batch = per_chip_batch * n_chips

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    optimizer = optax.sgd(1e-3, momentum=0.9)

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((batch, 224, 224, 3)).astype(np.float32)
    ys = rng.integers(0, 1000, size=(batch,)).astype(np.int32)

    mesh = make_mesh() if n_chips > 1 else None
    state = create_train_state(model, optimizer, xs[:2])
    if mesh is not None:
        state = jax.device_put(state, replicated_sharding(mesh))
        device_batch = put_global_batch(mesh, (xs, ys))
    else:
        device_batch = jax.device_put((jnp.asarray(xs), jnp.asarray(ys)))
    step = make_train_step(model.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh)

    # Warmup: compile + 3 steps. Synchronize by fetching the loss VALUE, not
    # just block_until_ready — remote-tunnel backends can treat the latter as
    # a no-op, which would time dispatch instead of compute.
    state, loss = step(state, device_batch)
    float(loss)
    for _ in range(3):
        state, loss = step(state, device_batch)
    float(loss)

    n_steps = 20
    start = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, device_batch)
    float(loss)
    elapsed = time.perf_counter() - start

    steps_per_sec_per_chip = n_steps / elapsed  # global step rate; batch scales with chips
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            recorded = json.load(f).get("value")
        if recorded:
            vs_baseline = steps_per_sec_per_chip / recorded

    print(
        json.dumps(
            {
                "metric": f"resnet50_bf16_train_steps_per_sec (batch {per_chip_batch}/chip, {n_chips} chip)",
                "value": round(steps_per_sec_per_chip, 4),
                "unit": "steps/s",
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
