"""Speculative decoding: draft-model proposal + chunked target verification.

No reference analog (the reference is a training tutorial; this repo's
inference surface goes beyond it — see ``generation.py``). Autoregressive
decode on TPU is latency-bound: each token is one tiny matmul pass that
cannot fill the MXU. Speculative decoding (Leviathan et al., 2023) converts
the TARGET model's serial decode into chunked verification: a small draft
model proposes ``gamma`` tokens autoregressively (cheap — its weights fit
the budget the target's can't), then the target scores all ``gamma``
positions in ONE multi-token forward — the same chunked decode path the
bucketed prefill uses (``models/transformer.py::Attention._decode_step``
handles per-position RoPE and the intra-chunk causal mask), so the verify
pass is MXU-shaped instead of bandwidth-shaped.

Greedy only, and exact BY ACCEPTANCE RULE: every emitted token equals the
target model's argmax given its prefix (only draft tokens matching the
target's own greedy choice are kept; the target's choice is emitted at the
first mismatch), so ``speculative_generate == generate(temperature=0)``
token-for-token — pinned by ``tests/test_speculative.py``. One honest
caveat: the verify pass computes those argmaxes from a ``gamma``-wide
chunked forward while plain ``generate`` uses single-token forwards, and
in reduced precision (bf16) XLA may fuse/reduce the two shapes differently
— a near-TIE between the top two logits can then break differently. The
rule is exact; float equality across chunk widths is the model's to
provide (the tests pin exactness at float32; ties this close are
epsilon-measure for trained models). Sampled speculative decoding
(modified rejection sampling) is out of scope.

Design notes (TPU/XLA):

* The outer loop is a ``lax.while_loop`` (the per-round advance is
  data-dependent: 1 to ``gamma`` positions), with every shape inside static:
  the draft phase is always ``gamma`` single-token steps, the verify chunk
  is always ``gamma`` wide, and the token buffer is padded by ``gamma`` so
  the final round's writes never need masking.
* Cache rollback is an index rewind, not a copy: the decode cache masks
  reads at ``k_abs <= q_abs`` (positions beyond ``cache_index`` are
  invisible) and writes through ``dynamic_update_slice`` at the index, so
  stale K/V from rejected draft tokens is dead by construction — rolling
  back IS setting ``cache_index`` (`_set_cache_index`), O(1).
* Batched rounds advance by the MINIMUM acceptance across rows (the cache
  index is one scalar per layer, not per row). Greedy determinism makes
  this exact: a row that accepted further just re-derives the identical
  tokens next round. The expected speedup therefore decays with batch
  size; B=1 is the latency case speculative decoding exists for.
* The per-round advance is capped at ``gamma`` (no "bonus" ``gamma+1``-th
  token on full acceptance): emitting it would advance past the draft
  cache's fill point and turn the next draft phase into a ragged catch-up
  chunk. One potential extra token per round is not worth dynamic shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _set_cache_index(cache, value):
    """Rewind every layer's ``cache_index`` leaf to ``value`` (O(1) — see
    module docstring on why this is a complete rollback)."""
    value = jnp.asarray(value, jnp.int32)

    def maybe(path, leaf):
        last = path[-1]
        key = getattr(last, "key", None)
        return value if key == "cache_index" else leaf

    return jax.tree_util.tree_map_with_path(maybe, cache)


def speculative_generate(
    model,
    params,
    draft_model,
    draft_params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    gamma: int = 4,
    prompt_lengths: Optional[jnp.ndarray] = None,
    pad_token: int = 0,
    return_stats: bool = False,
):
    """Greedy-decode ``max_new_tokens`` continuations of ``prompt`` [B, T0]
    with ``model`` as the target, using ``draft_model`` to propose
    ``gamma``-token chunks. Returns ``[B, T0 + max_new_tokens]`` ids —
    token-for-token identical to ``generate(model, ..., temperature=0)``
    up to reduced-precision argmax ties across chunk widths (see module
    docstring; exact at float32).

    ``return_stats=True`` additionally returns ``{"rounds": R,
    "positions_advanced": A}``, counting only GENERATED positions (rounds
    that merely replay bucketed-down prompt tails are excluded — their
    auto-accepted prompt positions would overstate draft quality): A/R in
    [1, gamma] is the mean accepted chunk length (draft quality x
    batch-min effect). R is a LOWER bound on the target's chunked
    forwards (replay-only rounds run one too but count toward neither);
    with power-of-two prompt lengths the two coincide, and either way
    the target ran far fewer forwards than A serial single-token steps.

    Both models must share the vocabulary; the draft is typically a
    narrower/shallower ``TransformerLM``. Single-mesh (unsharded) decode —
    compose with TP/DP via ``generation.generate`` if sharding is needed.
    """
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    tv = getattr(model, "vocab_size", None)
    dv = getattr(draft_model, "vocab_size", None)
    if tv is not None and dv is not None and tv != dv:
        raise ValueError(
            f"target and draft must share a vocabulary, got {tv} vs {dv}"
        )
    target = model.clone(decode=True)
    draft = draft_model.clone(decode=True)

    batch, prompt_len = prompt.shape
    total_len = prompt_len + max_new_tokens
    if prompt_lengths is None:
        prompt_lengths = jnp.full((batch,), prompt_len, jnp.int32)
    prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)

    # Buffer and caches padded by gamma: the last round may write (but
    # never emit) up to gamma - 1 positions past total_len.
    buf_len = total_len + gamma
    tokens0 = jnp.concatenate(
        [
            jnp.asarray(prompt, jnp.int32),
            jnp.full((batch, buf_len - prompt_len), pad_token, jnp.int32),
        ],
        axis=1,
    )

    from distributed_pytorch_tpu.generation import bucketed_prefill_len

    prefill_len = bucketed_prefill_len(prompt_lengths)

    t_abstract = jax.eval_shape(
        target.init, jax.random.PRNGKey(0),
        jnp.zeros((batch, buf_len), jnp.int32),
    )["cache"]
    d_abstract = jax.eval_shape(
        draft.init, jax.random.PRNGKey(0),
        jnp.zeros((batch, buf_len), jnp.int32),
    )["cache"]
    zeros = lambda s: jnp.zeros(s.shape, s.dtype)  # noqa: E731
    tcache = jax.tree_util.tree_map(zeros, t_abstract)
    dcache = jax.tree_util.tree_map(zeros, d_abstract)

    run = _compiled_spec_run(target, draft, buf_len, gamma, prefill_len)
    tokens, rounds, advanced = run(
        params, draft_params, tokens0, tcache, dcache, prompt_lengths,
        total_len,
    )
    tokens = tokens[:, :total_len]
    if return_stats:
        return tokens, {"rounds": rounds, "positions_advanced": advanced}
    return tokens


@functools.lru_cache(maxsize=16)
def _compiled_spec_run(target, draft, buf_len, gamma, prefill_len):
    """Jitted speculative loop, cached per (model pair, shapes, gamma)."""

    def run(params, draft_params, tokens, tcache, dcache, prompt_lengths,
            total_len):
        batch = tokens.shape[0]

        if prefill_len > 1:
            chunk = tokens[:, : prefill_len - 1]
            _, up = target.apply(
                {"params": params, "cache": tcache}, chunk, mutable=["cache"]
            )
            tcache = up["cache"]
            _, up = draft.apply(
                {"params": draft_params, "cache": dcache}, chunk,
                mutable=["cache"],
            )
            dcache = up["cache"]

        def draft_step(i, carry):
            tokens, dcache, t = carry
            current = jax.lax.dynamic_slice(tokens, (0, t + i), (batch, 1))
            logits, up = draft.apply(
                {"params": draft_params, "cache": dcache}, current,
                mutable=["cache"],
            )
            proposal = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            keep_prompt = (t + i + 1) < prompt_lengths
            existing = jax.lax.dynamic_slice(
                tokens, (0, t + i + 1), (batch, 1)
            )[:, 0]
            nxt = jnp.where(keep_prompt, existing, proposal)
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt[:, None], (0, t + i + 1)
            )
            return tokens, up["cache"], t

        def body(carry):
            tokens, tcache, dcache, t, rounds, advanced = carry
            # Round entry invariant: both cache_index == t; tokens[.., :t+1]
            # are final (target-greedy-consistent).
            tokens, dcache, _ = jax.lax.fori_loop(
                0, gamma, draft_step, (tokens, dcache, t)
            )
            # Target verifies the whole proposal in one chunked forward:
            # positions t .. t+gamma-1 predict t+1 .. t+gamma.
            chunk = jax.lax.dynamic_slice(tokens, (0, t), (batch, gamma))
            logits, up = target.apply(
                {"params": params, "cache": tcache}, chunk, mutable=["cache"]
            )
            tcache = up["cache"]
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, gamma]

            pos = t + 1 + jnp.arange(gamma)[None, :]  # positions decided
            written = jax.lax.dynamic_slice(
                tokens, (0, t + 1), (batch, gamma)
            )
            # Prompt positions are given, not generated: auto-accept.
            match = (written == g) | (pos < prompt_lengths[:, None])
            n_row = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B]
            n = jnp.min(n_row)  # batch-min advance (see module docstring)

            # Correction write: position t+n+1 gets the target's own token.
            # When n == gamma the clamped write is a no-op by construction
            # (match[:, gamma-1] held for every row, so written == g there);
            # rows that accepted beyond n overwrite with the identical value
            # (their match[:, n] held too).
            ni = jnp.minimum(n, gamma - 1)
            g_n = jax.lax.dynamic_index_in_dim(
                g, ni, axis=1, keepdims=False
            )  # [B]: each row's own target token at the correction column
            keep_prompt = (t + ni + 1) < prompt_lengths
            existing = jax.lax.dynamic_slice(
                tokens, (0, t + ni + 1), (batch, 1)
            )[:, 0]
            corrected = jnp.where(keep_prompt, existing, g_n)
            tokens = jax.lax.dynamic_update_slice(
                tokens, corrected[:, None], (0, t + ni + 1)
            )

            t_new = t + jnp.minimum(n + 1, gamma)
            tcache = _set_cache_index(tcache, t_new)
            dcache = _set_cache_index(dcache, t_new)
            # Stats count only GENERATED positions: rounds replaying
            # bucketed-down prompt tails auto-accept via the prompt term in
            # `match`, and crediting those would overstate draft quality
            # (position p is generated iff p >= its row's prompt length;
            # p > max_prompt - 1 covers every row).
            max_prompt = jnp.max(prompt_lengths)
            gen_adv = jnp.maximum(
                t_new - jnp.maximum(t, max_prompt - 1), 0
            )
            return (tokens, tcache, dcache, t_new,
                    rounds + (gen_adv > 0).astype(jnp.int32),
                    advanced + gen_adv)

        def cond(carry):
            return carry[3] < total_len - 1

        t0 = jnp.asarray(prefill_len - 1, jnp.int32)
        tokens, _, _, _, rounds, advanced = jax.lax.while_loop(
            cond, body,
            (tokens, tcache, dcache, t0, jnp.zeros((), jnp.int32),
             jnp.zeros((), jnp.int32)),
        )
        return tokens, rounds, advanced

    return jax.jit(run, static_argnames=())
