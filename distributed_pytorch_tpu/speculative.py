"""Speculative decoding: draft-model proposal + chunked target verification.

No reference analog (the reference is a training tutorial; this repo's
inference surface goes beyond it — see ``generation.py``). Autoregressive
decode on TPU is latency-bound: each token is one tiny matmul pass that
cannot fill the MXU. Speculative decoding (Leviathan et al., 2023) converts
the TARGET model's serial decode into chunked verification: a small draft
model proposes ``gamma`` tokens autoregressively (cheap — its weights fit
the budget the target's can't), then the target scores all ``gamma``
positions in ONE multi-token forward — the same chunked decode path the
bucketed prefill uses (``models/transformer.py::Attention._decode_step``
handles per-position RoPE and the intra-chunk causal mask), so the verify
pass is MXU-shaped instead of bandwidth-shaped.

Exact BY ACCEPTANCE RULE, in both modes. Greedy (``temperature=0``):
every emitted token equals the target model's argmax given its prefix
(only draft tokens matching the target's own greedy choice are kept; the
target's choice is emitted at the first mismatch), so
``speculative_generate == generate(temperature=0)`` token-for-token —
pinned by ``tests/test_speculative.py``. Sampled (``temperature > 0``):
Leviathan et al.'s modified rejection sampling — accept draft token x
with probability ``min(1, p(x)/q(x))``, resample the first rejection from
the residual ``max(p - q, 0)`` — whose lemma makes every emitted token
exactly ``p``-distributed (marginal law pinned statistically against the
target's softmax, with a plain-sampling control calibrating the bound).
One honest caveat: the verify pass computes ``p`` from a ``gamma``-wide
chunked forward while plain ``generate`` uses single-token forwards, and
in reduced precision (bf16) XLA may fuse/reduce the two shapes differently
— a near-TIE between the top two logits can then break differently (or,
sampled, shift a probability by float-epsilon). The rule is exact; float
equality across chunk widths is the model's to provide (the tests pin
greedy exactness at float32; ties this close are epsilon-measure for
trained models).

Design notes (TPU/XLA):

* The outer loop is a ``lax.while_loop`` (the per-round advance is
  data-dependent: 1 to ``gamma`` positions), with every shape inside static:
  the draft phase is always ``gamma`` single-token steps, the verify chunk
  is always ``gamma`` wide, and the token buffer is padded by ``gamma`` so
  the final round's writes never need masking.
* Cache rollback is an index rewind, not a copy: the decode cache masks
  reads at ``k_abs <= q_abs`` (positions beyond ``cache_index`` are
  invisible) and writes through ``dynamic_update_slice`` at the index, so
  stale K/V from rejected draft tokens is dead by construction — rolling
  back IS setting ``cache_index`` (`_set_cache_index`), O(1).
* Batched rounds advance PER ROW: every layer's ``cache_index`` is a [B]
  vector (``models/transformer.py::Attention._decode_step`` scatter-writes
  and masks at per-row offsets, the same machinery the paged serving path
  uses), so each row keeps exactly its own accepted prefix + correction and
  no row ever stalls on the batch minimum. A row that reaches the target
  length freezes (its advance clamps at ``total_len - 1``) and keeps
  proposing into the gamma-padded garbage region past its output — fixed
  shapes are preserved, and the overshoot writes are dropped/masked.
  Per-row keys (``fold_in(rng, t_row)``) keep every row's sampled stream
  independent of the other rows' acceptance, so the emitted law is exactly
  ``p`` row by row.
* The per-round advance is capped at ``gamma`` (no "bonus" ``gamma+1``-th
  token on full acceptance): emitting it would advance past the draft
  cache's fill point and turn the next draft phase into a ragged catch-up
  chunk. One potential extra token per round is not worth dynamic shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _set_cache_index(cache, value):
    """Rewind every layer's ``cache_index`` leaf to ``value`` (O(1) — see
    module docstring on why this is a complete rollback)."""
    value = jnp.asarray(value, jnp.int32)

    def maybe(path, leaf):
        last = path[-1]
        key = getattr(last, "key", None)
        return value if key == "cache_index" else leaf

    return jax.tree_util.tree_map_with_path(maybe, cache)


def speculative_generate(
    model,
    params,
    draft_model,
    draft_params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    gamma: int = 4,
    prompt_lengths: Optional[jnp.ndarray] = None,
    pad_token: int = 0,
    return_stats: bool = False,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    rng: Optional[jax.Array] = None,
    mesh=None,
    data_axis: str = "data",
):
    """Speculatively decode ``max_new_tokens`` continuations of ``prompt``
    [B, T0] with ``model`` as the target, using ``draft_model`` to propose
    ``gamma``-token chunks. Returns ``[B, T0 + max_new_tokens]`` ids whose
    law is EXACTLY the target's own decode: at ``temperature=0`` (default)
    token-for-token identical to ``generate(model, ..., temperature=0)``
    (up to reduced-precision argmax ties across chunk widths — see module
    docstring; exact at float32); at ``temperature > 0`` each emitted
    token is exactly target-distributed under the same
    ``temperature``/``top_k``/``top_p`` filters via modified rejection
    sampling (``rng`` seeds the draws).

    ``return_stats=True`` additionally returns ``{"rounds": R,
    "positions_advanced": A}``, counting only GENERATED positions — per
    row (position p counts for row b iff ``p >= prompt_lengths[b]``),
    averaged over the batch, so ragged batches report the true mean;
    rounds that merely replay bucketed-down prompt tails count toward
    neither (their auto-accepted prompt positions would overstate draft
    quality). A/R in [1, gamma] is the mean accepted chunk length (pure
    draft quality — acceptance is per row, so no batch-min decay). R is a
    LOWER bound on the target's chunked forwards (replay-only rounds run
    one too); with uniform power-of-two prompt lengths the two coincide,
    and either way the target ran far fewer forwards than A serial
    single-token steps.

    ``temperature > 0`` switches to SAMPLED speculative decoding
    (Leviathan et al. modified rejection sampling): the draft SAMPLES each
    proposal from its own (temperature/top-k/top-p filtered) distribution
    q, the target accepts token x with probability ``min(1, p(x)/q(x))``
    against its equally-filtered distribution p, and the first rejected
    position is resampled from the residual ``max(p - q, 0)`` — which
    makes every emitted token EXACTLY ``p``-distributed, the same law as
    ``generate(temperature=..., top_k=..., top_p=...)`` (the classic
    lemma; the marginal is pinned statistically in
    ``tests/test_speculative.py``). The draft's full distributions are
    recomputed in one chunked draft forward at verify time (cache rewound
    and replayed) rather than carried through the proposal loop — one
    cheap extra draft pass instead of a ``[B, gamma, V]`` carry.

    Both models must share the vocabulary; the draft is typically a
    narrower/shallower ``TransformerLM``. With ``mesh``, decoding runs
    batch-sharded like ``generation.generate``: tokens, prompt lengths,
    and BOTH models' KV caches are placed ``P(data_axis)`` and the params
    replicated — the loop is pure jit, so GSPMD partitions it from the
    placements alone (per-row advance leaves the loop condition's
    ``jnp.min`` over row cursors as the one cross-device collective per
    round). Output is token-for-token identical to the single-device run
    (pinned by test).
    """
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    tv = getattr(model, "vocab_size", None)
    dv = getattr(draft_model, "vocab_size", None)
    if tv is not None and dv is not None and tv != dv:
        raise ValueError(
            f"target and draft must share a vocabulary, got {tv} vs {dv}"
        )
    target = model.clone(decode=True)
    draft = draft_model.clone(decode=True)

    batch, prompt_len = prompt.shape
    total_len = prompt_len + max_new_tokens
    if prompt_lengths is None:
        prompt_lengths = jnp.full((batch,), prompt_len, jnp.int32)
    prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)

    # Buffer and caches padded by gamma: the last round may write (but
    # never emit) up to gamma - 1 positions past total_len.
    buf_len = total_len + gamma
    tokens0 = jnp.concatenate(
        [
            jnp.asarray(prompt, jnp.int32),
            jnp.full((batch, buf_len - prompt_len), pad_token, jnp.int32),
        ],
        axis=1,
    )

    from distributed_pytorch_tpu.generation import bucketed_prefill_len

    prefill_len = bucketed_prefill_len(prompt_lengths)

    t_abstract = jax.eval_shape(
        target.init, jax.random.PRNGKey(0),
        jnp.zeros((batch, buf_len), jnp.int32),
    )["cache"]
    d_abstract = jax.eval_shape(
        draft.init, jax.random.PRNGKey(0),
        jnp.zeros((batch, buf_len), jnp.int32),
    )["cache"]
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if mesh is not None:
        from distributed_pytorch_tpu.generation import batch_sharding_placer

        place, batch_sh, replicated = batch_sharding_placer(
            mesh, data_axis, batch
        )
        tcache = jax.tree_util.tree_map(place, t_abstract)
        dcache = jax.tree_util.tree_map(place, d_abstract)
        tokens0 = jax.device_put(tokens0, batch_sh)
        prompt_lengths = jax.device_put(prompt_lengths, batch_sh)
        params = jax.device_put(params, replicated)
        draft_params = jax.device_put(draft_params, replicated)
        rng = jax.device_put(rng, replicated)
    else:
        zeros = lambda s: jnp.zeros(s.shape, s.dtype)  # noqa: E731
        tcache = jax.tree_util.tree_map(zeros, t_abstract)
        dcache = jax.tree_util.tree_map(zeros, d_abstract)
    run = _compiled_spec_run(
        target, draft, buf_len, gamma, prefill_len, float(temperature),
        int(top_k), float(top_p),
    )
    tokens, rounds, advanced = run(
        params, draft_params, tokens0, tcache, dcache, prompt_lengths,
        total_len, rng,
    )
    tokens = tokens[:, :total_len]
    if return_stats:
        return tokens, {"rounds": rounds, "positions_advanced": advanced}
    return tokens


@functools.lru_cache(maxsize=16)
def _compiled_spec_run(target, draft, buf_len, gamma, prefill_len,
                       temperature=0.0, top_k=0, top_p=0.0):
    """Jitted speculative loop, cached per (model pair, shapes, gamma,
    sampling config)."""
    from distributed_pytorch_tpu.generation import truncate_logits

    sampled = temperature > 0.0

    def filtered(logits):
        # The distribution ACTUALLY sampled from, f32 for the acceptance
        # ratio arithmetic.
        return jax.nn.softmax(
            truncate_logits(logits / temperature, top_k, top_p).astype(
                jnp.float32
            ),
            axis=-1,
        )

    def run(params, draft_params, tokens, tcache, dcache, prompt_lengths,
            total_len, rng):
        batch = tokens.shape[0]

        if prefill_len > 1:
            chunk = tokens[:, : prefill_len - 1]
            _, up = target.apply(
                {"params": params, "cache": tcache}, chunk, mutable=["cache"]
            )
            tcache = up["cache"]
            _, up = draft.apply(
                {"params": draft_params, "cache": dcache}, chunk,
                mutable=["cache"],
            )
            dcache = up["cache"]

        rows_idx = jnp.arange(batch, dtype=jnp.int32)
        # One base key per ROW (row index folded in): rows at the same
        # cursor must still draw independently — vmapped categorical with
        # identical keys would clone one sample across the batch.
        row_keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            rng, rows_idx
        )

        def fold_rows(keys, i):
            # Per-row subkey: fold the same step tag into every row's key.
            return jax.vmap(jax.random.fold_in, in_axes=(0, None))(keys, i)

        def draft_step(i, carry):
            tokens, dcache, t, round_keys = carry
            # ``t`` is [B]: every row reads/writes at its OWN cursor.
            current = jnp.take_along_axis(tokens, (t + i)[:, None], axis=1)
            logits, up = draft.apply(
                {"params": draft_params, "cache": dcache}, current,
                mutable=["cache"],
            )
            last = logits[:, -1, :]
            if sampled:
                # Propose x ~ q (the draft's filtered distribution); the
                # full q row is recomputed at verify time in one chunked
                # draft pass instead of being carried through this loop.
                proposal = jax.vmap(jax.random.categorical)(
                    fold_rows(round_keys, i),
                    truncate_logits(last / temperature, top_k, top_p),
                ).astype(jnp.int32)
            else:
                proposal = jnp.argmax(last, axis=-1).astype(jnp.int32)
            keep_prompt = (t + i + 1) < prompt_lengths
            existing = jnp.take_along_axis(
                tokens, (t + i + 1)[:, None], axis=1
            )[:, 0]
            nxt = jnp.where(keep_prompt, existing, proposal)
            tokens = tokens.at[rows_idx, t + i + 1].set(nxt)
            return tokens, up["cache"], t, round_keys

        def body(carry):
            tokens, tcache, dcache, t, rounds, advanced = carry
            # Per-row round keys: a row's draws depend only on its own
            # (row, cursor) pair, so its sampled stream is independent of
            # how fast the other rows accepted.
            round_keys = jax.vmap(jax.random.fold_in)(row_keys, t)
            # Round entry invariant: both caches' per-row cache_index == t;
            # tokens[b, :t[b]+1] are final (target-consistent).
            tokens, dcache, _, _ = jax.lax.fori_loop(
                0, gamma, draft_step, (tokens, dcache, t, round_keys)
            )
            # Target verifies every proposal in one chunked forward:
            # row b's positions t[b] .. t[b]+gamma-1 predict
            # t[b]+1 .. t[b]+gamma.
            cols = jnp.arange(gamma, dtype=jnp.int32)[None, :]
            chunk = jnp.take_along_axis(tokens, t[:, None] + cols, axis=1)
            logits, up = target.apply(
                {"params": params, "cache": tcache}, chunk, mutable=["cache"]
            )
            tcache = up["cache"]

            pos = t[:, None] + 1 + cols  # positions decided
            in_prompt = pos < prompt_lengths[:, None]
            written = jnp.take_along_axis(tokens, pos, axis=1)
            if sampled:
                # Full q rows in ONE chunked draft replay: rewind the draft
                # cache to t and re-feed the same chunk (the K/V writes are
                # recomputed identically, so the cache stays consistent at
                # t+gamma afterwards).
                dlogits, up = draft.apply(
                    {"params": draft_params,
                     "cache": _set_cache_index(dcache, t)},
                    chunk, mutable=["cache"],
                )
                dcache = up["cache"]
                pf = filtered(logits)   # [B, gamma, V]
                qf = filtered(dlogits)  # [B, gamma, V]
                px = jnp.take_along_axis(pf, written[..., None], axis=-1)[..., 0]
                qx = jnp.take_along_axis(qf, written[..., None], axis=-1)[..., 0]
                u = jax.vmap(
                    lambda key: jax.random.uniform(key, (gamma,))
                )(fold_rows(round_keys, gamma))
                # u < min(1, px/qx)  <=>  u*qx < px (q(x) > 0 a.s. — x was
                # sampled from q). Prompt positions are given: auto-accept.
                match = (u * qx < px) | in_prompt
            else:
                g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                match = (written == g) | in_prompt
            n_row = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B]

            # Correction write at row b's position t[b]+n_row[b]+1. Greedy:
            # the target's own token. Sampled: a draw from the residual
            # max(p - q, 0) — the distribution that makes the emitted token
            # exactly p-law (falling back to p itself in the measure-zero
            # p == q corner where the residual has no mass). Rows with
            # n_row == gamma accepted their whole chunk: the clamped ni
            # routes them back to their already-written token via the
            # n_row > ni select, so the write is a no-op for them.
            ni = jnp.minimum(n_row, gamma - 1)  # [B]
            if sampled:
                pf_n = jnp.take_along_axis(
                    pf, ni[:, None, None], axis=1
                )[:, 0, :]  # [B, V]
                qf_n = jnp.take_along_axis(
                    qf, ni[:, None, None], axis=1
                )[:, 0, :]
                residual = jnp.maximum(pf_n - qf_n, 0.0)
                has_mass = jnp.sum(residual, axis=-1, keepdims=True) > 0
                res_dist = jnp.where(has_mass, residual, pf_n)
                replacement = jax.vmap(jax.random.categorical)(
                    fold_rows(round_keys, gamma + 1),
                    jnp.log(res_dist),
                ).astype(jnp.int32)
            else:
                replacement = jnp.take_along_axis(g, ni[:, None], axis=1)[
                    :, 0
                ]  # [B]: each row's own target token at its correction column
            kept = jnp.take_along_axis(written, ni[:, None], axis=1)[:, 0]
            corrected = jnp.where(n_row > ni, kept, replacement)
            tokens = tokens.at[rows_idx, t + ni + 1].set(corrected)

            # Per-row advance, clamped at the finish line: a row that
            # reached total_len - 1 freezes there (its later proposals land
            # in the gamma-padded buffer tail and are never emitted), so
            # fast rows never outrun the buffer while slow rows catch up.
            t_new = jnp.minimum(
                t + jnp.minimum(n_row + 1, gamma), total_len - 1
            )
            tcache = _set_cache_index(tcache, t_new)
            dcache = _set_cache_index(dcache, t_new)
            # Stats count only GENERATED positions: rounds replaying
            # bucketed-down prompt tails auto-accept via the prompt term in
            # `match`, and crediting those would overstate draft quality.
            # Counted PER ROW (position p is generated for row b iff
            # p >= prompt_lengths[b]) and averaged over the batch, so a
            # ragged batch — where some rows are already generating while
            # others still replay their prompt — reports the true mean
            # accepted chunk.
            per_row = jnp.clip(
                t_new - jnp.maximum(t, prompt_lengths - 1), 0, t_new - t
            ).astype(jnp.float32)
            gen_adv = jnp.mean(per_row)
            return (tokens, tcache, dcache, t_new,
                    rounds + (gen_adv > 0).astype(jnp.int32),
                    advanced + gen_adv)

        def cond(carry):
            # Run until EVERY row's cursor reaches the finish line — the
            # one cross-row (and, sharded, cross-device) reduction per round.
            return jnp.min(carry[3]) < total_len - 1

        t0 = jnp.full((batch,), prefill_len - 1, jnp.int32)
        tcache = _set_cache_index(tcache, t0)
        dcache = _set_cache_index(dcache, t0)
        tokens, _, _, _, rounds, advanced = jax.lax.while_loop(
            cond, body,
            (tokens, tcache, dcache, t0, jnp.zeros((), jnp.int32),
             jnp.zeros((), jnp.float32)),
        )
        return tokens, rounds, advanced

    return jax.jit(run, static_argnames=())
