"""Autoregressive text generation with a KV cache — the inference side of the
LM family.

No reference analog (the reference is a training tutorial); a complete
framework needs a sampling path, and on TPU it must be a SINGLE compiled
program, not a Python token loop: the whole generate pass is one
``lax.fori_loop`` inside ``jit``, so XLA pipelines the per-token steps and the
host is never in the loop.

Mechanics:

* the model is cloned with ``decode=True`` (:class:`..models.transformer
  .TransformerLM`); each attention layer carries ``cached_key``/``cached_value``
  buffers sized ``[B, max_len, H, D]`` plus a running ``cache_index``;
* each loop step feeds ONE token per sequence, updates the caches in place
  (functionally — donated buffers under jit), and samples the next token
  (greedy, temperature, optional top-k);
* the common prompt prefix (up to the shortest row's length) is PREFILLED
  in one batched forward — a single MXU-friendly pass instead of
  ``min_len`` serial single-token steps (``Attention._decode_step`` handles
  multi-token chunks: per-position RoPE and an intra-chunk causal mask);
* past the prefill, ragged prompts need no special casing: while ``t`` is
  inside a row's prompt the sampled token is discarded in favor of the
  prompt token, so one loop covers every row.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.ops.quant import dequantize_pytree


def truncate_logits(
    logits: jnp.ndarray, top_k: int, top_p: float
) -> jnp.ndarray:
    """Apply top-k and/or nucleus truncation with ONE descending sort
    (the decode hot loop calls this per token; sorting the vocab twice —
    once for the k-th threshold, once for the nucleus cumsum — would be
    pure waste). Semantically identical to top-k masking followed by
    :func:`top_p_filter` over the renormalized survivors: the nucleus
    probabilities are computed over the top-k prefix of the sorted row,
    which IS the renormalized survivor distribution."""
    if top_k <= 0 and not (0.0 < top_p < 1.0):
        return logits
    # top_k beyond the vocab means "keep everything" (the pre-fusion code
    # clamped the same way via negative-index sort slicing).
    top_k = min(top_k, logits.shape[-1])
    sorted_desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    if top_k > 0:
        threshold = sorted_desc[..., top_k - 1 : top_k]  # k-th largest
        # Tie-inclusive survivor set, exactly like masking then re-sorting:
        # entries equal to the k-th value all survive (matters for bf16 /
        # quantized logits where ties are common), so the nucleus below is
        # computed over the same renormalized distribution the sequential
        # top-k -> top_p_filter composition sees.
        masked_sorted = jnp.where(
            sorted_desc >= threshold, sorted_desc, -jnp.inf
        )
    else:
        threshold = sorted_desc[..., -1:]  # keeps everything
        masked_sorted = sorted_desc
    if 0.0 < top_p < 1.0:
        probs = jax.nn.softmax(masked_sorted, axis=-1)
        keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        n_keep = jnp.sum(keep, axis=-1, keepdims=True)  # >= 1
        # keep is a prefix of the survivor prefix, where masked_sorted ==
        # sorted_desc — so indexing the unmasked sort is safe.
        nucleus_thr = jnp.take_along_axis(sorted_desc, n_keep - 1, axis=-1)
        threshold = jnp.maximum(threshold, nucleus_thr)
    return jnp.where(logits < threshold, -jnp.inf, logits)


def top_p_filter(logits: jnp.ndarray, top_p: float) -> jnp.ndarray:
    """Nucleus filter: mask ``logits`` ([..., V]) to the smallest set of
    tokens whose cumulative probability reaches ``top_p``, returning the
    filtered logits (masked entries at ``-inf``).

    The token that crosses the threshold is INCLUDED (the kept mass is
    always >= top_p), and at least one token always survives — the
    standard Holtzman et al. convention. Ties at the boundary logit are all
    kept. Delegates to :func:`truncate_logits` (top_k disabled) so the
    sort/cumsum/threshold convention has exactly ONE implementation —
    ``tests/test_generation.py`` pins the equivalence the public name
    promises."""
    return truncate_logits(logits, 0, top_p)


def make_sampler(temperature: float, top_k: int, top_p: float):
    """Return ``sample(logits [B, V], rng) -> tokens [B]`` for a STATIC
    sampling config: greedy argmax at ``temperature <= 0``, else categorical
    over the temperature-scaled, top-k/top-p-truncated logits. This is THE
    next-token rule — ``generate``'s loop body and the serving engine's
    continuous-batching decode step both call it, so offline and served
    sampling can never drift apart. ``bias`` is an optional additive
    ``[B, V]`` logit offset (per-request logit-bias / grammar masks);
    a zeros bias is a bitwise no-op on the sampled tokens."""

    def sample(logits, step_rng, bias=None):
        if bias is not None:
            logits = logits + bias
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = truncate_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(step_rng, scaled).astype(jnp.int32)

    return sample


def make_row_sampler(top_k: int, top_p: float):
    """Return ``sample(logits [B, V], temps [B], keys [B, 2], bias
    [B, V]) -> tokens [B]`` — the PER-ROW variant of :func:`make_sampler`
    for the serving engine's one compiled decode program, where each
    batch row carries its own temperature (``<= 0`` = greedy), fold-in
    RNG key, and additive logit bias (zeros = bitwise no-op; per-request
    logit-bias and grammar-mask rows land here as data, never as a
    recompile). Same math, same order of operations as the static rule,
    so mods-off serving stays token-identical to offline decode."""

    def sample(logits, temps, keys, bias):
        logits = logits + bias
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        safe_t = jnp.where(temps > 0, temps, 1.0)
        scaled = truncate_logits(logits / safe_t[:, None], top_k, top_p)
        sampled = jax.vmap(jax.random.categorical)(keys, scaled)
        return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)

    return sample


def decode_token_step(decode_model, params, cache, current, **apply_kwargs):
    """ONE decode-mode forward: apply ``decode_model`` on ``current``
    ([B, T_step] token ids) against ``cache``, returning ``(last_logits,
    cache)`` where ``last_logits`` is ``[B, V]`` at the final position.

    This is the single-token step extracted from ``generate``'s loop body so
    the serving engine (serving/engine.py) drives EXACTLY the same compiled
    math — dequant-inside-the-step and all. Extra ``apply_kwargs``
    (``block_tables``/``seq_lens``) flow to the model for the paged-cache
    path; callers that only need the cache update may discard the logits
    (XLA dead-code-eliminates the LM head when the output is unused)."""
    dtype = getattr(decode_model, "dtype", jnp.bfloat16)
    logits, updated = decode_model.apply(
        {"params": dequantize_pytree(params, dtype), "cache": cache},
        current,
        mutable=["cache"],
        **apply_kwargs,
    )
    return logits[:, -1, :], updated["cache"]


def decode_chunk_step(decode_model, params, cache, current, **apply_kwargs):
    """Like :func:`decode_token_step` but keeps EVERY position's logits:
    ``(logits [B, T_step, V], cache)``. This is the speculative VERIFY
    forward — the target scores all ``gamma`` proposal positions in one
    chunked decode (per-position RoPE + intra-chunk causal mask come from
    the decode path itself), so acceptance is decided for the whole chunk
    from a single MXU-shaped program instead of ``gamma`` bandwidth-shaped
    single-token steps."""
    dtype = getattr(decode_model, "dtype", jnp.bfloat16)
    logits, updated = decode_model.apply(
        {"params": dequantize_pytree(params, dtype), "cache": cache},
        current,
        mutable=["cache"],
        **apply_kwargs,
    )
    return logits, updated["cache"]


def batch_sharding_placer(mesh: Mesh, data_axis: str, batch: int):
    """``(place, batch_sh, replicated)`` — THE decode placement rule,
    shared by :func:`generate`, :func:`beam_search`, and
    ``speculative.speculative_generate`` so the heuristic lives once:
    abstract arrays leading with the batch dim (tokens, KV caches and
    their scales) shard ``P(data_axis)``; scalars (``cache_index``) and
    anything else replicate."""
    batch_sh = NamedSharding(mesh, P(data_axis))
    replicated = NamedSharding(mesh, P())

    def place(s):
        sh = batch_sh if s.ndim > 0 and s.shape[0] == batch else replicated
        return jnp.zeros(s.shape, s.dtype, device=sh)

    return place, batch_sh, replicated


def bucketed_prefill_len(prompt_lengths) -> int:
    """Static prefill length, computed HOST-SIDE before any device placement
    (a batch-sharded array could span non-addressable devices). Clamped to
    1: a zero-length row means position 0 is already generated, so the
    serial loop must start at t=0 (the loop body at position t decides
    token t+1 — the last prefix token must go through the loop to produce
    the first prediction).

    Bucketed DOWN to a power of two: prefill_len is part of the
    compile-cache key, and with naturally varied prompt lengths an exact
    value would compile a fresh decode executable per distinct
    batch-minimum (thrashing the lru cache). Rounding down is always safe —
    positions between the bucketed prefill and each row's true prompt
    length are replayed by the serial loop's keep-prompt path — and costs
    at most 2x the prefill tokens while capping the variants at log2(T).
    Shared by :func:`generate` and ``speculative.speculative_generate`` so
    both paths bucket identically."""
    min_len = int(np.min(np.asarray(prompt_lengths)))
    if min_len < 0:
        raise ValueError(f"prompt lengths must be >= 0, got {min_len}")
    if min_len == 0:
        # A zero-length row has NO common prefix: any batched prefill would
        # feed that row's pad tokens as if they were prompt, corrupting its
        # cache before the serial loop's keep-prompt logic can take over.
        # Everything runs through the serial loop instead.
        return 1
    return 1 << (min_len.bit_length() - 1)


def generate(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    prompt_lengths: Optional[jnp.ndarray] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    rng: Optional[jax.Array] = None,
    pad_token: int = 0,
    mesh: Optional[Mesh] = None,
    data_axis: str = "data",
    param_shardings=None,
    quantize: bool = False,
    quantized_cache: bool = False,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations for ``prompt`` ``[B, T0]``.

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature, optionally truncated to the ``top_k`` most likely
    tokens and/or the ``top_p`` nucleus (smallest set of tokens reaching
    ``top_p`` cumulative mass; 0 or >= 1 disables). When both are given,
    top-k truncates first and the nucleus is computed over the renormalized
    survivors. ``prompt_lengths`` ([B]) supports ragged prompts padded to T0
    with ``pad_token`` — generation for each row starts after its own length.
    Returns ``[B, T0 + max_new_tokens]`` token ids.

    With ``mesh``, decoding runs sharded: tokens and every KV-cache buffer are
    placed ``P(data_axis)`` (batch-sharded — at ``[B, 32k, H, D]`` the cache,
    not the params, is the memory that matters), params are replicated unless
    ``param_shardings`` provides a placement: e.g. megatron TP via
    ``make_param_specs(params, TRANSFORMER_TP_RULES, mesh=mesh)`` from
    ``parallel.partitioning``, with each spec wrapped in
    ``NamedSharding(mesh, spec)`` — GSPMD then shards the per-token matmuls
    and the caches' head dim follows (see tests/test_generation.py).
    The decode hot loop itself feeds ONE token per step, so the flash kernel
    (built for long query blocks) does not apply; cache reads stay the
    einsum-over-cache path, which XLA fuses well at ``T_step=1``.

    ``quantize=True`` stores the matmul weights as int8 + per-channel scales
    (``ops.quant``, symmetric absmax) and dequantizes INSIDE the compiled
    decode loop — decode is HBM-bound on weight reads, so int8 halves the
    traffic on the quantized weights. Greedy outputs typically match the
    full-precision path exactly (see tests/test_quant.py).

    ``quantized_cache=True`` additionally stores the KV caches as int8 with
    per-(token, head) scales (``models.transformer.Attention``): at long
    context the ``[B, T, H, D]`` caches dominate decode memory and traffic,
    and this halves both. Composes with ``quantize`` and with the mesh path
    (the scale buffers lead with the batch dim, so they shard ``P(data)``).
    """
    clone_kw = {"decode": True}
    if quantized_cache:  # only models with the attribute support it
        clone_kw["quantized_cache"] = True
    decode_model = model.clone(**clone_kw)
    if quantize:
        from distributed_pytorch_tpu.ops.quant import (
            QuantTensor,
            quantize_pytree,
            quantize_shardings,
        )

        already = any(
            isinstance(leaf, QuantTensor)
            for leaf in jax.tree_util.tree_leaves(
                params, is_leaf=lambda x: isinstance(x, QuantTensor)
            )
        )
        if param_shardings is not None:
            if already:
                raise ValueError(
                    "pass the UNquantized params when combining quantize="
                    "True with param_shardings; the sharding tree is lifted "
                    "onto the quantized tree internally"
                )
            # Lift the param shardings onto the quantized tree (int8 q keeps
            # the kernel's sharding; per-channel scales drop contract axes).
            param_shardings = quantize_shardings(param_shardings, params)
        # Accept a pre-quantized tree (quantize_pytree run once by the
        # caller) so repeated generate() calls don't pay re-quantization.
        if not already:
            params = quantize_pytree(params)
            from distributed_pytorch_tpu.ops.quant import quant_coverage

            coverage = quant_coverage(params)
            if coverage < 0.5:
                import warnings

                warnings.warn(
                    f"quantize=True matched only {coverage:.0%} of param "
                    "elements — the quant rules likely don't cover this "
                    "model's kernels (see ops.quant.TRANSFORMER_QUANT_RULES); "
                    "decode will still read the unmatched weights in full "
                    "precision",
                    stacklevel=2,
                )
    batch, prompt_len = prompt.shape
    total_len = prompt_len + max_new_tokens
    if prompt_lengths is None:
        prompt_lengths = jnp.full((batch,), prompt_len, jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    # Size the KV caches from abstract shapes only — eval_shape traces init
    # without running it, so no throwaway params and no full-length forward.
    abstract = jax.eval_shape(
        decode_model.init,
        jax.random.PRNGKey(0),
        jnp.zeros((batch, total_len), jnp.int32),
    )["cache"]

    tokens0 = jnp.concatenate(
        [
            jnp.asarray(prompt, jnp.int32),
            jnp.full((batch, max_new_tokens), pad_token, jnp.int32),
        ],
        axis=1,
    )
    prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
    prefill_len = bucketed_prefill_len(prompt_lengths)

    if mesh is not None:
        place, batch_sh, replicated = batch_sharding_placer(
            mesh, data_axis, batch
        )
        cache = jax.tree_util.tree_map(place, abstract)
        tokens0 = jax.device_put(tokens0, batch_sh)
        prompt_lengths = jax.device_put(prompt_lengths, batch_sh)
        params = jax.device_put(params, param_shardings or replicated)
        rng = jax.device_put(rng, replicated)
    else:
        cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), abstract
        )

    run = _compiled_run(
        decode_model, total_len, float(temperature), int(top_k),
        float(top_p), prefill_len,
    )
    return run(params, tokens0, cache, prompt_lengths, rng)


@functools.lru_cache(maxsize=32)
def _compiled_run(
    decode_model,
    total_len: int,
    temperature: float,
    top_k: int,
    top_p: float = 0.0,
    prefill_len: int = 1,
):
    """Jitted decode loop, cached per (model config, length, sampling config,
    prefill length) so repeated generate() calls with the same shapes reuse
    the executable (flax modules are frozen dataclasses, hence hashable
    cache keys)."""
    sample = make_sampler(temperature, top_k, top_p)

    def run(params, tokens, cache, prompt_lengths, rng):
        batch = tokens.shape[0]

        if prefill_len > 1:
            # One batched forward over the common prefix: every row's tokens
            # at positions [0, prefill_len-1) are true prompt tokens (it is
            # the MINIMUM prompt length), so the caches fill in parallel and
            # the serial loop starts at prefill_len-1 with cache_index
            # already there — the same invariant (cache_index == t at body
            # entry) the single-token path maintains.
            chunk = tokens[:, : prefill_len - 1]
            _, cache = decode_token_step(decode_model, params, cache, chunk)

        def body(t, carry):
            tokens, cache, rng = carry
            current = jax.lax.dynamic_slice(tokens, (0, t), (batch, 1))
            # decode_token_step dequantizes (a no-op tree_map when nothing
            # is quantized) INSIDE the loop body: the int8->compute-dtype
            # convert is a producer each weight's consumer matmul fuses, so
            # the loop reads int8 from HBM.
            last_logits, cache = decode_token_step(
                decode_model, params, cache, current
            )
            rng, step_rng = jax.random.split(rng)
            proposed = sample(last_logits, step_rng)  # [B]
            # Inside each row's prompt, keep the prompt token; past it, take
            # the sample. (t+1 is the position being decided.)
            keep_prompt = (t + 1) < prompt_lengths
            existing = jax.lax.dynamic_slice(tokens, (0, t + 1), (batch, 1))[:, 0]
            next_token = jnp.where(keep_prompt, existing, proposed)
            tokens = jax.lax.dynamic_update_slice(
                tokens, next_token[:, None], (0, t + 1)
            )
            return tokens, cache, rng

        tokens, _, _ = jax.lax.fori_loop(
            prefill_len - 1, total_len - 1, body, (tokens, cache, rng)
        )
        return tokens

    # No donate_argnums: the cache lives its whole life INSIDE the fori_loop
    # carry, where XLA already updates it in place; it is not a jit output, so
    # donating its input buffer has nothing to alias against and only produced
    # a "Some donated buffers were not usable" warning every call.
    return jax.jit(run)


def generate_text_ids(model, params, prompt_ids, max_new_tokens, **kw) -> np.ndarray:
    """Convenience wrapper returning numpy ids."""
    return np.asarray(
        generate(model, params, jnp.asarray(prompt_ids), max_new_tokens, **kw)
    )


def beam_search(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    beam_size: int = 4,
    length_penalty: float = 0.0,
    mesh: Optional[Mesh] = None,
    data_axis: str = "data",
):
    """Fixed-length beam search over the KV-cache decode path: maintain the
    ``beam_size`` highest-log-probability continuations per batch row, one
    compiled ``fori_loop`` like :func:`generate`.

    Returns ``(tokens, scores)``: ``tokens`` is ``[B, beam, T0 + new]``
    sorted best-first, ``scores`` is ``[B, beam]`` — the summed next-token
    log-probabilities of each continuation, divided by
    ``(new_tokens) ** length_penalty`` when a penalty is set (0 = raw sum;
    GNMT-style normalization at 1.0). The best row's raw score EQUALS the
    full-forward log-prob sum of its tokens (pinned by test — the cache
    reorder below is the part that could silently break this).

    TPU shape: beams live flattened in the batch dim (``[B*beam, ...]``),
    so every model call is the same single-token decode the greedy path
    compiles; the per-step beam reorder is a ``jnp.take`` of every cache
    leaf along that dim (a gather XLA schedules well, but it does copy the
    cache each step — O(T^2) bytes over a decode, the classic beam cost).
    Uniform prompts only (no ``prompt_lengths``): ragged beams inside a
    prompt would force per-row divergence bookkeeping nobody needs —
    left-pad ragged batches instead. No EOS handling: this framework's
    models are tokenizer-free LMs; fixed-horizon search keeps shapes
    static (and XLA happy).

    With ``mesh``, the flattened ``[B*beam]`` dim shards ``P(data_axis)``
    (cache + tokens; params replicated). The per-step reorder gather's
    indices never cross a batch row's beam block, so when ``beam_size``
    beams land on one shard the gather stays device-local; either way the
    output is token-identical to the single-device run (pinned by test).
    """
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    decode_model = model.clone(decode=True)
    batch, prompt_len = prompt.shape
    total_len = prompt_len + max_new_tokens
    flat = batch * beam_size

    # Cache sized for [B]: the prefill runs ONCE per batch row and the
    # leaves are repeated to [B*beam] inside the compiled run — every beam
    # starts from the identical prompt, so prefilling flat would burn
    # beam_size x the prefill FLOPs and cache writes on bit-equal rows.
    abstract = jax.eval_shape(
        decode_model.init,
        jax.random.PRNGKey(0),
        jnp.zeros((batch, total_len), jnp.int32),
    )["cache"]
    tokens0 = jnp.concatenate(
        [
            jnp.repeat(jnp.asarray(prompt, jnp.int32), beam_size, axis=0),
            jnp.full((flat, max_new_tokens), 0, jnp.int32),
        ],
        axis=1,
    )
    if mesh is not None:
        # The prefill cache is [B]-sized (batch dim), the token buffer
        # [B*beam]; both lead with the dim that shards.
        place_b, batch_sh, replicated = batch_sharding_placer(
            mesh, data_axis, batch
        )
        cache = jax.tree_util.tree_map(place_b, abstract)
        tokens0 = jax.device_put(tokens0, batch_sh)
        params = jax.device_put(params, replicated)
    else:
        cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), abstract
        )
    run = _compiled_beam_run(
        decode_model, total_len, prompt_len, beam_size,
        float(length_penalty),
    )
    return run(params, tokens0, cache)


@functools.lru_cache(maxsize=16)
def _compiled_beam_run(decode_model, total_len, prompt_len, beam_size,
                       length_penalty):
    """Jitted beam loop, cached per (model config, lengths, beam)."""

    def run(params, tokens, cache):
        flat = tokens.shape[0]
        batch = flat // beam_size
        dtype = getattr(decode_model, "dtype", jnp.bfloat16)

        if prompt_len > 1:
            # Prefill at [B] (the cache arrives [B]-sized; beams are
            # identical here), then fan the filled cache out to [B*beam].
            chunk = tokens[::beam_size, : prompt_len - 1]
            _, up = decode_model.apply(
                {"params": dequantize_pytree(params, dtype), "cache": cache},
                chunk,
                mutable=["cache"],
            )
            cache = up["cache"]
        cache = jax.tree_util.tree_map(
            lambda leaf: jnp.repeat(leaf, beam_size, axis=0)
            if leaf.ndim > 0 and leaf.shape[0] == batch
            else leaf,
            cache,
        )

        # Only beam 0 is live at the start — every beam holds the same
        # prompt, and without this mask the first top-k would pick the
        # same token beam_size times.
        scores = jnp.tile(
            jnp.where(jnp.arange(beam_size) == 0, 0.0, -jnp.inf)[None, :],
            (batch, 1),
        )  # [B, beam]

        def body(t, carry):
            tokens, cache, scores = carry
            current = jax.lax.dynamic_slice(tokens, (0, t), (flat, 1))
            logits, up = decode_model.apply(
                {"params": dequantize_pytree(params, dtype), "cache": cache},
                current,
                mutable=["cache"],
            )
            cache = up["cache"]
            logp = jax.nn.log_softmax(
                logits[:, -1, :].astype(jnp.float32), axis=-1
            )  # [B*beam, V]
            v = logp.shape[-1]
            cand = scores[..., None] + logp.reshape(batch, beam_size, v)
            top, idx = jax.lax.top_k(
                cand.reshape(batch, beam_size * v), beam_size
            )  # [B, beam]
            parent = idx // v  # which beam each winner extends
            token = (idx % v).astype(jnp.int32)
            # Reorder beams: winner k of row b continues beam parent[b, k]
            # — gather tokens and every cache leaf along the flattened dim.
            flat_src = (
                jnp.arange(batch)[:, None] * beam_size + parent
            ).reshape(-1)  # [B*beam]
            tokens = jnp.take(tokens, flat_src, axis=0)
            cache = jax.tree_util.tree_map(
                lambda leaf: jnp.take(leaf, flat_src, axis=0)
                if leaf.ndim > 0 and leaf.shape[0] == flat
                else leaf,
                cache,
            )
            tokens = jax.lax.dynamic_update_slice(
                tokens, token.reshape(-1)[:, None], (0, t + 1)
            )
            return tokens, cache, top

        tokens, _, scores = jax.lax.fori_loop(
            prompt_len - 1, total_len - 1, body, (tokens, cache, scores)
        )
        if length_penalty and total_len > prompt_len:
            # (max_new_tokens == 0 would divide by 0.0 ** penalty == 0.)
            scores = scores / (
                float(total_len - prompt_len) ** length_penalty
            )
        # Sort best-first (top_k returns sorted, but the last reorder
        # interleaves; make the contract explicit).
        order = jnp.argsort(-scores, axis=-1)
        tokens = tokens.reshape(batch, beam_size, -1)
        tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
        return tokens, scores

    return jax.jit(run)
