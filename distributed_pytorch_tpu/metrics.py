"""Structured training metrics.

The reference computes loss but never logs it (SURVEY.md §5: ``print()``-only
observability, an unused ``SummaryWriter`` import at
``multigpu_profile.py:10``). We close that gap: per-epoch structured lines from
process 0, with optional TensorBoard scalars when a writer backend is
available.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from distributed_pytorch_tpu.parallel.bootstrap import is_main_process


class MetricLogger:
    """Process-0 metric emitter: one JSON line per report + optional TensorBoard."""

    def __init__(self, tensorboard_dir: Optional[str] = None):
        self._writer = None
        if tensorboard_dir and is_main_process():
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._writer = SummaryWriter(tensorboard_dir)
            except Exception:  # torch TB backend optional
                self._writer = None
        self._start = time.perf_counter()

    def log(self, step: int, **scalars: float) -> None:
        if not is_main_process():
            return
        record = {"step": int(step), "elapsed_s": round(time.perf_counter() - self._start, 3)}
        record.update({k: float(v) for k, v in scalars.items()})
        print(json.dumps(record), flush=True)
        if self._writer is not None:
            for key, value in scalars.items():
                self._writer.add_scalar(key, value, step)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.flush()
            self._writer.close()
