"""Structured training metrics.

The reference computes loss but never logs it (SURVEY.md §5: ``print()``-only
observability, an unused ``SummaryWriter`` import at
``multigpu_profile.py:10``). We close that gap: per-epoch structured lines from
process 0, with optional TensorBoard scalars when a writer backend is
available; :class:`ReservoirHistogram` adds bounded-memory latency quantiles
(p50/p95/p99) for the serving engine's TTFT/TPOT and the Trainer's step-time
cadence.
"""

from __future__ import annotations

import bisect
import json
import math
import random
import time
from typing import Dict, Optional

from distributed_pytorch_tpu.parallel.bootstrap import is_main_process


class ReservoirHistogram:
    """Bounded-memory quantile estimator: uniform reservoir sampling
    (Vitter's algorithm R) over a stream of observations.

    Counters alone (the old metrics surface) cannot answer "what is p99
    TTFT?" without keeping every sample; a reservoir keeps a fixed
    ``capacity`` (default 1024) uniform subsample regardless of stream
    length, so quantiles stay O(capacity) memory and are exact until the
    reservoir first overflows. Deterministic for a given ``seed`` and record
    order — the serving tests rely on that.

    ``sum``/``count``/``min``/``max`` are exact over the WHOLE stream (they
    are running aggregates, not reservoir-derived)."""

    def __init__(self, capacity: int = 1024, seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._rng = random.Random(seed)
        self._samples: list = []
        # Lazily-built sorted view, kept valid incrementally once a
        # quantile has been read: per-step SLO evaluation would otherwise
        # re-sort the full reservoir on every tick. None = not built.
        self._ordered: Optional[list] = None
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        if len(self._samples) < self.capacity:
            self._samples.append(value)
            if self._ordered is not None:
                bisect.insort(self._ordered, value)
        else:
            j = self._rng.randrange(self.count)
            if j < self.capacity:
                old = self._samples[j]
                self._samples[j] = value
                if self._ordered is not None:
                    self._ordered.pop(bisect.bisect_left(self._ordered, old))
                    bisect.insort(self._ordered, value)

    def quantile(self, q: float) -> float:
        """Linear-interpolated quantile over the reservoir; NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if not self._samples:
            return math.nan
        if self._ordered is None:
            self._ordered = sorted(self._samples)
        ordered = self._ordered
        pos = q * (len(ordered) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(ordered) - 1)
        frac = pos - lo
        return ordered[lo] * (1.0 - frac) + ordered[hi] * frac

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else math.nan

    def summary(self, prefix: str = "") -> Dict[str, float]:
        """``{count, mean, min, max, p50, p95, p99}``, optionally prefixed —
        ready to splat into :meth:`MetricLogger.log` or a JSON report."""
        if not self.count:
            return {f"{prefix}count": 0}
        return {
            f"{prefix}count": self.count,
            f"{prefix}mean": self.mean,
            f"{prefix}min": self.min,
            f"{prefix}max": self.max,
            f"{prefix}p50": self.quantile(0.50),
            f"{prefix}p95": self.quantile(0.95),
            f"{prefix}p99": self.quantile(0.99),
        }

    def state(self) -> Dict[str, object]:
        """JSON-serializable full state — exact aggregates plus the
        reservoir samples — the unit of cross-host aggregation: each host
        ships its state, one host folds them with :meth:`merge_state`."""
        return {
            "capacity": self.capacity,
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "samples": list(self._samples),
        }

    def merge_state(self, state: Dict[str, object]) -> None:
        """Fold another reservoir's :meth:`state` into this one. Exact
        aggregates (count/sum/min/max) combine exactly; the merged
        reservoir is a weighted subsample of the union — each retained
        sample stands for ``stream_count / n_samples`` observations of its
        source stream, and when the pooled samples overflow ``capacity``
        they are downsampled without replacement by those weights
        (Efraimidis-Spirakis keys on this reservoir's own RNG, so merges
        stay deterministic per seed and merge order). Merging an empty
        state is a no-op; merging INTO an empty reservoir adopts the
        incoming samples."""
        n = int(state["count"])
        if n == 0:
            return
        my_count = self.count
        theirs = [float(v) for v in state["samples"]]
        self.count += n
        self.sum += float(state["sum"])
        self.min = min(self.min, float(state["min"]))
        self.max = max(self.max, float(state["max"]))
        pool = [
            (v, my_count / len(self._samples)) for v in self._samples
        ] + [(v, n / len(theirs)) for v in theirs]
        if len(pool) <= self.capacity:
            self._samples = [v for v, _ in pool]
        else:
            keyed = sorted(
                pool,
                key=lambda vw: self._rng.random() ** (1.0 / vw[1]),
                reverse=True,
            )
            self._samples = [v for v, _ in keyed[: self.capacity]]
        self._ordered = None


class ReservoirGroup:
    """A fixed family of labeled :class:`ReservoirHistogram` reservoirs
    sharing one capacity — per-source latency splits, e.g. TTFT for
    prefix-cache hits vs misses. Labels are declared up front so the
    summary surface is stable (an unseen label reports ``count: 0`` rather
    than vanishing); each label gets a distinct derived seed so the
    reservoirs stay deterministic yet uncorrelated."""

    def __init__(self, labels, capacity: int = 1024, seed: int = 0):
        labels = tuple(labels)
        if not labels:
            raise ValueError("ReservoirGroup needs at least one label")
        if len(set(labels)) != len(labels):
            raise ValueError(f"duplicate labels: {labels}")
        self._hists: Dict[str, ReservoirHistogram] = {
            label: ReservoirHistogram(capacity, seed=seed + i)
            for i, label in enumerate(labels)
        }

    @property
    def labels(self):
        return tuple(self._hists)

    def __getitem__(self, label: str) -> ReservoirHistogram:
        return self._hists[label]

    def record(self, label: str, value: float) -> None:
        try:
            hist = self._hists[label]
        except KeyError:
            raise KeyError(
                f"unknown label {label!r}; declared: {self.labels}"
            ) from None
        hist.record(value)

    def summary(self, prefix: str = "") -> Dict[str, float]:
        """Every label's summary merged flat: ``{prefix}{label}_p50`` etc."""
        out: Dict[str, float] = {}
        for label, hist in self._hists.items():
            out.update(hist.summary(f"{prefix}{label}_"))
        return out

    def state(self) -> Dict[str, Dict[str, object]]:
        """Per-label :meth:`ReservoirHistogram.state` — the group's
        cross-host aggregation unit."""
        return {label: hist.state() for label, hist in self._hists.items()}

    def merge_state(self, state: Dict[str, Dict[str, object]]) -> None:
        """Fold another group's :meth:`state` in, label by label. Labels
        are a fixed declared family, so an unknown incoming label is a
        schema mismatch and raises (same contract as :meth:`record`)."""
        for label, sub in state.items():
            if label not in self._hists:
                raise KeyError(
                    f"unknown label {label!r}; declared: {self.labels}"
                )
            self._hists[label].merge_state(sub)


class MetricLogger:
    """Process-0 metric emitter: one JSON line per report + optional TensorBoard."""

    def __init__(self, tensorboard_dir: Optional[str] = None):
        self._writer = None
        if tensorboard_dir and is_main_process():
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._writer = SummaryWriter(tensorboard_dir)
            except Exception:  # torch TB backend optional
                self._writer = None
        self._start = time.perf_counter()

    def log(self, step: int, **scalars: float) -> None:
        if not is_main_process():
            return
        record = {"step": int(step), "elapsed_s": round(time.perf_counter() - self._start, 3)}
        record.update({k: float(v) for k, v in scalars.items()})
        print(json.dumps(record), flush=True)
        if self._writer is not None:
            for key, value in scalars.items():
                self._writer.add_scalar(key, value, step)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.flush()
            self._writer.close()
