"""Pallas TPU kernel: activation @ int8-weight matmul with in-VMEM dequant.

The guaranteed-fused counterpart to ``dequantize() + @``: the weight tile is
read from HBM as **int8**, converted and scaled in VMEM registers, and fed
straight to the MXU — the bf16/f32 weight tensor never exists in HBM. This
is the fallback for the case where XLA chooses to materialize the dequant
instead of fusing it into the dot (observed on the CPU backend; the TPU
fusion A/B is ``tools/decode_bench.py`` — see BASELINE.md "pending on-chip
measurements"). Decode-shaped: small-batch x [B, K] against q [K, N].

Grid: one program per N-block; K is kept whole in VMEM (int8 K x block_n
tiles are small — 8192 x 512 is 4 MB of the ~16 MB VMEM).

Off-TPU the public op falls back to the dequantize + matmul XLA path, so
tests run everywhere; ``interpret=True`` runs the actual kernel logic on
CPU for correctness tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributed_pytorch_tpu.ops.quant import QuantTensor, dequantize
from distributed_pytorch_tpu.utils.platform import on_tpu


def _kernel(x_ref, q_ref, s_ref, o_ref):
    x = x_ref[:]  # [B, K] float32
    w = q_ref[:].astype(jnp.float32)  # [K, bn] int8 -> f32, in VMEM
    acc = jax.lax.dot_general(
        x,
        w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = acc * s_ref[:]  # s: [1, bn] per-output-channel scales


def _pad_rows(x: jnp.ndarray, multiple: int) -> jnp.ndarray:
    rows = x.shape[0]
    padded = -(-rows // multiple) * multiple
    if padded == rows:
        return x
    return jnp.pad(x, ((0, padded - rows), (0, 0)))


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _quant_matmul_tpu(
    x: jnp.ndarray,
    q: jnp.ndarray,
    scale: jnp.ndarray,
    *,
    block_n: int,
    interpret: bool,
) -> jnp.ndarray:
    batch, k = x.shape
    n = q.shape[1]
    x32 = _pad_rows(x.astype(jnp.float32), 8)  # f32 sublane multiple
    grid = (n // block_n,)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((x32.shape[0], k), lambda j: (0, 0)),
            pl.BlockSpec((k, block_n), lambda j: (0, j)),
            pl.BlockSpec((1, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((x32.shape[0], block_n), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((x32.shape[0], n), jnp.float32),
        interpret=interpret,
    )(x32, q, scale)
    return out[:batch].astype(x.dtype)


def quant_matmul(
    x: jnp.ndarray,
    qt: QuantTensor,
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x [B, K] @ dequant(qt) [K, N] -> [B, N]`` reading int8 weights.

    ``qt`` must be a 2-D :class:`~.quant.QuantTensor` quantized over its
    contraction dim (``quantize_int8(w, (0,))`` — scale shape ``[1, N]``).
    Runs the Pallas kernel on TPU (or under ``interpret=True``); elsewhere
    falls back to the XLA dequant + matmul path.
    """
    if qt.q.ndim != 2 or qt.scale.shape != (1, qt.q.shape[1]):
        raise ValueError(
            f"need a 2-D weight quantized over dim 0; got q {qt.q.shape}, "
            f"scale {qt.scale.shape}"
        )
    n = qt.q.shape[1]
    use_kernel = interpret or on_tpu()
    if not use_kernel or n % block_n != 0:
        return (x @ dequantize(qt, x.dtype)).astype(x.dtype)
    return _quant_matmul_tpu(
        x, qt.q, qt.scale, block_n=block_n, interpret=interpret
    )
