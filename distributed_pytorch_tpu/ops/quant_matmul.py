"""Pallas TPU kernel: activation @ int8-weight matmul with in-VMEM dequant.

The guaranteed-fused counterpart to ``dequantize() + @``: the weight tile is
read from HBM as **int8**, converted and scaled in VMEM registers, and fed
straight to the MXU — the bf16/f32 weight tensor never exists in HBM. This
is the fallback for the case where XLA chooses to materialize the dequant
instead of fusing it into the dot (observed on the CPU backend; the TPU
fusion A/B is ``tools/decode_bench.py`` — see BASELINE.md "pending on-chip
measurements"). Decode-shaped: small-batch x [B, K] against q [K, N].

Grid: ``(N/block_n, K/block_k)`` — K is TILED, not held whole in VMEM.
TPU grid execution is sequential with the last dimension fastest, so each
output block accumulates over its K tiles in place and applies the
per-channel scales once on the final tile. Per-program VMEM residency is
``block_k * block_n`` int8 (+ its f32 convert) plus the small x/out tiles,
so arbitrary K fits; shapes whose dims no supported tile divides fall back
to the XLA dequant + matmul path instead of failing in the Mosaic compiler
(the n % block_n fallback generalized, per ADVICE round 3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributed_pytorch_tpu.ops.quant import QuantTensor, dequantize
from distributed_pytorch_tpu.utils.platform import on_tpu

# Candidate K-tile sizes, largest first: bigger tiles amortize grid overhead;
# 128 is the MXU contraction width and the f32 lane tile, so every candidate
# keeps the x tile lane-aligned. 2048 int8 x 512 lanes = 1 MB int8 + 4 MB f32
# convert per tile — comfortable in ~16 MB VMEM.
_BLOCK_K_CANDIDATES = (2048, 1024, 512, 256, 128)


def _kernel(x_ref, q_ref, s_ref, o_ref):
    kid = pl.program_id(1)

    @pl.when(kid == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    x = x_ref[:]  # [B, block_k] float32
    w = q_ref[:].astype(jnp.float32)  # [block_k, bn] int8 -> f32, in VMEM
    o_ref[:] += jax.lax.dot_general(
        x,
        w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kid == pl.num_programs(1) - 1)
    def _finish():
        o_ref[:] = o_ref[:] * s_ref[:]  # s: [1, bn] per-output-channel


def _pad_rows(x: jnp.ndarray, multiple: int) -> jnp.ndarray:
    rows = x.shape[0]
    padded = -(-rows // multiple) * multiple
    if padded == rows:
        return x
    return jnp.pad(x, ((0, padded - rows), (0, 0)))


@functools.partial(
    jax.jit, static_argnames=("block_n", "block_k", "interpret")
)
def _quant_matmul_tpu(
    x: jnp.ndarray,
    q: jnp.ndarray,
    scale: jnp.ndarray,
    *,
    block_n: int,
    block_k: int,
    interpret: bool,
) -> jnp.ndarray:
    batch, k = x.shape
    n = q.shape[1]
    x32 = _pad_rows(x.astype(jnp.float32), 8)  # f32 sublane multiple
    grid = (n // block_n, k // block_k)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((x32.shape[0], block_k), lambda j, kk: (0, kk)),
            pl.BlockSpec((block_k, block_n), lambda j, kk: (kk, j)),
            pl.BlockSpec((1, block_n), lambda j, kk: (0, j)),
        ],
        # Independent of the K grid index: the same output block is revisited
        # across K tiles (sequential on TPU), accumulating in place.
        out_specs=pl.BlockSpec((x32.shape[0], block_n), lambda j, kk: (0, j)),
        out_shape=jax.ShapeDtypeStruct((x32.shape[0], n), jnp.float32),
        interpret=interpret,
    )(x32, q, scale)
    return out[:batch].astype(x.dtype)


def quant_matmul(
    x: jnp.ndarray,
    qt: QuantTensor,
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x [B, K] @ dequant(qt) [K, N] -> [B, N]`` reading int8 weights.

    ``qt`` must be a 2-D :class:`~.quant.QuantTensor` quantized over its
    contraction dim (``quantize_int8(w, (0,))`` — scale shape ``[1, N]``).
    Runs the Pallas kernel on TPU (or under ``interpret=True``); elsewhere —
    or when no supported tile divides N and K evenly — falls back to the
    XLA dequant + matmul path, so every shape computes correctly and only
    aligned ones take the kernel.
    """
    if qt.q.ndim != 2 or qt.scale.shape != (1, qt.q.shape[1]):
        raise ValueError(
            f"need a 2-D weight quantized over dim 0; got q {qt.q.shape}, "
            f"scale {qt.scale.shape}"
        )
    k, n = qt.q.shape
    block_k = next((c for c in _BLOCK_K_CANDIDATES if k % c == 0), None)
    use_kernel = interpret or on_tpu()
    if not use_kernel or n % block_n != 0 or block_k is None:
        return (x @ dequantize(qt, x.dtype)).astype(x.dtype)
    return _quant_matmul_tpu(
        x, qt.q, qt.scale, block_n=block_n, block_k=block_k, interpret=interpret
    )
