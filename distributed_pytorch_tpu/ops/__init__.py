"""Hot ops: attention cores (dense / ring / Pallas flash) and fused losses."""

from distributed_pytorch_tpu.ops.attention import (
    dot_product_attention,
    ring_attention,
)
from distributed_pytorch_tpu.ops.flash_attention import flash_attention
from distributed_pytorch_tpu.ops.fused_cross_entropy import (
    fused_linear_cross_entropy,
)

__all__ = [
    "dot_product_attention",
    "flash_attention",
    "fused_linear_cross_entropy",
    "ring_attention",
]
