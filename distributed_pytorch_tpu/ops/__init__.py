"""Hot ops: attention cores (dense / ring / Pallas flash), fused losses,
and int8 weight/KV quantization."""

from distributed_pytorch_tpu.ops.attention import (
    dot_product_attention,
    ring_attention,
    ulysses_attention,
)
from distributed_pytorch_tpu.ops.flash_attention import flash_attention
from distributed_pytorch_tpu.ops.fused_cross_entropy import (
    fused_linear_cross_entropy,
)
from distributed_pytorch_tpu.ops.quant import (
    QuantTensor,
    dequantize_pytree,
    quantize_int8,
    quantize_pytree,
)
from distributed_pytorch_tpu.ops.quant_matmul import quant_matmul

__all__ = [
    "QuantTensor",
    "dequantize_pytree",
    "dot_product_attention",
    "flash_attention",
    "fused_linear_cross_entropy",
    "quant_matmul",
    "quantize_int8",
    "quantize_pytree",
    "ring_attention",
    "ulysses_attention",
]
