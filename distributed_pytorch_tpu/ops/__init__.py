# Pallas/custom-op kernels live here (see distributed_pytorch_tpu/ops/).
