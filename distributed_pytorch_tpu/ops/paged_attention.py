"""Pallas paged-attention (flash-decode) over the serving engine's KV pools.

The serving decode hot path reads the paged KV cache — a global per-layer
pool ``[num_pages, page_size, Hkv, D]`` addressed through per-sequence block
tables — and until this module existed it did so via a plain-XLA gather
(``models/transformer.py:_paged_decode_step``): materialize every row's
``[pages_per_seq * page_size, Hkv, D]`` logical view in HBM, then attend.
``obs/roofline.py`` classifies that program bandwidth-bound; the gather
writes and re-reads the whole working set once per generated token.

This kernel fuses the block-table indirection into the attention loop:

* grid ``(slots, kv_blocks)`` with the KV dim innermost. Each grid step
  streams ``pages_per_block`` PHYSICAL pages HBM->VMEM — the block table
  rides as a scalar-prefetch operand (``pltpu.PrefetchScalarGridSpec``), so
  every page's ``BlockSpec`` index map picks its physical page id before the
  body runs and Pallas double-buffers the page fetches like any other block.
  The gathered logical view is never materialized.
* online softmax (FlashAttention-style running max / denominator / output
  accumulator in fp32 VMEM scratch, persisting across the KV blocks) with
  grouped-query head mapping: query head ``h`` reads kv head ``h // group``,
  the same contraction layout as the XLA reference's grouped einsums.
* masking is positional, exactly as the reference: key position ``kpos`` is
  visible iff ``kpos <= pos`` (the row's current absolute position). NULL
  pages (physical page 0 — inactive slots, padded table tails) are read but
  every one of their positions fails the visibility test, so their contents
  die in the softmax; KV blocks entirely past ``pos`` skip their MXU work
  via ``pl.when``.
* int8 KV pages: with ``k_scale``/``v_scale`` (``[num_pages, page_size,
  Hkv]`` float32, quantized on page write by the model) the kernel fetches
  int8 pages plus their scales and dequantizes in VMEM — HBM sees a quarter
  of the fp32 page bytes plus one scale per (slot, head).

``paged_attention_reference`` is the pure-XLA fallback: op-for-op the read
side of ``_paged_decode_step``, so an engine toggling the kernel off is
bitwise-identical to the pre-kernel engine. Mode resolution ("auto") uses
the kernel on TPU and the reference elsewhere; ``kernel="interpret"`` runs
the Pallas kernel through the interpreter — the CPU test rig's way of
exercising the real kernel code path.

GSPMD cannot partition a ``pallas_call``, so under a sharded jit pass
``mesh`` (as :class:`models.transformer.Attention` does): the kernel then
runs per-shard under ``shard_map`` with the KV-head dim split over the
``model`` axis — the same placement ``serving/mesh.py:KV_POOL_SPEC`` gives
the pools, so no collective is added beyond what the weight split implies.

Block sizing (``pages_per_block``) comes from the ``ops/flash_autotune``
harness' ``paged_decode`` family: measured winners on real hardware, a
seeded table entry for CPU/interpret so CI never autotunes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.ops.attention import NEG_INF
from distributed_pytorch_tpu.utils.platform import on_tpu

#: Accepted ``kernel=`` modes: "auto" resolves per backend, "pallas" forces
#: the compiled kernel, "interpret" runs the kernel through the Pallas
#: interpreter (CPU tests), "xla" forces the reference fallback.
KERNEL_MODES = ("auto", "pallas", "interpret", "xla")


def _shard_map(f, mesh, in_specs, out_specs):
    """``shard_map`` across the JAX versions this repo meets: the top-level
    ``jax.shard_map`` (with ``check_vma``) when present, else the
    ``jax.experimental`` original (with ``check_rep``). Unlike the training
    kernels' mesh paths, this one runs on the CPU test rig (interpret-mode
    parity matrix), so it cannot assume the newest API."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def resolve_kernel(kernel) -> str:
    """Settle a ``kernel=`` toggle to a concrete mode.

    ``"auto"``/``True`` pick the compiled kernel on TPU and the XLA
    reference everywhere else (the interpreter is orders of magnitude
    slower than dense XLA — it is a correctness tool, never an implicit
    fallback)."""
    if kernel is True or kernel in (None, "auto"):
        return "pallas" if on_tpu() else "xla"
    mode = str(kernel)
    if mode not in ("pallas", "interpret", "xla"):
        raise ValueError(
            f"unknown paged-attention kernel mode {kernel!r} "
            f"(expected one of {KERNEL_MODES})"
        )
    return mode


def paged_attention_reference(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    seq_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """The XLA gather path: op-for-op the read side of
    ``_paged_decode_step`` (gather each row's pages into its contiguous
    logical view, positional visibility mask, grouped GQA einsums, f32
    softmax) — the bitwise-parity anchor the kernel is tested against.

    ``q`` [S, T_step, H, D] is post-RoPE; ``seq_lens`` [S] is each row's
    token count BEFORE the step (= the absolute position of its first new
    token). With ``k_scale``/``v_scale`` the pools are int8 and dequantize
    at the gather, mirroring the contiguous quantized-cache idiom."""
    s, t_step, h, d = q.shape
    kv_heads = k_pool.shape[2]
    page = k_pool.shape[1]
    pages_per_seq = block_tables.shape[1]
    kv_len = pages_per_seq * page

    positions = seq_lens.astype(jnp.int32)[:, None] + jnp.arange(
        t_step, dtype=jnp.int32
    )
    keys = k_pool[block_tables].reshape(s, kv_len, kv_heads, d)
    values = v_pool[block_tables].reshape(s, kv_len, kv_heads, d)
    if k_scale is not None:
        ks = k_scale[block_tables].reshape(s, kv_len, kv_heads)
        vs = v_scale[block_tables].reshape(s, kv_len, kv_heads)
        keys = keys.astype(q.dtype) * ks[..., None].astype(q.dtype)
        values = values.astype(q.dtype) * vs[..., None].astype(q.dtype)
    scale = d**-0.5
    k_abs = jnp.arange(kv_len)[None, None, :]
    visible = k_abs <= positions[:, :, None]  # [S, T_step, K]
    group = h // kv_heads
    qg = q.reshape(s, t_step, kv_heads, group, d)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, keys) * scale
    logits = jnp.where(visible[:, None, None], logits, NEG_INF)
    weights = jax.nn.softmax(
        logits.astype(jnp.float32), axis=-1
    ).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, values)
    return out.reshape(s, t_step, h, d)


def _decode_kernel(
    bt_ref, lens_ref, q_ref, *refs, npb, group, sm_scale, quantized
):
    """One (slot, kv-block) grid step of the flash-decode kernel.

    ``refs`` unpacks to ``npb`` K page blocks, ``npb`` V page blocks,
    (when quantized) ``npb`` + ``npb`` scale blocks, the output block, and
    the three fp32 scratch accumulators (running max ``m``, denominator
    ``l``, output ``acc``) that persist across the innermost grid dim."""
    k_refs, v_refs = refs[:npb], refs[npb : 2 * npb]
    if quantized:
        ks_refs = refs[2 * npb : 3 * npb]
        vs_refs = refs[3 * npb : 4 * npb]
        o_ref, m_scr, l_scr, acc_scr = refs[4 * npb :]
    else:
        ks_refs = vs_refs = None
        o_ref, m_scr, l_scr, acc_scr = refs[2 * npb :]

    b = pl.program_id(0)
    j = pl.program_id(1)
    page = k_refs[0].shape[1]
    kv_heads, d = k_refs[0].shape[2], k_refs[0].shape[3]
    h = q_ref.shape[1]
    bkv = npb * page
    block_start = j * bkv

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = lens_ref[b]  # the decode token's absolute position (T_step == 1)

    # Blocks wholly past the row's current position contribute nothing —
    # skip their MXU work (the page DMAs still happen; the grid is static).
    # Every computed block has key `block_start` visible, so the running
    # max stays finite and no exp(NEG_INF - NEG_INF) row can arise.
    @pl.when(block_start <= pos)
    def _step():
        def load(page_refs, scale_refs):
            tiles = []
            for n in range(npb):
                tile = page_refs[n][0].astype(jnp.float32)
                if quantized:
                    tile = tile * scale_refs[n][0].astype(jnp.float32)[
                        ..., None
                    ]
                tiles.append(tile)
            return (
                jnp.concatenate(tiles, axis=0) if npb > 1 else tiles[0]
            )  # [bkv, Hkv, D] f32

        k = load(k_refs, ks_refs)
        v = load(v_refs, vs_refs)
        q = q_ref[0].astype(jnp.float32)  # [H, D]
        # Grouped-query mapping: query head h reads kv head h // group —
        # kv leads group, matching the reference's qg reshape.
        qg = q.reshape(kv_heads, group, d)
        kt = k.transpose(1, 0, 2)  # [Hkv, bkv, D]
        s_blk = (
            jax.lax.dot_general(
                qg, kt, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # [Hkv, group, bkv]
        kpos = block_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, bkv), 2
        )
        # Positional visibility IS the NULL-page mask: padded table tails
        # and inactive slots resolve to physical page 0, whose every key
        # position here fails kpos <= pos — their contents never survive.
        s_blk = jnp.where(kpos <= pos, s_blk, NEG_INF)
        s2 = s_blk.reshape(h, bkv)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=-1, keepdims=True))
        p = jnp.exp(s2 - m_new)
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
        pg = p.reshape(kv_heads, group, bkv)
        vt = v.transpose(1, 0, 2)  # [Hkv, bkv, D]
        pv = jax.lax.dot_general(
            pg, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [Hkv, group, D]
        acc_scr[:] = acc_scr[:] * correction + pv.reshape(h, d)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _paged_flash(
    q3, k_pool, v_pool, block_tables, seq_lens, k_scale, v_scale,
    *, pages_per_block, interpret,
):
    """Build and invoke the pallas_call for ``q3`` [S, H, D] (T_step == 1).

    Each of the ``pages_per_block`` pages in a KV block is its own input
    operand (the same pool array, aliased) with its own index map reading
    the scalar-prefetched block table — Pallas fetches ``pages_per_block``
    non-contiguous physical pages per grid step and the kernel concatenates
    them in VMEM. Logical pages past the table width clamp to the last
    entry; their key positions sit past any legal ``pos``, so the
    visibility mask kills the duplicates."""
    s, h, d = q3.shape
    page = k_pool.shape[1]
    kv_heads = k_pool.shape[2]
    pages_per_seq = block_tables.shape[1]
    group = h // kv_heads
    npb = max(1, min(int(pages_per_block), pages_per_seq))
    nblk = -(-pages_per_seq // npb)
    quantized = k_scale is not None

    def page_index(n):
        def index_map(b, j, bt, lens):
            logical = jnp.minimum(j * npb + n, pages_per_seq - 1)
            return (bt[b, logical], 0, 0, 0)

        return index_map

    def scale_index(n):
        def index_map(b, j, bt, lens):
            logical = jnp.minimum(j * npb + n, pages_per_seq - 1)
            return (bt[b, logical], 0, 0)

        return index_map

    def row_spec(shape):
        return pl.BlockSpec(
            shape, lambda b, j, bt, lens: (b, 0, 0),
            memory_space=pltpu.VMEM,
        )

    k_specs = [
        pl.BlockSpec(
            (1, page, kv_heads, d), page_index(n), memory_space=pltpu.VMEM
        )
        for n in range(npb)
    ]
    in_specs = [row_spec((1, h, d))] + k_specs + k_specs
    operands = [q3] + [k_pool] * npb + [v_pool] * npb
    if quantized:
        s_specs = [
            pl.BlockSpec(
                (1, page, kv_heads), scale_index(n),
                memory_space=pltpu.VMEM,
            )
            for n in range(npb)
        ]
        in_specs += s_specs + s_specs
        operands += [k_scale] * npb + [v_scale] * npb

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, nblk),
        in_specs=in_specs,
        out_specs=row_spec((1, h, d)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),  # running max m
            pltpu.VMEM((h, 128), jnp.float32),  # denominator l
            pltpu.VMEM((h, d), jnp.float32),  # output accumulator
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _decode_kernel, npb=npb, group=group, sm_scale=d**-0.5,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, d), q3.dtype),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        *operands,
    )


def paged_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    seq_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    kernel="auto",
    pages_per_block: Optional[int] = None,
    mesh=None,
    heads_axis: str = "model",
) -> jnp.ndarray:
    """Paged attention over ``q`` [S, T_step, H, D] against the page pools.

    Kernel-eligible steps (T_step == 1, the batched decode step) dispatch
    per ``kernel`` (see :func:`resolve_kernel`); chunked reads — prefill
    chunks, speculative verification — always take the XLA reference, which
    handles any T_step. ``pages_per_block`` defaults to the autotune
    harness' ``paged_decode`` family entry for this shape.

    Under a sharded jit pass ``mesh``: the kernel runs per-shard via
    ``shard_map`` with Q heads and KV heads (and scale heads) split over
    ``heads_axis`` and everything else replicated — the exact placement the
    engine's pool/param shardings already use, so no extra collective."""
    s, t_step, h, d = q.shape
    kv_heads = k_pool.shape[2]
    if h % kv_heads:
        raise ValueError(
            f"query heads {h} not divisible by kv heads {kv_heads}"
        )
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    mode = resolve_kernel(kernel)
    if mode == "xla" or t_step != 1:
        return paged_attention_reference(
            q, k_pool, v_pool, block_tables, seq_lens,
            k_scale=k_scale, v_scale=v_scale,
        )

    if pages_per_block is None:
        from distributed_pytorch_tpu.ops.flash_autotune import lookup_paged

        page = k_pool.shape[1]
        pages_per_block = lookup_paged(
            block_tables.shape[1] * page, page, d,
            dtype_name=jnp.dtype(q.dtype).name,
        )

    run = functools.partial(
        _paged_flash,
        pages_per_block=pages_per_block,
        interpret=(mode == "interpret"),
    )
    q3 = q.reshape(s, h, d)
    bt = block_tables.astype(jnp.int32)
    lens = seq_lens.astype(jnp.int32)

    tp = 1 if mesh is None else dict(mesh.shape).get(heads_axis, 1)
    if tp <= 1:
        out3 = run(q3, k_pool, v_pool, bt, lens, k_scale, v_scale)
        return out3.reshape(s, 1, h, d)

    if kv_heads % tp or h % tp:
        raise ValueError(
            f"heads (H={h}, Hkv={kv_heads}) not divisible by mesh axis "
            f"{heads_axis!r} (size {tp})"
        )
    args = [q3, k_pool, v_pool, bt, lens]
    specs = [
        P(None, heads_axis, None),
        P(None, None, heads_axis, None),
        P(None, None, heads_axis, None),
        P(None, None),
        P(None),
    ]
    if k_scale is not None:
        args += [k_scale, v_scale]
        specs += [P(None, None, heads_axis), P(None, None, heads_axis)]

    def local(*a):
        ks, vs = (a[5], a[6]) if len(a) == 7 else (None, None)
        return run(a[0], a[1], a[2], a[3], a[4], ks, vs)

    out3 = _shard_map(
        local, mesh, tuple(specs), P(None, heads_axis, None)
    )(*args)
    return out3.reshape(s, 1, h, d)
