"""Attention ops: dense reference implementation and ring attention for
sequence/context parallelism.

No reference analog — the reference's only attention is inside the
(commented-out) torchvision ViT at ``multigpu_profile.py:24``. Long-context
support is a first-class requirement of this framework, so the attention op is
built for it from the start:

* :func:`dot_product_attention` — plain fused-softmax attention; XLA fuses this
  well on TPU for moderate sequence lengths.
* :func:`ring_attention` — blockwise-streaming attention over a sharded
  sequence axis. Each device holds ``T/n`` of the sequence; K/V shards rotate
  around the mesh axis via ``jax.lax.ppermute`` (nearest-neighbor ICI traffic,
  no all-gather), and softmax is accumulated online (flash-attention style
  running max/denominator), so the full ``T x T`` score matrix never
  materializes and memory per chip stays ``O(T/n)``. This is the
  RingAttention construction (Liu et al. 2023), expressed with
  ``shard_map`` + ``ppermute`` so XLA schedules the collective permutes
  onto the ICI ring.
* :func:`ulysses_attention` — the all-to-all alternative (DeepSpeed-Ulysses
  style): one ``all_to_all`` turns sequence-sharded activations into
  head-sharded ones, attention runs fully local over the WHOLE sequence
  (single-chip flash kernel at full efficiency), one inverse ``all_to_all``
  restores the layout. Two collectives total instead of ``n`` hops; needs
  ``(H / tp) % sp == 0`` and per-chip memory ``O(T)`` for the exchanged
  activations — choose ring when T alone outgrows a chip, ulysses when it
  fits and heads are plentiful. Both compose with DP and TP; the model
  selects via ``TransformerLM(sequence_mode="ring" | "ulysses")``.

Shapes follow the TPU-friendly convention ``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_pytorch_tpu.utils.platform import on_tpu

NEG_INF = -1e30


def axis_if_divisible(mesh: Mesh, axis: Optional[str], size: int) -> Optional[str]:
    """``axis`` when it names a real mesh axis whose size divides ``size``,
    else None (replicate). The shared eligibility rule for sharding an array
    dim in the attention entry points."""
    if axis and axis in mesh.shape and size % mesh.shape[axis] == 0:
        return axis
    return None


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    window: int = 0,
) -> jnp.ndarray:
    """Reference attention. ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D].
    ``window > 0`` (causal only): position q sees keys in ``(q-window, q]``
    — the sliding-window (local) attention reference."""
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window requires causal=True")
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = jnp.arange(q.shape[1])[:, None]
        kv_pos = jnp.arange(k.shape[1])[None, :]
        ok = q_pos >= kv_pos
        if window:
            ok = ok & (q_pos - kv_pos < window)
        logits = jnp.where(ok, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _dense_hop(q32, k_blk, v_blk, *, positions=None, window=0):
    """One ring hop's local attention with its logsumexp, dense XLA math.
    ``q32``: [B, Tq, H, D] fp32; ``k_blk``/``v_blk``: [B, Tk, H, D].
    ``positions``: (q_pos, kv_pos) GLOBAL position arrays for a masked hop
    (the causal diagonal, or any hop of banded attention), None for a
    fully-visible hop; ``window > 0`` adds the band's lower bound.
    Returns ``(o [B,Tq,H,D] f32, lse [B,H,Tq] f32)``. Fully-masked rows
    come out with lse ~ NEG_INF, so the online merge weighs them to zero."""
    scale = q32.shape[-1] ** -0.5
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
    )
    if positions is not None:
        q_pos, kv_pos = positions
        ok = q_pos[:, None] >= kv_pos[None, :]
        if window:
            ok = ok & (q_pos[:, None] - kv_pos[None, :] < window)
        logits = jnp.where(ok, logits, NEG_INF)
    m = jnp.max(logits, axis=-1)  # [B,H,Tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
    o = o / l.transpose(0, 2, 1)[..., None]
    return o, m + jnp.log(l)


def _flash_hop(
    q, k_blk, v_blk, *, causal, block_q, block_k, interpret,
    window=0, q_offset=0,
):
    """One ring hop through the Pallas flash kernel (``[B,T,H,D]`` in/out,
    ``lse`` reshaped to the merge layout ``[B,H,Tq]``). ``window``/
    ``q_offset`` (static) select the kernel's banded path: masking sees the
    queries at ``local + q_offset`` global rows, so off-diagonal hops of
    sliding-window attention skip their out-of-band tiles in-kernel."""
    from distributed_pytorch_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    b, t, h, d = q.shape

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    o3, lse3 = flash_attention_with_lse(
        to3(q), to3(k_blk), to3(v_blk), causal, block_q, block_k, interpret,
        window, q_offset,
    )
    o = o3.reshape(b, h, t, d).transpose(0, 2, 1, 3).astype(jnp.float32)
    lse = lse3[..., 0].reshape(b, h, t)
    return o, lse


def _merge_hops(o_acc, lse_acc, o_hop, lse_hop):
    """Online-softmax merge of two partial attention results (flash-style
    running rescale, shared by the fori and the unrolled windowed loops)."""
    lse_new = jnp.logaddexp(lse_acc, lse_hop)
    w_acc = jnp.exp(lse_acc - lse_new).transpose(0, 2, 1)[..., None]
    w_hop = jnp.exp(lse_hop - lse_new).transpose(0, 2, 1)[..., None]
    return o_acc * w_acc + o_hop * w_hop, lse_new


def ring_live_hops(axis_size: int, t_local: int, window: int) -> int:
    """How many off-diagonal ring hops of banded (sliding-window) attention
    carry ANY live (q, k) pair. Hop ``s`` holds keys whose newest position
    trails this device's oldest query by ``(s-1)*t_local + 1`` rows — live
    only while that gap is ``< window``. The q/k offset is uniform around
    the ring, so the bound holds for every device and dead hops need not
    even rotate: ring cost drops from ``axis_size - 1`` hops to
    ``O(window / t_local)``."""
    if window <= 1:  # each query sees only itself
        return 0
    return min(axis_size - 1, (window - 2) // t_local + 1)


def _ring_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool,
    flash_blocks=None,
    interpret: bool = False,
    kv_groups: int = 1,
    window: int = 0,
) -> jnp.ndarray:
    """Per-device body (runs under shard_map): per-hop local attention with
    online lse merging over rotating K/V blocks.

    ``q,k,v``: [B, T_local, H, D] shards of the global sequence. Each hop's
    local block runs through the Pallas flash kernel when ``flash_blocks``
    is set (``(block_q, block_k)``), else dense XLA math; causal hops that
    are fully masked (this device's queries precede every key in the block)
    are skipped entirely via ``lax.cond`` — no score FLOPs, no exp, only the
    ring rotation they must forward anyway.

    ``window > 0`` (sliding-window attention, causal only) switches the hop
    loop from ``fori_loop`` to a PYTHON-unrolled loop over the statically
    known live hops (:func:`ring_live_hops`): hops wholly behind the band
    are never rotated at all — the ring's ppermute count drops from
    ``axis_size - 1`` to ``O(window / t_local)`` — and each live hop masks
    with global coordinates (static ``q_offset`` into the flash kernel, so
    its out-of-band tiles skip their MXU work too).

    Hop structure: block at step ``s`` is the K/V shard originally owned by
    device ``(my_index - s) % axis_size``. Step 0 is this device's own block
    — the causal *diagonal* — so the accumulator starts finite and the merge
    ``exp(lse - lse_new)`` never sees (-inf) - (-inf).
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]

    def hop(k_blk, v_blk, hop_causal, kv_index, q_offset=0):
        if kv_groups > 1:
            # GQA: blocks ROTATE at kv-head size (the ICI saving); the
            # broadcast to query heads is local per hop and fuses into the
            # hop's attention math.
            k_blk = jnp.repeat(k_blk, kv_groups, axis=2)
            v_blk = jnp.repeat(v_blk, kv_groups, axis=2)
        if flash_blocks is not None:
            # hop_causal selects the kernel's masked path: the diagonal
            # block (local positions align; q_offset 0) or, under window,
            # any live hop with its static global offset. Unmasked
            # otherwise.
            return _flash_hop(
                q, k_blk, v_blk, causal=hop_causal,
                block_q=flash_blocks[0], block_k=flash_blocks[1],
                interpret=interpret, window=window, q_offset=q_offset,
            )
        offsets = None
        if hop_causal:
            q_pos = my_index * t_local + jnp.arange(t_local)
            kv_pos = kv_index * t_local + jnp.arange(t_local)
            offsets = (q_pos, kv_pos)
        return _dense_hop(
            q.astype(jnp.float32), k_blk, v_blk, positions=offsets,
            window=window,
        )

    # Step 0: own block (the diagonal when causal).
    o_acc, lse_acc = hop(k, v, causal, my_index)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    if causal and window:
        # Banded: unroll over the statically-live hops only. Dead hops are
        # dead for EVERY device (the q/k offset is uniform around the
        # ring), so their rotations vanish from the program entirely.
        k_blk, v_blk = k, v
        for step in range(1, ring_live_hops(axis_size, t_local, window) + 1):
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            kv_index = (my_index - step) % axis_size

            def live(args, _k=k_blk, _v=v_blk, _kv=kv_index, _s=step):
                o_hop, lse_hop = hop(_k, _v, True, _kv, _s * t_local)
                return _merge_hops(*args, o_hop, lse_hop)

            # Wraparound hops (step > my_index) hold future keys: fully
            # masked causally, skip all compute.
            o_acc, lse_acc = jax.lax.cond(
                step <= my_index, live, lambda args: args, (o_acc, lse_acc)
            )
        return o_acc.astype(q.dtype)

    def body(step, carry):
        o_acc, lse_acc, k_blk, v_blk = carry
        # Rotate first: after `step` rotations this device holds the block of
        # device (my_index - step) % axis_size.
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        kv_index = (my_index - step) % axis_size

        def live(args):
            o_acc, lse_acc = args
            o_hop, lse_hop = hop(k_blk, v_blk, False, kv_index)
            return _merge_hops(o_acc, lse_acc, o_hop, lse_hop)

        if causal:
            # Hop blocks are fully visible iff the block's owner precedes
            # this device (kv_index < my_index ⇔ step <= my_index for
            # step >= 1); otherwise fully masked — skip all compute.
            o_acc, lse_acc = jax.lax.cond(
                step <= my_index, live, lambda args: args, (o_acc, lse_acc)
            )
        else:
            o_acc, lse_acc = live((o_acc, lse_acc))
        return o_acc, lse_acc, k_blk, v_blk

    o_acc, lse_acc, _, _ = jax.lax.fori_loop(
        1, axis_size, body, (o_acc, lse_acc, k, v)
    )
    return o_acc.astype(q.dtype)


def _ulysses_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool,
    flash_blocks=None,
    interpret: bool = False,
    window: int = 0,
) -> jnp.ndarray:
    """Per-device body (runs under shard_map): head-scatter / seq-gather
    all-to-all, full-sequence attention on the local heads, inverse
    all-to-all.

    ``q,k,v``: [B, T_local, H_local, D] shards. The forward ``all_to_all``
    splits the heads dim across the axis and concatenates the sequence dim
    (tiled, source-device order = global sequence order), yielding
    [B, T, H_local/sp, D]; attention then needs NO cross-device math at all
    — the causal mask is the ordinary full-sequence one (optionally banded:
    ``window`` composes trivially here) — and the inverse exchange restores
    the sequence sharding.
    """
    def scatter_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if flash_blocks is not None:
        from distributed_pytorch_tpu.ops.flash_attention import (
            flash_attention_4d,
        )

        o = flash_attention_4d(
            qh, kh, vh, causal=causal,
            block_q=flash_blocks[0], block_k=flash_blocks[1],
            interpret=interpret, window=window,
        )
    else:
        o = dot_product_attention(qh, kh, vh, causal=causal, window=window)
    return jax.lax.all_to_all(
        o, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis_name: str = "sequence",
    causal: bool = False,
    batch_axis: Optional[str] = "data",
    heads_axis: Optional[str] = "tensor",
    use_flash: Optional[bool] = None,
    interpret: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    window: int = 0,
) -> jnp.ndarray:
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all over the
    ``axis_name`` mesh axis redistributes sequence-sharded activations into
    head-sharded ones, each device runs ORDINARY full-sequence attention on
    ``H/sp`` heads (the Pallas flash kernel when legal), and the inverse
    all-to-all restores sequence sharding.

    The complementary strategy to :func:`ring_attention` (no reference
    analog — the reference has no attention at all; this implements the
    "all-to-all sequence/context parallelism" alternative named in the
    framework brief):

    * **ring**: K/V rotate hop-by-hop (nearest-neighbor ICI), compute and
      comm overlap across ``sp`` hops, per-device memory ``O(T/sp)`` — the
      choice when T alone is too big for one chip's HBM.
    * **ulysses**: two all-to-alls total (well-scheduled on ICI's all-to-all
      bandwidth), then the attention itself is entirely local, so the
      single-chip flash kernel runs at full efficiency over the WHOLE
      sequence — the choice while ``T`` fits per-chip memory and the head
      count is divisible; it caps ``sp`` at the (local) head count.

    Composes with TP the same way ring does: heads arrive sharded along
    ``heads_axis`` and stay sharded — Ulysses further splits the LOCAL
    heads, so it needs ``(H / tp) % sp == 0``.
    """
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window requires causal=True")
    seq_size = mesh.shape.get(axis_name, 1)
    if seq_size == 1:
        return dot_product_attention(q, k, v, causal=causal, window=window)
    b, t, h, d = q.shape
    if t % seq_size != 0:
        raise ValueError(
            f"sequence length {t} not divisible by mesh axis "
            f"{axis_name!r} ({seq_size})"
        )
    heads_spec = axis_if_divisible(mesh, heads_axis, h)
    h_local = h // mesh.shape[heads_spec] if heads_spec else h
    if h_local % seq_size != 0:
        raise ValueError(
            f"ulysses needs local head count {h_local} divisible by mesh "
            f"axis {axis_name!r} ({seq_size}); use ring_attention for "
            "head-starved configs"
        )

    from distributed_pytorch_tpu.ops.flash_attention import gate_flash_blocks

    # Blocks are resolved for the FULL sequence: post-exchange attention is
    # global-T on local heads.
    use_flash, flash_blocks = gate_flash_blocks(
        t, d, q.dtype, causal, interpret, block_q, block_k, use_flash
    )
    spec = P(
        axis_if_divisible(mesh, batch_axis, b),
        axis_name,
        heads_spec,
        None,
    )
    body = functools.partial(
        _ulysses_shard,
        axis_name=axis_name,
        causal=causal,
        flash_blocks=flash_blocks,
        interpret=interpret,
        window=window,
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis_name: str = "sequence",
    causal: bool = False,
    batch_axis: Optional[str] = "data",
    heads_axis: Optional[str] = "tensor",
    use_flash: Optional[bool] = None,
    interpret: bool = False,
    block_q: Optional[int] = None,  # None: measured table (flash_autotune)
    block_k: Optional[int] = None,
    kv_groups: int = 1,
    window: int = 0,
) -> jnp.ndarray:
    """Sequence-parallel attention over globally-shaped arrays.

    ``window > 0`` (causal only) composes sliding-window attention with the
    ring: live hops mask to the band (in-kernel tile skipping included) and
    hops wholly outside the band are NEVER ROTATED — the hop loop unrolls
    to the static :func:`ring_live_hops` bound, so both compute AND ICI
    traffic drop from O(T) to O(window) per query block.

    ``kv_groups > 1`` is grouped-query attention: ``k``/``v`` carry
    ``H / kv_groups`` heads and ROTATE at that size (the ppermute bytes are
    where sequence-parallel GQA saves); each hop broadcasts them to the
    query heads locally.

    Inputs are global ``[B, T, H, D]`` arrays whose sequence dim is (to be)
    sharded along ``axis_name``; the shard_map splits them, runs the ring, and
    reassembles. Degenerates to one dense block when the axis has size 1.

    Each ring hop's local block runs through the Pallas flash kernel
    (``use_flash``: None = auto — on when the backend is TPU, or when
    ``interpret`` is set, and the local block tiles legally), composing the
    cross-chip ring with the single-chip tiled kernel: per-hop memory drops
    from ``O(T_local^2)`` scores to ``O(block)``, and fully-masked causal
    hops skip their compute entirely (only the ring rotation remains).

    Under tensor parallelism the heads dim arrives sharded along
    ``heads_axis``; the shard_map keeps it sharded (heads are independent in
    attention), so SP x TP composes without gathering activations.
    """
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window requires causal=True")
    seq_size = mesh.shape.get(axis_name, 1)
    if seq_size == 1:
        # Mesh has no (or a trivial) sequence axis: plain dense attention.
        return dot_product_attention(q, k, v, causal=causal, window=window)
    if q.shape[1] % seq_size != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name!r} ({seq_size})"
        )

    from distributed_pytorch_tpu.ops.flash_attention import gate_flash_blocks

    # Blocks are resolved for the LOCAL hop length: each ring hop attends
    # one T/sp-long K/V block.
    t_local = q.shape[1] // seq_size
    use_flash, hop_blocks = gate_flash_blocks(
        t_local, q.shape[-1], q.dtype, causal, interpret,
        block_q, block_k, use_flash,
    )
    q_heads_spec = axis_if_divisible(mesh, heads_axis, q.shape[2])
    kv_heads_spec = axis_if_divisible(mesh, heads_axis, k.shape[2])
    if q_heads_spec is not None and kv_heads_spec is None and kv_groups > 1:
        # Launch-time guard: sharded query heads with replicated kv heads
        # would mismatch per-device head counts only deep inside shard_map.
        raise ValueError(
            f"GQA ring attention under tensor parallelism needs the kv "
            f"head count ({k.shape[2]}) divisible by mesh axis "
            f"{heads_axis!r} ({mesh.shape[heads_axis]}); keep "
            "n_kv_heads % tp == 0"
        )
    spec = P(
        axis_if_divisible(mesh, batch_axis, q.shape[0]),
        axis_name,
        q_heads_spec,
        None,
    )
    kv_spec = P(
        axis_if_divisible(mesh, batch_axis, k.shape[0]),
        axis_name,
        kv_heads_spec,
        None,
    )
    body = functools.partial(
        _ring_attention_shard,
        axis_name=axis_name,
        causal=causal,
        flash_blocks=hop_blocks,
        interpret=interpret,
        kv_groups=kv_groups,
        window=window,
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, kv_spec, kv_spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
