"""Attention ops: dense reference implementation and ring attention for
sequence/context parallelism.

No reference analog — the reference's only attention is inside the
(commented-out) torchvision ViT at ``multigpu_profile.py:24``. Long-context
support is a first-class requirement of this framework, so the attention op is
built for it from the start:

* :func:`dot_product_attention` — plain fused-softmax attention; XLA fuses this
  well on TPU for moderate sequence lengths.
* :func:`ring_attention` — blockwise-streaming attention over a sharded
  sequence axis. Each device holds ``T/n`` of the sequence; K/V shards rotate
  around the mesh axis via ``jax.lax.ppermute`` (nearest-neighbor ICI traffic,
  no all-gather), and softmax is accumulated online (flash-attention style
  running max/denominator), so the full ``T x T`` score matrix never
  materializes and memory per chip stays ``O(T/n)``. This is the
  RingAttention construction (Liu et al. 2023), expressed with
  ``shard_map`` + ``ppermute`` so XLA schedules the collective permutes
  onto the ICI ring.

Shapes follow the TPU-friendly convention ``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def axis_if_divisible(mesh: Mesh, axis: Optional[str], size: int) -> Optional[str]:
    """``axis`` when it names a real mesh axis whose size divides ``size``,
    else None (replicate). The shared eligibility rule for sharding an array
    dim in the attention entry points."""
    if axis and axis in mesh.shape and size % mesh.shape[axis] == 0:
        return axis
    return None


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
) -> jnp.ndarray:
    """Reference attention. ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D]."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = jnp.arange(q.shape[1])[:, None]
        kv_pos = jnp.arange(k.shape[1])[None, :]
        logits = jnp.where(q_pos >= kv_pos, logits, NEG_INF)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _ring_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool,
) -> jnp.ndarray:
    """Per-device body (runs under shard_map): online-softmax over rotating
    K/V blocks. ``q,k,v``: [B, T_local, H, D] shards of the global sequence."""
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = q.shape[-1] ** -0.5

    q32 = q.astype(jnp.float32)
    q_pos = my_index * t_local + jnp.arange(t_local)

    def body(step, carry):
        o, m, l, kv = carry
        k_blk, v_blk = kv
        # Block `step` holds the K/V shard originally owned by device
        # (my_index - step) mod axis_size.
        kv_index = (my_index - step) % axis_size
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        if causal:
            kv_pos = kv_index * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= kv_pos[None, :]
            logits = jnp.where(mask, logits, NEG_INF)
        blk_max = jnp.max(logits, axis=-1)  # [B,H,Tq]
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])  # [B,H,Tq,Tk]
        new_l = l * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        new_o = o * correction.transpose(0, 2, 1)[..., None] + pv
        # Rotate K/V one hop around the ring (nearest-neighbor ICI); the final
        # block needs no rotation, so skip that pair of collectives.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next, v_next = jax.lax.cond(
            step < axis_size - 1,
            lambda kv: (
                jax.lax.ppermute(kv[0], axis_name, perm),
                jax.lax.ppermute(kv[1], axis_name, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return new_o, new_m, new_l, (k_next, v_next)

    b, _, h, d = q.shape
    o0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, t_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    o, m, l, _ = jax.lax.fori_loop(0, axis_size, body, (o0, m0, l0, (k, v)))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis_name: str = "sequence",
    causal: bool = False,
    batch_axis: Optional[str] = "data",
    heads_axis: Optional[str] = "tensor",
) -> jnp.ndarray:
    """Sequence-parallel attention over globally-shaped arrays.

    Inputs are global ``[B, T, H, D]`` arrays whose sequence dim is (to be)
    sharded along ``axis_name``; the shard_map splits them, runs the ring, and
    reassembles. Degenerates to one dense block when the axis has size 1.

    Under tensor parallelism the heads dim arrives sharded along
    ``heads_axis``; the shard_map keeps it sharded (heads are independent in
    attention), so SP x TP composes without gathering activations.
    """
    seq_size = mesh.shape.get(axis_name, 1)
    if seq_size == 1:
        # Mesh has no (or a trivial) sequence axis: plain dense attention.
        return dot_product_attention(q, k, v, causal=causal)
    if q.shape[1] % seq_size != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name!r} ({seq_size})"
        )
    spec = P(
        axis_if_divisible(mesh, batch_axis, q.shape[0]),
        axis_name,
        axis_if_divisible(mesh, heads_axis, q.shape[2]),
        None,
    )
    body = functools.partial(
        _ring_attention_shard, axis_name=axis_name, causal=causal
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
