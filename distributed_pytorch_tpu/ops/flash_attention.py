"""Pallas flash attention for TPU: tiled online-softmax attention that never
materializes the ``[T, T]`` score matrix in HBM.

No reference analog (the reference's only attention is torchvision ViT's,
``multigpu_profile.py:24``); this is the single-chip hot-op complement to
:func:`.attention.ring_attention` (which handles the cross-chip sequence
dimension — each ring hop's local block can use this kernel's math).

Design (FlashAttention-2 style, built per the Pallas TPU playbook):

* forward: grid over ``(batch*heads, Tq/block_q)``; each program streams K/V
  ``block_k`` tiles from VMEM, maintaining the online-softmax running max
  ``m``, denominator ``l``, and accumulator ``o`` in fp32 registers; writes
  the normalized output plus the logsumexp row stats for the backward pass.
* backward: the standard two-kernel split — one grid over Q tiles producing
  ``dQ``, one over K/V tiles producing ``dK``/``dV`` — each recomputing
  probabilities from the saved logsumexp (no stored score matrix), with
  ``delta = rowsum(dO * O)`` precomputed outside.
* all matmuls run on the MXU with ``preferred_element_type=float32``;
  bfloat16 inputs are upcast per tile.

``interpret=True`` runs the same kernels on CPU for tests; on non-TPU
backends without interpret, :func:`flash_attention` falls back to the dense
XLA path automatically, as it does for shapes the tiling cannot cover.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.ops.attention import dot_product_attention

NEG_INF = -1e30


def _causal_mask(s, q_start, k_start):
    bq, bk = s.shape
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, scale, causal):
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    block_q, d = q.shape
    seq_k = k_ref.shape[1]
    q_start = pl.program_id(1) * block_q

    def body(j, carry):
        m, l, o = carry
        k_start = j * block_k
        k_blk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [block_q, block_k]
        if causal:
            s = _causal_mask(s, q_start, k_start)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_new = o * correction[:, None] + pv
        return m_new, l_new, o_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o0 = jnp.zeros((block_q, d), jnp.float32)
    n_blocks = seq_k // block_k
    if causal:
        # Blocks entirely above the diagonal contribute nothing — skip them.
        # (fori_loop accepts a traced bound, so this is per-program.)
        n_blocks = jnp.minimum(
            n_blocks, pl.cdiv(q_start + block_q, block_k)
        )
    m, l, o = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, o0))
    o_ref[0] = (o / l[:, None]).astype(o_ref.dtype)
    # lse rides as [BH, T, 1]: stats live in the sublane dim (lane dim 1), so
    # per-tile blocks and multiple-of-8 dynamic offsets stay Mosaic-legal for
    # any block size — lane-dim offsets would need 128 alignment.
    lse_ref[0, :, 0] = m + jnp.log(l)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_k, scale, causal
):
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    block_q, d = q.shape
    seq_k = k_ref.shape[1]
    q_start = pl.program_id(1) * block_q
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]

    def body(j, dq):
        k_start = j * block_k
        k_blk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jnp.zeros((block_q, d), jnp.float32)
    n_blocks = seq_k // block_k
    if causal:
        n_blocks = jnp.minimum(n_blocks, pl.cdiv(q_start + block_q, block_k))
    dq = jax.lax.fori_loop(0, n_blocks, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q, scale, causal,
):
    k = k_ref[0].astype(jnp.float32)  # [block_k, D]
    v = v_ref[0].astype(jnp.float32)
    block_k, d = k.shape
    seq_q = q_ref.shape[1]
    k_start = pl.program_id(1) * block_k

    def body(i, carry):
        dk, dv = carry
        q_start = i * block_q
        q_blk = q_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, pl.ds(q_start, block_q), 0]
        delta_blk = delta_ref[0, pl.ds(q_start, block_q), 0]
        s = (
            jax.lax.dot_general(
                q_blk, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [block_q, block_k]
        if causal:
            s = _causal_mask(s, q_start, k_start)
        p = jnp.exp(s - lse_blk[:, None])
        dv_new = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_blk[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    start = k_start // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(start, seq_q // block_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _row_spec(block, d):
    return pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM)


def _full_spec(t, d):
    return pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM)


def _vec_spec(block):
    # Row stats ride as [BH, T, 1] (stats along sublanes, trivial lane dim):
    # block (1, block, 1) is legal for any multiple-of-8 block because the
    # lane dim equals the full array dim.
    return pl.BlockSpec((1, block, 1), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM)


def _full_vec_spec(t):
    return pl.BlockSpec((1, t, 1), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    bh, seq, d = q.shape
    grid = (bh, seq // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, scale=d**-0.5, causal=causal
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _row_spec(block_q, d),
            _full_spec(seq, d),
            _full_spec(seq, d),
        ],
        out_specs=[_row_spec(block_q, d), _vec_spec(block_q)],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    bh, seq, d = q.shape
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, :, None]
    scale = d**-0.5

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, scale=scale, causal=causal
        ),
        grid=(bh, seq // block_q),
        in_specs=[
            _row_spec(block_q, d),
            _full_spec(seq, d),
            _full_spec(seq, d),
            _row_spec(block_q, d),
            _vec_spec(block_q),
            _vec_spec(block_q),
        ],
        out_specs=_row_spec(block_q, d),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, scale=scale, causal=causal
        ),
        grid=(bh, seq // block_k),
        in_specs=[
            _full_spec(seq, d),
            _row_spec(block_k, d),
            _row_spec(block_k, d),
            _full_spec(seq, d),
            _full_vec_spec(seq),
            _full_vec_spec(seq),
        ],
        out_specs=[_row_spec(block_k, d), _row_spec(block_k, d)],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(block: int, t: int) -> int | None:
    """Largest multiple of 8 that is <= ``block`` and divides ``t``
    (None when no such size exists — caller falls back to dense)."""
    b = min(block, t)
    b -= b % 8
    while b >= 8 and t % b != 0:
        b -= 8
    return b if b >= 8 else None


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
    mesh=None,
    batch_axis: str | None = "data",
    heads_axis: str | None = "tensor",
) -> jnp.ndarray:
    """Tiled attention over ``[B, T, H, D]`` (same convention as
    :func:`.attention.dot_product_attention`, to which it is numerically
    equivalent).

    Falls back to the dense XLA path when no multiple-of-8 block divides the
    sequence length, or when running on a non-TPU backend without
    ``interpret``.

    GSPMD cannot partition a ``pallas_call``, so under a sharded jit the bare
    kernel would make XLA all-gather the global batch onto every chip. Pass
    ``mesh`` (as the :class:`Attention` module does) to run the kernel under
    ``shard_map`` instead: each device computes only its ``batch_axis`` /
    ``heads_axis`` shard, preserving data/tensor parallelism.
    """
    b, t, h, d = q.shape
    if interpret is None:
        if jax.default_backend() != "tpu":
            # No TPU and no explicit interpret request: the dense XLA path is
            # far faster than the Pallas interpreter — use it.
            return dot_product_attention(q, k, v, causal=causal)
        interpret = False
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, t)
    if block_q is None or block_k is None:
        return dot_product_attention(q, k, v, causal=causal)

    def run_local(ql, kl, vl):
        bl, tl, hl, dl = ql.shape

        def to3(x):
            return x.transpose(0, 2, 1, 3).reshape(bl * hl, tl, dl)

        out = _flash(to3(ql), to3(kl), to3(vl), causal, block_q, block_k, interpret)
        return out.reshape(bl, hl, tl, dl).transpose(0, 2, 1, 3)

    if mesh is None:
        return run_local(q, k, v)

    def axis_if_divisible(axis, size):
        return (
            axis
            if (axis and axis in mesh.shape and size % mesh.shape[axis] == 0)
            else None
        )

    b_ax = axis_if_divisible(batch_axis, b)
    h_ax = axis_if_divisible(heads_axis, h)
    spec = P(b_ax, None, h_ax, None)
    return jax.shard_map(
        run_local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
