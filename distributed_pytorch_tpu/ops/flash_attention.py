"""Pallas flash attention for TPU: tiled online-softmax attention that never
materializes the ``[T, T]`` score matrix in HBM.

No reference analog (the reference's only attention is torchvision ViT's,
``multigpu_profile.py:24``); this is the single-chip hot-op complement to
:func:`.attention.ring_attention` (which handles the cross-chip sequence
dimension — each ring hop's local block can use this kernel's math).

Design (FlashAttention-2 style, built per the Pallas TPU playbook):

* forward: grid ``(batch*heads, Tq/block_q, Tk/block_k)`` with the K dim
  innermost — Pallas streams ``block_k`` K/V tiles HBM->VMEM with automatic
  double buffering, so VMEM stays ``O(block)`` at any sequence length. The
  online-softmax running max ``m``, denominator ``l``, and output accumulator
  live in fp32 VMEM scratch that persists across the K iterations; the last K
  step normalizes and writes the output tile plus logsumexp row stats.
* backward: the standard two-kernel split — one grid producing ``dQ`` (K
  innermost), one producing ``dK``/``dV`` (Q innermost) — each recomputing
  probabilities from the saved logsumexp (no stored score matrix), with
  ``delta = rowsum(dO * O)`` precomputed outside.
* causal programs skip the matmul work of fully-masked tiles via ``pl.when``.
* all matmuls keep their inputs in the source dtype (bfloat16 feeds the MXU
  at full rate — an f32 upcast would quarter matmul throughput) and
  accumulate in float32 via ``preferred_element_type``; softmax statistics
  (m, l, lse) are float32 throughout, and the probability/ds tiles are cast
  back to the input dtype for the second matmul of each kernel.

``interpret=True`` runs the same kernels on CPU for tests; on non-TPU
backends without interpret, :func:`flash_attention` falls back to the dense
XLA path automatically, as it does for shapes the tiling cannot cover.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.utils.platform import on_tpu
from distributed_pytorch_tpu.ops.attention import (
    NEG_INF,
    axis_if_divisible,
    dot_product_attention,
)


def _causal_mask(s, q_start, k_start, window=0):
    """Causal mask, optionally banded: with ``window > 0`` position q sees
    only keys in ``(q - window, q]`` — sliding-window (Mistral-style local)
    attention."""
    bq, bk = s.shape
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    ok = q_pos >= k_pos
    if window:
        ok = ok & (q_pos - k_pos < window)
    return jnp.where(ok, s, NEG_INF)


def _tile_live(q_start, block_q, k_start, block_k, causal, window):
    """Whether any (q, k) pair in this tile is unmasked: the causal upper
    bound plus (when windowed) the band's lower bound. Static Python bools
    when causal=False; traced predicates otherwise — both fine for
    ``pl.when``."""
    if not causal:
        return True
    live = k_start <= q_start + block_q - 1
    if window:
        live = live & (q_start - (k_start + block_k - 1) <= window - 1)
    return live


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, window=0, q_offset=0,
):
    block_q, d = q_ref.shape[1:]
    block_k = k_ref.shape[1]
    # q_offset shifts query positions for masking only (ring hops: this
    # device's queries sit q_offset rows below the visiting K/V block's
    # origin in the GLOBAL sequence, while both arrays index locally).
    q_start = pl.program_id(1) * block_q + q_offset
    k_idx = pl.program_id(2)
    k_start = k_idx * block_k

    @pl.when(k_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: tiles fully above the diagonal contribute nothing; windowed:
    # neither do tiles fully below the band — skip the MXU work for both
    # (the tile DMA still happens; the grid is static).
    live = _tile_live(q_start, block_q, k_start, block_k, causal, window)

    @pl.when(live)
    def _step():
        # Matmul inputs stay in the source dtype (bf16 runs the MXU at full
        # rate; an f32 upcast here quarters throughput) with f32 accumulation
        # via preferred_element_type. Softmax stats are f32 throughout.
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = (
            jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [block_q, block_k] f32
        if causal:
            s = _causal_mask(s, q_start, k_start, window)
        m_prev = m_scr[:, :1]  # [block_q, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * correction + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(k_idx == pl.num_programs(2) - 1)
    def _finish():
        l = l_scr[:, :1]
        # A query row whose every tile was skipped (possible only in a
        # banded off-diagonal ring hop: the whole row sits outside the
        # window) leaves l = 0 — emit 0 output and a NEG_INF lse so the
        # ring merge weighs the row to exactly zero instead of NaN (0/0).
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, :, :] = jnp.where(
            l == 0.0, NEG_INF, m_scr[:, :1] + jnp.log(l_safe)
        )


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale, causal, window=0, q_offset=0,
):
    block_q, d = q_ref.shape[1:]
    block_k = k_ref.shape[1]
    q_start = pl.program_id(1) * block_q + q_offset
    k_idx = pl.program_id(2)
    k_start = k_idx * block_k

    @pl.when(k_idx == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = _tile_live(q_start, block_q, k_start, block_k, causal, window)

    @pl.when(live)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [block_q, 1]
        delta = delta_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = (
            jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            s = _causal_mask(s, q_start, k_start, window)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(k_idx == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, scale, causal, window=0, q_offset=0,
):
    block_k, d = k_ref.shape[1:]
    block_q = q_ref.shape[1]
    k_start = pl.program_id(1) * block_k
    q_idx = pl.program_id(2)
    q_start = q_idx * block_q + q_offset

    @pl.when(q_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = _tile_live(q_start, block_q, k_start, block_k, causal, window)

    @pl.when(live)
    def _step():
        k = k_ref[0]
        v = v_ref[0]
        q_blk = q_ref[0]
        do_blk = do_ref[0]
        lse_blk = lse_ref[0]  # [block_q, 1]
        delta_blk = delta_ref[0]
        s = (
            jax.lax.dot_general(
                q_blk, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [block_q, block_k] f32
        if causal:
            s = _causal_mask(s, q_start, k_start, window)
        p = jnp.exp(s - lse_blk)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta_blk) * scale).astype(q_blk.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(q_idx == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _q_spec(block, d):
    return pl.BlockSpec(
        (1, block, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
    )


def _kv_spec(block, d):
    return pl.BlockSpec(
        (1, block, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM
    )


def _q_vec_spec(block):
    # Row stats ride as [BH, T, 1]: stats along sublanes, trivial lane dim —
    # legal for any multiple-of-8 block (lane-dim offsets would need 128
    # alignment).
    return pl.BlockSpec(
        (1, block, 1), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
    )


def _swap_q(spec_fn, block, *rest):
    """Same specs with the roles of grid dims 1/2 swapped (dK/dV kernel:
    grid is (bh, k_tile, q_tile))."""
    inner = spec_fn(block, *rest)
    return pl.BlockSpec(
        inner.block_shape,
        lambda b, i, j: inner.index_map(b, j, i),
        memory_space=pltpu.VMEM,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, block_q, block_k, interpret, window=0, q_offset=0):
    out, _ = _flash_fwd(
        q, k, v, causal, block_q, block_k, interpret, window, q_offset
    )
    return out


def _flash_fwd(
    q, k, v, causal, block_q, block_k, interpret, window=0, q_offset=0
):
    bh, seq, d = q.shape
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=d**-0.5, causal=causal, window=window,
            q_offset=q_offset,
        ),
        grid=(bh, seq // block_q, seq // block_k),
        in_specs=[
            _q_spec(block_q, d),
            _kv_spec(block_k, d),
            _kv_spec(block_k, d),
        ],
        out_specs=[_q_spec(block_q, d), _q_vec_spec(block_q)],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # denominator l
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out, (q, k, v, out, lse)


def _flash_bwd(
    causal, block_q, block_k, interpret, window, q_offset, residuals, g
):
    q, k, v, out, lse = residuals
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, :, None]
    return _flash_bwd_impl(
        causal, block_q, block_k, interpret, q, k, v, lse, g, delta,
        window=window, q_offset=q_offset,
    )


def _flash_bwd_impl(
    causal, block_q, block_k, interpret, q, k, v, lse, g, delta,
    window=0, q_offset=0,
):
    bh, seq, d = q.shape
    scale = d**-0.5

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, window=window,
            q_offset=q_offset,
        ),
        grid=(bh, seq // block_q, seq // block_k),
        in_specs=[
            _q_spec(block_q, d),
            _kv_spec(block_k, d),
            _kv_spec(block_k, d),
            _q_spec(block_q, d),
            _q_vec_spec(block_q),
            _q_vec_spec(block_q),
        ],
        out_specs=_q_spec(block_q, d),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, window=window,
            q_offset=q_offset,
        ),
        grid=(bh, seq // block_k, seq // block_q),
        in_specs=[
            _swap_q(_q_spec, block_q, d),
            # Grid dim 1 is the K tile here, so K/V use the dim-1 index map.
            _q_spec(block_k, d),
            _q_spec(block_k, d),
            _swap_q(_q_spec, block_q, d),
            _swap_q(_q_vec_spec, block_q),
            _swap_q(_q_vec_spec, block_q),
        ],
        out_specs=[_q_spec(block_k, d)] * 2,
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_with_lse(
    q, k, v, causal, block_q, block_k, interpret, window=0, q_offset=0
):
    """Tiled attention returning ``(out, lse)`` over ``[BH, T, D]`` inputs.

    The building block for composing this kernel with
    :func:`.attention.ring_attention`: each ring hop computes its local
    ``(out, lse)`` here and the hops merge online outside. Differentiable in
    BOTH outputs — the lse cotangent folds into the backward kernels as
    ``delta - dlse`` (since d(lse)/d(scores) is exactly the softmax ``p``,
    the same factor the dO path multiplies), so no extra kernel is needed.

    ``window``/``q_offset`` (causal only) make one call compute a ring hop of
    BANDED attention: masking sees query positions at ``local + q_offset``
    while keys stay local, so an off-diagonal hop (queries ``q_offset``
    rows below the visiting K/V block) masks with global coordinates and
    out-of-band tiles skip their MXU work.

    ``lse`` is ``[BH, T, 1]`` float32.
    """
    out, residuals = _flash_fwd(
        q, k, v, causal, block_q, block_k, interpret, window, q_offset
    )
    return out, residuals[-1]


def _flash_lse_fwd(
    q, k, v, causal, block_q, block_k, interpret, window=0, q_offset=0
):
    out, residuals = _flash_fwd(
        q, k, v, causal, block_q, block_k, interpret, window, q_offset
    )
    return (out, residuals[-1]), residuals


def _flash_lse_bwd(
    causal, block_q, block_k, interpret, window, q_offset, residuals,
    cotangents,
):
    g_out, g_lse = cotangents
    q, k, v, out, lse = residuals
    delta = (
        jnp.sum(g_out.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[
            :, :, None
        ]
        - g_lse.astype(jnp.float32)
    )
    return _flash_bwd_impl(
        causal, block_q, block_k, interpret, q, k, v, lse, g_out, delta,
        window=window, q_offset=q_offset,
    )


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_4d(
    q, k, v, *, causal, block_q, block_k, interpret, window=0
):
    """``[B, T, H, D]`` through the 3-D Pallas kernel and back — THE layout
    shim between the model convention and the kernel's ``[B*H, T, D]``.
    Shared by :func:`flash_attention`'s local body and
    ``attention.ulysses_attention`` so the convention can never diverge
    between entry points."""
    b, t, h, d = q.shape

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    out = _flash(
        to3(q), to3(k), to3(v), causal, block_q, block_k, interpret, window
    )
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def gate_flash_blocks(
    t: int,
    d: int,
    dtype,
    causal: bool,
    interpret: bool,
    block_q: int | None,
    block_k: int | None,
    use_flash: bool | None,
):
    """The shared flash-eligibility gate for the sequence-parallel entry
    points: resolve block sizes for a ``t``-long attention, fit them, apply
    the hardware lane rule, and settle ``use_flash`` (None = auto). Returns
    ``(use_flash, (fit_q, fit_k) or None)``; raises when the caller forced
    ``use_flash=True`` on an untileable shape. One implementation so a new
    Mosaic constraint (like the existing 128-lane check) lands everywhere
    at once."""
    if use_flash is False:
        return False, None
    block_q, block_k = resolve_blocks(
        block_q, block_k, t, d, dtype, causal, interpret
    )
    fit_q = _fit_block(block_q, t)
    fit_k = _fit_block(block_k, t)
    blocks_fit = fit_q is not None and fit_k is not None
    if blocks_fit and not interpret and (fit_k % 128 != 0):
        blocks_fit = False  # lane alignment (see flash_attention)
    if use_flash is None:
        use_flash = (on_tpu() or interpret) and blocks_fit
    elif not blocks_fit:  # use_flash is True here
        raise ValueError(
            f"use_flash=True but no legal flash tiling for T={t}"
        )
    return use_flash, ((fit_q, fit_k) if use_flash else None)


def _fit_block(block: int, t: int) -> int | None:
    """Largest multiple of 8 that is <= ``block`` and divides ``t``
    (None when no such size exists — caller falls back to dense)."""
    b = min(block, t)
    b -= b % 8
    while b >= 8 and t % b != 0:
        b -= 8
    return b if b >= 8 else None


def resolve_blocks(
    block_q: int | None,
    block_k: int | None,
    t: int,
    d: int,
    dtype,
    causal: bool,
    interpret: bool,
) -> tuple:
    """Fill unspecified block sizes from the measured table
    (:mod:`.flash_autotune`); with ``FLASH_AUTOTUNE=1`` on real hardware, run
    the measured sweep for this shape instead (compiles each candidate once,
    cached on disk thereafter)."""
    if block_q is not None and block_k is not None:
        return block_q, block_k
    from distributed_pytorch_tpu.ops.flash_autotune import (
        autotune,
        autotune_enabled,
        lookup,
    )

    dtype_name = jnp.dtype(dtype).name
    if (
        autotune_enabled()
        and not interpret
        and on_tpu()
        # Multi-process SPMD: the sweep's winner is timing-dependent, and
        # hosts choosing different blocks would trace divergent programs
        # around the same collectives (hang/crash). Every host must take
        # the deterministic table path instead.
        and jax.process_count() == 1
    ):
        bq, bk = autotune(t, d, dtype=dtype, causal=causal)
    else:
        bq, bk = lookup(t, d, dtype_name, causal)
    return block_q or bq, block_k or bk


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    window: int = 0,
    block_q: int | None = None,  # None: measured table / FLASH_AUTOTUNE sweep
    block_k: int | None = None,
    interpret: bool | None = None,
    mesh=None,
    batch_axis: str | None = "data",
    heads_axis: str | None = "tensor",
) -> jnp.ndarray:
    """Tiled attention over ``[B, T, H, D]`` (same convention as
    :func:`.attention.dot_product_attention`, to which it is numerically
    equivalent).

    Falls back to the dense XLA path when no multiple-of-8 block divides the
    sequence length, or when running on a non-TPU backend without
    ``interpret``.

    ``window > 0`` (causal only) is sliding-window attention: position q
    attends keys in ``(q - window, q]``. Tiles fully outside the band are
    SKIPPED (their DMA happens; their MXU work does not), so compute per
    step drops from O(T^2) toward O(T * window) as T grows past the window
    — the Mistral-style long-context compute lever.

    GSPMD cannot partition a ``pallas_call``, so under a sharded jit the bare
    kernel would make XLA all-gather the global batch onto every chip. Pass
    ``mesh`` (as the :class:`Attention` module does) to run the kernel under
    ``shard_map`` instead: each device computes only its ``batch_axis`` /
    ``heads_axis`` shard, preserving data/tensor parallelism.
    """
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window requires causal=True")
    b, t, h, d = q.shape
    if interpret is None:
        if not on_tpu():
            # No TPU and no explicit interpret request: the dense XLA path is
            # far faster than the Pallas interpreter — use it.
            return dot_product_attention(
                q, k, v, causal=causal, window=window
            )
        interpret = False
    # The shared gate (resolve + fit + the Mosaic 128-lane rule): with
    # use_flash=None it settles to False for untileable shapes -> dense
    # fallback, exactly the old inline behavior.
    use_flash, blocks = gate_flash_blocks(
        t, d, q.dtype, causal, interpret, block_q, block_k, None
    )
    if not use_flash:
        return dot_product_attention(q, k, v, causal=causal, window=window)
    block_q, block_k = blocks

    def run_local(ql, kl, vl):
        return flash_attention_4d(
            ql, kl, vl, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret, window=window,
        )

    if mesh is None:
        return run_local(q, k, v)

    spec = P(
        axis_if_divisible(mesh, batch_axis, b),
        None,
        axis_if_divisible(mesh, heads_axis, h),
        None,
    )
    return jax.shard_map(
        run_local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
