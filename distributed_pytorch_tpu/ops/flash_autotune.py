"""Measured block-size selection for the Pallas flash-attention kernel.

Replaces the round-1 hardcoded ``(512, 1024)`` guess (VERDICT item 8) with a
tiered lookup, cheapest first:

1. the in-process cache,
2. an explicit precomputed table file (``FLASH_BLOCKS_TABLE=/path.json`` —
   see the pod workflow below),
3. the on-disk cache of this machine's own measured sweeps (``~/.cache/...``),
4. a shipped table measured on real hardware (``DEFAULT_TABLE`` below, keyed
   by device kind), nearest-``T`` entry wins,
5. for device kinds with no measured entry, the VMEM-reasoned
   :func:`analytic_default` (largest legal tile, square-preferred — see its
   docstring; the old bare ``(512, 1024)`` guess remains only as the
   last-resort ``_FALLBACK`` when no candidate is legal).

To add a NEW device kind to the shipped table, run
``tools/flash_autotune_gen.py`` on one host of that kind — it sweeps the
standard shapes and prints a ready-to-paste ``DEFAULT_TABLE`` entry plus a
``FLASH_BLOCKS_TABLE`` JSON for immediate pod deployment.

A full *measured sweep* (``autotune()``) compiles and times each legal
``(block_q, block_k)`` candidate with value-fetch synchronization and caches
the winner. That costs one kernel compile per candidate (~tens of seconds
each on a remote-tunnel rig), so it never runs implicitly: call it directly,
run ``python -m distributed_pytorch_tpu.ops.flash_autotune``, or set
``FLASH_AUTOTUNE=1`` to let :func:`flash_attention` sweep on first call per
shape.

**Multi-host pods**: the live sweep is disabled under multi-process SPMD on
purpose (hosts could time different winners and trace divergent programs
around the same collectives — hang). Instead, generate the table OFFLINE on
one host of the same device kind and ship it to every host::

    python -m distributed_pytorch_tpu.ops.flash_autotune \
        --seq_lens 8192,16384 --head_dims 64,128 --export v5e_blocks.json
    # then on every pod host:
    export FLASH_BLOCKS_TABLE=/shared/v5e_blocks.json

The explicit table outranks each host's private disk cache, so all hosts are
guaranteed identical block choices (deterministic traces) even when their
local caches disagree. The shipped DEFAULT_TABLE numbers were measured on
TPU v5e (see BASELINE.md round 2).
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Iterable, Optional, Tuple

# (t_bucket, head_dim) -> (block_q, block_k); nearest t_bucket is used.
# Measured on TPU v5 lite (v5e), causal fwd+bwd, bf16 (sweep log in
# BASELINE.md round 2). At T=2048 all candidates sit within dispatch noise;
# from T=8192 up, (1024, 1024) beats the round-1 (512, 1024) guess by
# ~6-10%, and (1024, 2048) exceeds VMEM (the sweep skips failures).
DEFAULT_TABLE = {
    "tpu v5 lite": {
        (2048, 64): (256, 512),
        (2048, 128): (1024, 1024),
        (8192, 64): (1024, 1024),
        (8192, 128): (512, 2048),
        (16384, 64): (1024, 1024),
        (16384, 128): (1024, 1024),
    },
}

#: Cache-key family tag for the paged flash-decode kernel
#: (ops/paged_attention.py). Paged entries share the tiered lookup,
#: disk-cache file, and FLASH_BLOCKS_TABLE schema with the training
#: kernel's — the family tag rides inside the key's dtype slot
#: (``"paged_decode:<dtype>:p<page_size>"``), so the two families can
#: never collide and existing table files keep decoding unchanged.
PAGED_FAMILY = "paged_decode"

# device_kind -> pages-per-block for the paged decode kernel. The "cpu"
# entry is the SEEDED interpret/CI value: the test rig resolves its block
# size from here, so CI never runs a sweep. TPU entries follow the same
# grow-the-tile direction the flash sweeps measured (fewer grid steps,
# longer contractions); re-measure with ``main(--paged)`` per device kind.
PAGED_DEFAULT_TABLE = {
    "cpu": 2,
    "tpu v5 lite": 8,
}

_FALLBACK = (512, 1024)
_PAGED_FALLBACK = 4  # pages per block when nothing is known about the chip
_runtime_cache: dict = {}
# Keys whose measured sweep failed outright (no candidate compiled) in THIS
# process: memoized so the live FLASH_AUTOTUNE=1 path doesn't re-pay the
# failing sweep per retrace, distinguishable so the table generator never
# emits the fallback as a measured winner, and never written to disk so a
# future process (new compiler, new driver) retries for real.
_failed_sweeps: set = set()


def _cache_path() -> str:
    root = os.environ.get(
        "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
    )
    return os.path.join(root, "distributed_pytorch_tpu", "flash_blocks.json")


def _load_disk_cache() -> dict:
    try:
        with open(_cache_path()) as f:
            return {tuple(json.loads(k)): tuple(v) for k, v in json.load(f).items()}
    except Exception:
        return {}


def _save_disk_cache(cache: dict) -> None:
    path = _cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({json.dumps(list(k)): list(v) for k, v in cache.items()}, f)
    except OSError:
        pass  # read-only home: in-process cache still works


def _key(device_kind: str, t: int, d: int, dtype_name: str, causal: bool):
    return (device_kind.lower(), t, d, dtype_name, bool(causal))


@functools.lru_cache(maxsize=8)
def _load_table_file(path: str) -> dict:
    """Explicit precomputed table (FLASH_BLOCKS_TABLE): same JSON schema as
    the disk cache. Errors are loud — a pod pointing at a bad table should
    fail at startup, not silently fall back to divergent local caches."""
    with open(path) as f:
        return {tuple(json.loads(k)): tuple(v) for k, v in json.load(f).items()}


def candidates(t: int, d: int) -> Iterable[Tuple[int, int]]:
    """Legal (block_q, block_k) pairs for sequence length ``t``: both divide
    ``t``, block_k lane-aligned (multiple of 128), VMEM-bounded."""
    qs = [b for b in (256, 512, 1024) if t % b == 0]
    ks = [b for b in (256, 512, 1024, 2048) if t % b == 0 and b % 128 == 0]
    for bq in qs or [t]:
        for bk in ks or []:
            # Rough VMEM bound: score tile + K/V tiles in fp32.
            if bq * bk * 4 + 2 * bk * d * 4 <= 12 * 2**20:
                yield bq, bk


def analytic_default(t: int, d: int) -> Tuple[int, int]:
    """VMEM-reasoned block choice for device kinds with no measured entry.

    Every TPU generation since v4 carries >=16 MB of VMEM per core, so the
    same 12 MB working budget as :func:`candidates` is legal everywhere the
    kernel runs. Among legal candidates, pick the largest tile area (fewer
    grid steps, longer MXU contractions per program — the direction every
    measured v5e sweep moved in from T=8192 up), breaking ties toward
    square blocks ((1024, 1024) won those sweeps over (512, 2048) at equal
    area). This replaces the old bare ``(512, 1024)`` guess, which pinned
    pods on unmeasured chips to a tiling the v5e sweep beat by 6-10%.
    """
    # Stricter than candidates(): the sweep probes compile-failures at
    # runtime, but this path must never hand out a tiling that cannot
    # compile. The backward kernel holds ~3 score-shaped [bq, bk] f32
    # buffers (S, P, dS), so the measured legality boundary on v5e sits at
    # area 2^20 — (1024, 2048) fails to lower while every area<=2^20
    # candidate compiles (BASELINE.md round-2 sweep log).
    legal = [c for c in candidates(t, d) if c[0] * c[1] <= 1 << 20]
    if not legal:
        return _FALLBACK
    return max(legal, key=lambda c: (c[0] * c[1], min(c)))


def lookup(
    t: int,
    d: int,
    dtype_name: str = "bfloat16",
    causal: bool = True,
    device_kind: Optional[str] = None,
) -> Tuple[int, int]:
    """Best-known (block_q, block_k) for this shape without measuring."""
    if device_kind is None:
        device_kind = _device_kind()
    key = _key(device_kind, t, d, dtype_name, causal)
    if key in _runtime_cache:
        return _runtime_cache[key]
    table_path = os.environ.get("FLASH_BLOCKS_TABLE")
    if table_path:
        shipped = _load_table_file(table_path)
        if key in shipped:
            _runtime_cache[key] = shipped[key]
            return shipped[key]
    disk = _load_disk_cache()
    if key in disk:
        _runtime_cache[key] = disk[key]
        return disk[key]
    table = DEFAULT_TABLE.get(device_kind.lower())
    if table:
        near = min(table, key=lambda k: (abs(k[0] - t), abs(k[1] - d)))
        blocks = table[near]
    else:
        # Unknown chip: reason from VMEM legality instead of guessing.
        blocks = analytic_default(t, d)
    # Memoize table/fallback hits too: repeat lookups (one per trace) must
    # not re-open the disk cache file.
    _runtime_cache[key] = blocks
    return blocks


def _paged_key(
    device_kind: str, kv_len: int, page_size: int, head_dim: int,
    dtype_name: str,
):
    """Key for the ``paged_decode`` family: same 5-tuple shape as
    :func:`_key` (so every cache tier and table file works unchanged) with
    the family tag and page size folded into the dtype slot."""
    return _key(
        device_kind, int(kv_len), int(head_dim),
        f"{PAGED_FAMILY}:{dtype_name}:p{int(page_size)}", False,
    )


def paged_candidates(pages_per_seq: int, page_size: int):
    """Legal pages-per-block choices for the paged decode kernel: powers of
    two up to the table width, KV-block span bounded so the per-step page
    tiles (2 pools x fp32 worst case) stay comfortably inside VMEM."""
    out, c = [], 1
    while c <= max(1, int(pages_per_seq)):
        if c * page_size <= 4096:
            out.append(c)
        c *= 2
    return out or [1]


def lookup_paged(
    kv_len: int,
    page_size: int,
    head_dim: int,
    dtype_name: str = "float32",
    device_kind: Optional[str] = None,
) -> int:
    """Best-known pages-per-block for a paged decode shape, tiered exactly
    like :func:`lookup`: runtime cache -> FLASH_BLOCKS_TABLE ->
    disk cache -> seeded :data:`PAGED_DEFAULT_TABLE` -> fallback. Entries
    store ``(pages_per_block, pages_per_block * page_size)`` to keep the
    two-int JSON schema shared with the flash family."""
    if device_kind is None:
        device_kind = _device_kind()
    key = _paged_key(device_kind, kv_len, page_size, head_dim, dtype_name)
    if key in _runtime_cache:
        return int(_runtime_cache[key][0])
    table_path = os.environ.get("FLASH_BLOCKS_TABLE")
    if table_path:
        shipped = _load_table_file(table_path)
        if key in shipped:
            _runtime_cache[key] = shipped[key]
            return int(shipped[key][0])
    disk = _load_disk_cache()
    if key in disk:
        _runtime_cache[key] = disk[key]
        return int(disk[key][0])
    npb = PAGED_DEFAULT_TABLE.get(device_kind.lower(), _PAGED_FALLBACK)
    pages_per_seq = max(1, int(kv_len) // max(1, int(page_size)))
    legal = paged_candidates(pages_per_seq, page_size)
    fitting = [c for c in legal if c <= npb]
    npb = max(fitting) if fitting else legal[0]
    _runtime_cache[key] = (npb, npb * int(page_size))
    return npb


def autotune_paged(
    kv_len: int,
    page_size: int,
    head_dim: int,
    *,
    slots: int = 8,
    kv_heads: int = 8,
    group: int = 1,
    dtype=None,
    steps: int = 20,
    verbose: bool = False,
    force: bool = False,
    interpret: Optional[bool] = None,
) -> int:
    """Measured sweep for the paged decode kernel: times every legal
    pages-per-block over a synthetic full-pool decode batch and caches the
    winner under the ``paged_decode`` family key (in-process + on disk,
    same persistence rules as :func:`autotune`). Offline tool — the serving
    path only ever reads :func:`lookup_paged`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.ops.paged_attention import paged_attention
    from distributed_pytorch_tpu.utils.platform import on_tpu

    dtype = dtype or jnp.float32
    dtype_name = jnp.dtype(dtype).name
    device_kind = _device_kind()
    key = _paged_key(device_kind, kv_len, page_size, head_dim, dtype_name)
    if not force:
        if key in _runtime_cache:
            return int(_runtime_cache[key][0])
        disk = _load_disk_cache()
        if key in disk:
            _runtime_cache[key] = disk[key]
            return int(disk[key][0])
    if interpret is None:
        interpret = not on_tpu()
    mode = "interpret" if interpret else "pallas"

    pages_per_seq = max(1, kv_len // page_size)
    num_pages = slots * pages_per_seq + 1  # + the reserved null page
    rng = np.random.default_rng(0)
    h = kv_heads * group
    q = jnp.asarray(
        rng.standard_normal((slots, 1, h, head_dim)), dtype
    )
    pool = (num_pages, page_size, kv_heads, head_dim)
    k_pool = jnp.asarray(rng.standard_normal(pool), dtype)
    v_pool = jnp.asarray(rng.standard_normal(pool), dtype)
    tables = jnp.asarray(
        1 + np.arange(slots * pages_per_seq).reshape(slots, pages_per_seq),
        jnp.int32,
    )
    lens = jnp.full((slots,), kv_len - 1, jnp.int32)

    best, best_dt = None, float("inf")
    for npb in paged_candidates(pages_per_seq, page_size):
        try:
            fn = jax.jit(
                functools.partial(
                    paged_attention, kernel=mode, pages_per_block=npb
                )
            )
            fn(q, k_pool, v_pool, tables, lens).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(q, k_pool, v_pool, tables, lens)
            out.block_until_ready()
            dt = (time.perf_counter() - t0) / steps
        except Exception as e:  # lowering failure for this blocking: skip
            if verbose:
                print(f"  npb={npb:3d}: failed ({type(e).__name__})")
            continue
        if verbose:
            print(f"  npb={npb:3d}: {dt * 1e6:9.1f} us")
        if dt < best_dt:
            best, best_dt = npb, dt
    if best is None:
        import warnings

        warnings.warn(
            f"paged autotune: no pages-per-block candidate ran for "
            f"kv_len={kv_len} page={page_size} d={head_dim} on "
            f"{device_kind!r}; using fallback {_PAGED_FALLBACK} "
            "(not persisted)"
        )
        _runtime_cache[key] = (_PAGED_FALLBACK, _PAGED_FALLBACK * page_size)
        _failed_sweeps.add(key)
        return _PAGED_FALLBACK
    _runtime_cache[key] = (best, best * page_size)
    _failed_sweeps.discard(key)
    disk = _load_disk_cache()
    disk[key] = (best, best * page_size)
    _save_disk_cache(disk)
    return best


def _device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def autotune(
    t: int,
    d: int,
    *,
    bh: int = 16,
    dtype=None,
    causal: bool = True,
    steps: int = 5,
    verbose: bool = False,
    force: bool = False,
) -> Tuple[int, int]:
    """Measured sweep: times causal fwd+bwd for every legal candidate and
    caches the winner (in-process + on disk). Returns (block_q, block_k).

    ``force=True`` skips the cache READS (still writes) — the table
    generator uses it so a re-run after a compiler upgrade (or with a
    different ``bh``, which the cache key deliberately omits) re-measures
    instead of replaying stale winners. A sweep in which EVERY candidate
    fails to compile returns the legacy fallback, memoized in-process only
    (``_failed_sweeps`` marks it un-measured; the disk cache is never
    written): the live path doesn't re-pay the failing sweep, the table
    generator excludes the shape, and a future process retries for real.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_tpu.ops.flash_attention import _flash

    dtype = dtype or jnp.bfloat16
    dtype_name = jnp.dtype(dtype).name
    device_kind = _device_kind()
    key = _key(device_kind, t, d, dtype_name, causal)
    if not force:
        if key in _runtime_cache:
            return _runtime_cache[key]
        disk = _load_disk_cache()
        if key in disk:  # a previous process already swept this shape
            _runtime_cache[key] = disk[key]
            return disk[key]

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((bh, t, d)), dtype) for _ in range(3)
    )

    best, best_dt = _FALLBACK, float("inf")
    for bq, bk in candidates(t, d):
        try:
            loss = jax.jit(
                jax.grad(
                    lambda q, k, v: jnp.sum(
                        _flash(q, k, v, causal, bq, bk, False).astype(jnp.float32)
                        ** 2
                    )
                )
            )
            g = loss(q, k, v)
            float(jnp.sum(g.astype(jnp.float32)))  # sync (tunnel-safe)
            t0 = time.perf_counter()
            for _ in range(steps):
                g = loss(q, k, v)
            float(jnp.sum(g.astype(jnp.float32)))
            dt = (time.perf_counter() - t0) / steps
        except Exception as e:  # lowering failure for this tiling: skip
            if verbose:
                print(f"  ({bq:5d},{bk:5d}): failed ({type(e).__name__})")
            continue
        if verbose:
            print(f"  ({bq:5d},{bk:5d}): {dt * 1e3:8.2f} ms")
        if dt < best_dt:
            best, best_dt = (bq, bk), dt
    if best_dt == float("inf"):
        # Nothing compiled. Memoize in-process only (the live path must not
        # re-pay a failing sweep per retrace; a future process with a newer
        # compiler should retry), mark the key failed so the table
        # generator excludes it, and return the fallback.
        import warnings

        warnings.warn(
            f"flash autotune: no (block_q, block_k) candidate compiled for "
            f"T={t} d={d} on {device_kind!r}; using fallback {_FALLBACK} "
            "(not persisted)"
        )
        _runtime_cache[key] = _FALLBACK
        _failed_sweeps.add(key)
        return _FALLBACK
    _runtime_cache[key] = best
    _failed_sweeps.discard(key)
    disk = _load_disk_cache()
    disk[key] = best
    _save_disk_cache(disk)
    return best


@functools.lru_cache(maxsize=None)
def autotune_enabled() -> bool:
    return os.environ.get("FLASH_AUTOTUNE", "") not in ("", "0")


def main(argv=None) -> None:
    """Sweep representative shapes on the current device; print a
    ready-to-paste ``DEFAULT_TABLE`` entry and optionally export a
    ``FLASH_BLOCKS_TABLE`` JSON. ``tools/flash_autotune_gen.py`` is the
    documented alias of this entry point — one implementation, two names.

    Only MEASURED winners are emitted: ``--force`` re-sweeps past any
    cached entry, and a shape where every candidate failed to compile is
    reported and EXCLUDED from both outputs (measured-ness is checked
    against the disk cache, which a failed sweep never writes)."""
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--seq_lens", default="2048,8192,16384")
    parser.add_argument("--head_dims", default="64,128")
    parser.add_argument("--bh", default=16, type=int, help="batch*heads")
    parser.add_argument(
        "--export", default="",
        help="write the swept entries to this JSON (ship to pod hosts via "
        "FLASH_BLOCKS_TABLE so every host picks identical blocks)",
    )
    parser.add_argument(
        "--force", action="store_true",
        help="re-measure even when a cached winner exists (use after a "
        "compiler/runtime upgrade or with a different --bh)",
    )
    parser.add_argument(
        "--paged", action="store_true",
        help="sweep the paged_decode family (pages-per-block for the "
        "serving decode kernel) instead of the training flash family",
    )
    parser.add_argument(
        "--kv_lens", default="512,2048,8192",
        help="paged sweep: per-sequence KV capacities (block-table width "
        "x page size)",
    )
    parser.add_argument(
        "--page_sizes", default="16,64",
        help="paged sweep: KV page sizes to tune for",
    )
    parser.add_argument("--slots", default=8, type=int,
                        help="paged sweep: decode batch size")
    args = parser.parse_args(argv)
    kind = _device_kind()
    if kind == "unknown":
        raise SystemExit("no JAX backend reachable — run on the target device")
    print(f"device: {kind}", flush=True)
    entries = {}  # (t, d) -> measured blocks
    shipped = {}  # full key -> blocks, for --export
    failed = []

    if args.paged:
        paged_entries = []  # (kv_len, page, d) -> npb
        for kv_len in (int(x) for x in args.kv_lens.split(",")):
            for page in (int(x) for x in args.page_sizes.split(",")):
                for d in (int(x) for x in args.head_dims.split(",")):
                    print(f"kv={kv_len} page={page} d={d}:", flush=True)
                    npb = autotune_paged(
                        kv_len, page, d, slots=args.slots, verbose=True,
                        force=args.force,
                    )
                    key = _paged_key(kind, kv_len, page, d, "float32")
                    if key in _failed_sweeps:
                        print("  -> MEASUREMENT FAILED (excluded)", flush=True)
                        failed.append((kv_len, page, d))
                        continue
                    print(f"  -> pages_per_block={npb}", flush=True)
                    paged_entries.append(npb)
                    shipped[key] = (npb, npb * page)
        if paged_entries:
            # The seeded table is one npb per device kind; suggest the
            # most common measured winner across shapes.
            best = max(set(paged_entries), key=paged_entries.count)
            print("\n# Paste into ops/flash_autotune.py PAGED_DEFAULT_TABLE:")
            print(f'    "{kind.lower()}": {best},', flush=True)
        if failed:
            print(f"\n# NOT measured: {failed}", flush=True)
        if args.export:
            with open(args.export, "w") as f:
                json.dump(
                    {json.dumps(list(k)): list(v) for k, v in shipped.items()},
                    f,
                )
            print(
                f"exported {len(shipped)} measured entries to {args.export} — "
                "deploy with FLASH_BLOCKS_TABLE=<path> on every pod host",
                flush=True,
            )
        return

    for t in (int(x) for x in args.seq_lens.split(",")):
        for d in (int(x) for x in args.head_dims.split(",")):
            blocks = autotune(t, d, bh=args.bh, verbose=True, force=args.force)
            key = _key(kind, t, d, "bfloat16", True)
            # Measured-ness comes from the sweep itself (_failed_sweeps),
            # not the disk cache — stale disk entries or a read-only home
            # must not flip a shape between measured and failed.
            if key in _failed_sweeps:
                print(
                    f"T={t:6d} d={d:4d} -> MEASUREMENT FAILED (excluded)",
                    flush=True,
                )
                failed.append((t, d))
                continue
            analytic = analytic_default(t, d)
            marker = "  (= analytic default)" if blocks == analytic else ""
            print(f"T={t:6d} d={d:4d} -> {blocks}{marker}", flush=True)
            entries[(t, d)] = blocks
            shipped[key] = blocks

    if entries:
        print("\n# Paste into ops/flash_autotune.py DEFAULT_TABLE:")
        print(f'    "{kind.lower()}": {{')
        for (t, d), (bq, bk) in sorted(entries.items()):
            print(f"        ({t}, {d}): ({bq}, {bk}),")
        print("    },", flush=True)
    if failed:
        print(
            f"\n# NOT measured (every candidate failed to compile): {failed}",
            flush=True,
        )
    if args.export:
        with open(args.export, "w") as f:
            json.dump(
                {json.dumps(list(k)): list(v) for k, v in shipped.items()}, f
            )
        print(
            f"exported {len(shipped)} measured entries to {args.export} — "
            "deploy with FLASH_BLOCKS_TABLE=<path> on every pod host",
            flush=True,
        )


if __name__ == "__main__":
    main()
