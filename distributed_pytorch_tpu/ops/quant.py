"""Weight-only int8 quantization for memory-bound inference.

No reference analog (the reference is a training tutorial); on TPU the case
is structural: batched autoregressive decode is HBM-bandwidth-bound on
*parameter reads* (every generated token re-reads every weight), so halving
weight bytes approaches 2x tokens/s. This module stores weights as
``int8 q`` + float32 per-output-channel ``scale`` (symmetric, absmax) and
dequantizes at the point of use INSIDE the compiled decode loop —
``q.astype(bf16) * scale`` feeding a matmul is a producer fusion XLA handles,
so the bf16 weights are never materialized in HBM; the loop reads int8.

Usage (see ``generation.generate(quantize=True)``):

    qtree = quantize_pytree(params, TRANSFORMER_QUANT_RULES)
    ...inside jit: params = dequantize_pytree(qtree, jnp.bfloat16)

Quantization error for symmetric absmax int8 on well-scaled weights is
~0.2-0.4% RMS — below bf16 activation noise for decode ranking; the parity
test asserts top-1 agreement against the f32 path on a trained toy model.
"""

from __future__ import annotations

import re
from typing import Any, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import jax.tree_util as jtu


@flax.struct.dataclass
class QuantTensor:
    """Symmetric int8 tensor: ``value ~= q * scale`` with ``scale`` shaped
    like ``q`` with the contraction dims collapsed to 1 (broadcast-ready)."""

    q: jnp.ndarray  # int8, original shape
    scale: jnp.ndarray  # float32, keepdims-reduced over contract dims


def quantize_int8(
    w: jnp.ndarray, contract_dims: Sequence[int]
) -> QuantTensor:
    """Per-output-channel symmetric absmax quantization: the scale is computed
    over ``contract_dims`` (the dims a matmul sums over), so each output
    channel keeps its own dynamic range."""
    w = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=tuple(contract_dims), keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QuantTensor(q=q, scale=scale)


def dequantize(qt: QuantTensor, dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    """``q * scale`` in ``dtype``. Under jit this is a producer the consumer
    matmul fuses — no HBM materialization of the dequantized tensor."""
    return (qt.q.astype(jnp.float32) * qt.scale).astype(dtype)


# (path-regex, contract_dims) — which dims of each matched kernel a matmul
# contracts over, i.e. the dims to reduce when computing per-channel scales.
# Matches TransformerLM's param tree (models/transformer.py): QKV are
# DenseGeneral [d_model, H, Dh] (contract 0), attention-out is
# DenseGeneral(axis=(-2,-1)) [H, Dh, d_model] (contract 0,1), MLP/LM-head
# kernels are [in, out] (contract 0). Embedding is a gather, not a matmul —
# quantizing it saves bytes but not decode time; biases/LayerNorms are tiny.
TRANSFORMER_QUANT_RULES: Sequence[Tuple[str, Tuple[int, ...]]] = (
    (r".*/attention/(query|key|value)/kernel$", (0,)),
    (r".*/attention/out/kernel$", (0, 1)),
    (r".*/mlp/(up|down)/kernel$", (0,)),
    # MoE stacked expert kernels [E, in, out] (models/moe.py einsums
    # "ebcm,emf" / "ebcf,efm" contract dim 1) — in an MoE model these ARE
    # the bulk of the params, so skipping them would quietly gut the int8
    # memory win. Scales stay per-(expert, out-channel). The tiny router
    # Dense stays full precision (it feeds a float32 softmax for routing
    # stability).
    (r".*/moe/(up|down)_kernel$", (1,)),
    (r"^lm_head/kernel$", (0,)),
)


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if isinstance(entry, jtu.DictKey):
            parts.append(str(entry.key))
        elif isinstance(entry, jtu.SequenceKey):
            parts.append(str(entry.idx))
        else:
            parts.append(str(getattr(entry, "name", entry)))
    return "/".join(parts)


def quantize_pytree(params: Any, rules=TRANSFORMER_QUANT_RULES) -> Any:
    """Quantize every param whose "/"-joined path matches a rule (first match
    wins); everything else passes through unchanged. The result has the same
    tree structure with :class:`QuantTensor` nodes at matched leaves."""
    compiled = [(re.compile(pattern), dims) for pattern, dims in rules]

    def maybe_quant(path, leaf):
        path_s = _path_str(path)
        for pattern, dims in compiled:
            if pattern.match(path_s):
                return quantize_int8(leaf, dims)
        return leaf

    return jtu.tree_map_with_path(maybe_quant, params)


def dequantize_pytree(qparams: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Inverse of :func:`quantize_pytree` — call INSIDE jit so XLA fuses the
    dequant into each weight's consumer matmul."""
    return jtu.tree_map(
        lambda leaf: dequantize(leaf, dtype)
        if isinstance(leaf, QuantTensor)
        else leaf,
        qparams,
        is_leaf=lambda x: isinstance(x, QuantTensor),
    )


def quantize_shardings(
    shardings: Any, params: Any, rules=TRANSFORMER_QUANT_RULES
) -> Any:
    """Lift a param-tree sharding pytree onto the quantized tree produced by
    :func:`quantize_pytree` with the same ``rules``: the int8 ``q`` keeps the
    original kernel's sharding; the ``scale`` (contract dims collapsed to 1)
    gets the same spec with the contract-dim axes dropped — a size-1 dim
    cannot be sharded. ``shardings`` leaves must be ``NamedSharding``; pass
    the original ``params`` tree alongside for path matching.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    compiled = [(re.compile(pattern), dims) for pattern, dims in rules]

    def lift(path, sharding, leaf):
        path_s = _path_str(path)
        for pattern, dims in compiled:
            if pattern.match(path_s):
                spec = list(sharding.spec)  # may be shorter than ndim
                spec += [None] * (leaf.ndim - len(spec))
                scale_spec = [
                    None if d in dims else spec[d] for d in range(leaf.ndim)
                ]
                return QuantTensor(
                    q=sharding,
                    scale=NamedSharding(
                        sharding.mesh, PartitionSpec(*scale_spec)
                    ),
                )
        return sharding

    return jtu.tree_map_with_path(lift, shardings, params)


def quantized_bytes(tree: Any) -> Tuple[int, int]:
    """(bytes_quantized, bytes_original_f32) over matched leaves — the memory
    story for logs/tests."""
    q_bytes = orig = 0
    for leaf in jtu.tree_leaves(
        tree, is_leaf=lambda x: isinstance(x, QuantTensor)
    ):
        if isinstance(leaf, QuantTensor):
            q_bytes += leaf.q.size + leaf.scale.size * 4
            orig += leaf.q.size * 4
    return q_bytes, orig


def quant_coverage(tree: Any) -> float:
    """Fraction of parameter ELEMENTS held in :class:`QuantTensor` leaves.

    Rule tables match by path, and a silent non-match (a renamed module, a
    new model family) would quietly ship a "quantized" model that still
    reads most of its weights in full precision. Callers that quantize on
    behalf of a user (``generation.generate``) check this and warn when the
    rules missed the bulk of the params — the repo's no-silent-caps
    convention.
    """
    quantized = total = 0
    for leaf in jtu.tree_leaves(
        tree, is_leaf=lambda x: isinstance(x, QuantTensor)
    ):
        if isinstance(leaf, QuantTensor):
            quantized += leaf.q.size
            total += leaf.q.size
        elif hasattr(leaf, "size"):
            total += leaf.size
    return quantized / total if total else 0.0
