"""Fused LM-head + softmax cross-entropy with chunked vocabulary.

No reference analog. For a language model the ``[N, V]`` logits tensor is
often the single largest activation (N = batch*seq, V = vocab): at N=8192,
V=128k, fp32 that is 4 GiB — materialized by the standard
``Dense -> softmax_cross_entropy`` pair in forward AND kept for backward.

:func:`fused_linear_cross_entropy` computes ``mean CE(hidden @ W + b, targets)``
without ever materializing the full logits: a ``lax.scan`` over vocabulary
chunks keeps one ``[N, chunk]`` tile live at a time, accumulating the online
logsumexp (flash-attention-style running max/denominator) and the target
logit. The backward pass (custom VJP) recomputes each chunk's logits from the
saved logsumexp and feeds ``dW``/``dhidden`` per chunk. Peak activation memory
drops from ``O(N*V)`` to ``O(N*chunk)``; every matmul stays a large MXU GEMM.

This is a pure-XLA fusion (scan + GEMM) rather than a Pallas kernel: the op
is GEMM-dominated, so MXU scheduling is already optimal — the win is the
memory/addressing structure, which lax.scan expresses directly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _num_chunks(vocab: int, chunk: int) -> int:
    if vocab % chunk != 0:
        raise ValueError(f"vocab {vocab} not divisible by chunk_size {chunk}")
    return vocab // chunk


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_linear_cross_entropy(
    hidden: jnp.ndarray,
    weight: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    targets: jnp.ndarray,
    chunk_size: int = 8192,
) -> jnp.ndarray:
    """Mean softmax cross-entropy of ``hidden @ weight + bias`` vs integer
    ``targets`` without materializing the logits.

    ``hidden``: [N, D] (flatten batch/seq first); ``weight``: [D, V];
    ``bias``: [V] or None; ``targets``: [N] int. ``chunk_size`` divides V.
    """
    loss, _ = _fwd(hidden, weight, bias, targets, chunk_size)
    return loss


def _chunk(weight, bias, c, chunk_size):
    w_c = jax.lax.dynamic_slice_in_dim(weight, c * chunk_size, chunk_size, axis=1)
    b_c = (
        jax.lax.dynamic_slice_in_dim(bias, c * chunk_size, chunk_size, axis=0)
        if bias is not None
        else None
    )
    return w_c, b_c


def _fwd(hidden, weight, bias, targets, chunk_size):
    n, _ = hidden.shape
    vocab = weight.shape[1]
    n_chunks = _num_chunks(vocab, chunk_size)
    h32 = hidden.astype(jnp.float32)

    def body(carry, c):
        m, l, tgt = carry
        w_c, b_c = _chunk(weight, bias, c, chunk_size)
        logits = h32 @ w_c.astype(jnp.float32)  # [N, chunk]
        if b_c is not None:
            logits = logits + b_c.astype(jnp.float32)
        # Online logsumexp update.
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        l_new = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        # Gather this chunk's target logits (0 where out of chunk).
        local = targets - c * chunk_size
        in_chunk = (local >= 0) & (local < chunk_size)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk_size - 1)[:, None], axis=1
        )[:, 0]
        tgt = jnp.where(in_chunk, picked, tgt)
        return (m_new, l_new, tgt), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    t0 = jnp.zeros((n,), jnp.float32)
    (m, l, tgt), _ = jax.lax.scan(body, (m0, l0, t0), jnp.arange(n_chunks))
    lse = m + jnp.log(l)  # [N]
    loss = jnp.mean(lse - tgt)
    return loss, (hidden, weight, bias, targets, lse)


def _bwd(chunk_size, residuals, g):
    hidden, weight, bias, targets, lse = residuals
    n, d = hidden.shape
    vocab = weight.shape[1]
    n_chunks = _num_chunks(vocab, chunk_size)
    h32 = hidden.astype(jnp.float32)
    scale = g / n  # d(mean)/d(per-row)

    use_bias = bias is not None

    def body(carry, c):
        if use_bias:
            dh, dw_chunks, db_chunks = carry
        else:
            dh, dw_chunks = carry
        w_c, b_c = _chunk(weight, bias, c, chunk_size)
        logits = h32 @ w_c.astype(jnp.float32)
        if b_c is not None:
            logits = logits + b_c.astype(jnp.float32)
        p = jnp.exp(logits - lse[:, None])  # softmax chunk [N, chunk]
        local = targets - c * chunk_size
        in_chunk = (local >= 0) & (local < chunk_size)
        onehot = (
            jax.nn.one_hot(jnp.clip(local, 0, chunk_size - 1), chunk_size)
            * in_chunk[:, None]
        )
        dlogits = (p - onehot) * scale  # [N, chunk]
        dh = dh + dlogits @ w_c.astype(jnp.float32).T
        dw_c = h32.T @ dlogits  # [D, chunk]
        if not use_bias:
            # bias=None (e.g. a tied LM head): no db carry AT TRACE LEVEL —
            # the [n_chunks, chunk_size] accumulator and its per-chunk
            # reduction never exist, rather than relying on XLA to
            # dead-code them out of the scan.
            return (dh, dw_chunks.at[c].set(dw_c)), None
        db_c = jnp.sum(dlogits, axis=0)
        return (dh, dw_chunks.at[c].set(dw_c), db_chunks.at[c].set(db_c)), None

    dh0 = jnp.zeros((n, d), jnp.float32)
    dw0 = jnp.zeros((n_chunks, d, chunk_size), jnp.float32)
    if use_bias:
        db0 = jnp.zeros((n_chunks, chunk_size), jnp.float32)
        (dh, dw_chunks, db_chunks), _ = jax.lax.scan(
            body, (dh0, dw0, db0), jnp.arange(n_chunks)
        )
        db = db_chunks.reshape(vocab).astype(bias.dtype)
    else:
        (dh, dw_chunks), _ = jax.lax.scan(
            body, (dh0, dw0), jnp.arange(n_chunks)
        )
        db = None
    dw = jnp.transpose(dw_chunks, (1, 0, 2)).reshape(d, vocab)
    return (
        dh.astype(hidden.dtype),
        dw.astype(weight.dtype),
        db,
        None,  # integer targets
    )


def _fwd_vjp(hidden, weight, bias, targets, chunk_size):
    return _fwd(hidden, weight, bias, targets, chunk_size)


fused_linear_cross_entropy.defvjp(_fwd_vjp, _bwd)
