"""Native (C++) runtime components and their build glue.

The reference inherits all of its native capability from the ``torch`` wheel
(SURVEY.md §2a); this package is where our framework's own native runtime
lives. Sources are compiled on first use with ``g++`` into ``_build/`` next to
this file and cached by source mtime, so there is no separate install step
(mirroring the zero-setup character of the reference scripts).

Components:

* ``kvstore.cpp``   -> ``tpu_kvstore`` binary — TCP rendezvous/KV store
  (c10d TCPStore twin; reference ``slurm/sbatch_run.sh:21-22``).
"""

from __future__ import annotations

import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")
_BUILD_LOCK = threading.Lock()


def _needs_rebuild(src: str, out: str) -> bool:
    return not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src)


def _compile(src_name: str, out_name: str, *, shared: bool) -> str:
    """Compile ``src_name`` (in this dir) to ``_build/out_name`` if stale."""
    src = os.path.join(_NATIVE_DIR, src_name)
    out = os.path.join(_BUILD_DIR, out_name)
    with _BUILD_LOCK:
        if not _needs_rebuild(src, out):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = ["g++", "-O2", "-std=c++17", "-pthread"]
        if shared:
            cmd += ["-fPIC", "-shared"]
        # Per-process temp name: the threading lock doesn't cover concurrent
        # *processes* (two agents cold-starting on one machine), so each must
        # link into its own file before the atomic rename.
        tmp = f"{out}.tmp.{os.getpid()}"
        cmd += [src, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic: concurrent builders see old or new
    return out


def kvstore_binary() -> str:
    """Path to the ``tpu_kvstore`` server binary (building it if needed)."""
    return _compile("kvstore.cpp", "tpu_kvstore", shared=False)
