"""Native (C++) runtime components and their build glue.

The reference inherits all of its native capability from the ``torch`` wheel
(SURVEY.md §2a); this package is where our framework's own native runtime
lives. Sources are compiled on first use with ``g++`` into ``_build/`` next to
this file and cached by source mtime, so there is no separate install step
(mirroring the zero-setup character of the reference scripts).

Components:

* ``kvstore.cpp``   -> ``tpu_kvstore`` binary — TCP rendezvous/KV store
  (c10d TCPStore twin; reference ``slurm/sbatch_run.sh:21-22``).
* ``prefetch.cpp``  -> ``libtpu_prefetch.so`` — GIL-free batch-prefetch worker
  pool (torch ``DataLoader`` worker/pin-memory twin; reference
  ``multigpu.py:72-79``), driven via ctypes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()


def _build_dir() -> str:
    """Output dir for compiled artifacts.

    Prefer ``_build/`` next to the sources (editable installs, repo
    checkouts); when the package dir is read-only (non-editable wheel in
    system site-packages) fall back to a per-user cache dir keyed by the
    source location, so distinct installs never share stale binaries.
    """
    preferred = os.path.join(_NATIVE_DIR, "_build")
    if os.access(_NATIVE_DIR, os.W_OK):
        return preferred
    import hashlib

    key = hashlib.sha256(_NATIVE_DIR.encode()).hexdigest()[:16]
    cache_root = os.environ.get(
        "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
    )
    return os.path.join(cache_root, "distributed_pytorch_tpu", key)


def _needs_rebuild(src: str, out: str) -> bool:
    return not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src)


def _compile(src_name: str, out_name: str, *, shared: bool) -> str:
    """Compile ``src_name`` (in this dir) to ``_build/out_name`` if stale."""
    src = os.path.join(_NATIVE_DIR, src_name)
    build_dir = _build_dir()
    out = os.path.join(build_dir, out_name)
    with _BUILD_LOCK:
        if not _needs_rebuild(src, out):
            return out
        os.makedirs(build_dir, exist_ok=True)
        cmd = ["g++", "-O2", "-std=c++17", "-pthread"]
        if shared:
            cmd += ["-fPIC", "-shared"]
        # Per-process temp name: the threading lock doesn't cover concurrent
        # *processes* (two agents cold-starting on one machine), so each must
        # link into its own file before the atomic rename.
        tmp = f"{out}.tmp.{os.getpid()}"
        cmd += [src, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic: concurrent builders see old or new
    return out


def kvstore_binary() -> str:
    """Path to the ``tpu_kvstore`` server binary (building it if needed)."""
    return _compile("kvstore.cpp", "tpu_kvstore", shared=False)


_PREFETCH_LIB = None


def prefetch_library() -> ctypes.CDLL:
    """The batch-prefetch shared library, built on first use, with argtypes
    bound."""
    global _PREFETCH_LIB
    if _PREFETCH_LIB is not None:
        return _PREFETCH_LIB
    path = _compile("prefetch.cpp", "libtpu_prefetch.so", shared=True)
    lib = ctypes.CDLL(path)
    lib.prefetch_create.restype = ctypes.c_void_p
    lib.prefetch_create.argtypes = [
        ctypes.c_void_p,  # x rows
        ctypes.c_void_p,  # y rows
        ctypes.c_long,  # row_x bytes
        ctypes.c_long,  # row_y bytes
        ctypes.POINTER(ctypes.c_long),  # indices
        ctypes.c_long,  # n_indices
        ctypes.c_long,  # batch
        ctypes.c_int,  # depth
        ctypes.c_int,  # n_threads
    ]
    lib.prefetch_next.restype = ctypes.c_int
    lib.prefetch_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.prefetch_stop.restype = None
    lib.prefetch_stop.argtypes = [ctypes.c_void_p]
    lib.prefetch_destroy.restype = None
    lib.prefetch_destroy.argtypes = [ctypes.c_void_p]
    _PREFETCH_LIB = lib
    return lib
