// Background batch-prefetch engine: the native data-loader worker pool.
//
// Capability twin of the reference's `DataLoader(..., pin_memory=True)` worker
// machinery (reference multigpu.py:72-79) — the part of the input pipeline the
// reference inherits from torch's C++ core (SURVEY.md §2a "Pinned-memory H2D
// copy path"). Here it is a standalone shared library driven through ctypes:
//
//   * N worker threads gather sample rows (raw memcpy, dtype-agnostic) from a
//     caller-owned dataset buffer into a bounded ring of batch slots — the
//     Python GIL is never touched while batches are assembled;
//   * the consumer drains batches strictly in order (slot b % depth carries
//     batch b), so results are identical to serial iteration;
//   * `depth` bounds memory: workers stall until the consumer frees a slot.
//
// Row indices are precomputed by the Python side (ShardedLoader semantics:
// shuffle, shard, pad-by-wrap), keeping the C++ side a pure data mover.

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

enum SlotState { kEmpty = 0, kFilling = 1, kReady = 2 };

struct Loader {
  const char* x;
  const char* y;
  long row_x;  // bytes per sample row in x
  long row_y;
  std::vector<long> indices;  // n_batches * batch sample indices
  long batch;
  long n_batches;
  int depth;

  std::vector<std::vector<char>> slot_x;
  std::vector<std::vector<char>> slot_y;
  std::vector<int> state;

  std::mutex mu;
  std::condition_variable cv_slot_free;
  std::condition_variable cv_slot_ready;
  std::atomic<long> next_claim{0};
  long next_deliver = 0;
  bool stopping = false;
  int consumers_inflight = 0;  // prefetch_next calls currently executing
  std::condition_variable cv_consumer_done;
  std::vector<std::thread> workers;
};

void worker_loop(Loader* ld) {
  for (;;) {
    long b = ld->next_claim.fetch_add(1);
    if (b >= ld->n_batches) return;
    int slot = static_cast<int>(b % ld->depth);
    {
      std::unique_lock<std::mutex> lock(ld->mu);
      // Slot b%depth is ours once the consumer has drained batch b-depth.
      ld->cv_slot_free.wait(lock, [&] {
        return ld->stopping ||
               (ld->state[slot] == kEmpty && b - ld->next_deliver < ld->depth);
      });
      if (ld->stopping) return;
      ld->state[slot] = kFilling;
    }
    char* out_x = ld->slot_x[slot].data();
    char* out_y = ld->slot_y[slot].data();
    const long* idx = ld->indices.data() + b * ld->batch;
    for (long i = 0; i < ld->batch; ++i) {
      std::memcpy(out_x + i * ld->row_x, ld->x + idx[i] * ld->row_x, ld->row_x);
      std::memcpy(out_y + i * ld->row_y, ld->y + idx[i] * ld->row_y, ld->row_y);
    }
    {
      std::lock_guard<std::mutex> lock(ld->mu);
      ld->state[slot] = kReady;
    }
    ld->cv_slot_ready.notify_all();
  }
}

}  // namespace

extern "C" {

// Dataset buffers stay owned by the caller and must outlive the loader.
void* prefetch_create(const char* x, const char* y, long row_x, long row_y,
                      const long* indices, long n_indices, long batch,
                      int depth, int n_threads) {
  if (batch <= 0 || n_indices % batch != 0 || depth <= 0 || n_threads <= 0) {
    return nullptr;
  }
  auto* ld = new Loader();
  ld->x = x;
  ld->y = y;
  ld->row_x = row_x;
  ld->row_y = row_y;
  ld->indices.assign(indices, indices + n_indices);
  ld->batch = batch;
  ld->n_batches = n_indices / batch;
  ld->depth = depth;
  ld->slot_x.resize(depth, std::vector<char>(batch * row_x));
  ld->slot_y.resize(depth, std::vector<char>(batch * row_y));
  ld->state.assign(depth, kEmpty);
  for (int t = 0; t < n_threads; ++t) {
    ld->workers.emplace_back(worker_loop, ld);
  }
  return ld;
}

// Copies the next batch into caller buffers. 1 = delivered, 0 = exhausted.
int prefetch_next(void* handle, char* out_x, char* out_y) {
  auto* ld = static_cast<Loader*>(handle);
  long b;
  int slot;
  {
    std::unique_lock<std::mutex> lock(ld->mu);
    if (ld->stopping || ld->next_deliver >= ld->n_batches) return 0;
    ++ld->consumers_inflight;
    b = ld->next_deliver;
    slot = static_cast<int>(b % ld->depth);
    // Also wake on stopping: a cross-thread destroy must not strand a
    // blocked consumer forever (mirrors the worker-side wait predicate).
    ld->cv_slot_ready.wait(
        lock, [&] { return ld->state[slot] == kReady || ld->stopping; });
    if (ld->stopping) {
      // Notify while still holding the mutex: the moment the lock is
      // released with consumers_inflight == 0, destroy may delete ld, so
      // no ld member may be touched outside the lock from here on.
      --ld->consumers_inflight;
      ld->cv_consumer_done.notify_all();
      return 0;
    }
  }
  std::memcpy(out_x, ld->slot_x[slot].data(), ld->batch * ld->row_x);
  std::memcpy(out_y, ld->slot_y[slot].data(), ld->batch * ld->row_y);
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->state[slot] = kEmpty;
    ld->next_deliver = b + 1;
    --ld->consumers_inflight;
    // Same rule: notify under the lock — after release, ld may be gone.
    ld->cv_slot_free.notify_all();
    ld->cv_consumer_done.notify_all();
  }
  return 1;
}

// Ends the stream without freeing: any prefetch_next blocked in its wait (or
// starting afterwards) returns 0. Safe to call from any thread while a
// consumer is mid-call — the teardown contract is: stop() from anywhere,
// let the consumer loop exit, THEN destroy() (a prefetch_next that *starts*
// after destroy would touch freed memory, same as any handle API).
void prefetch_stop(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->stopping = true;
    ld->cv_slot_free.notify_all();
    ld->cv_slot_ready.notify_all();
  }
}

void prefetch_destroy(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  {
    std::unique_lock<std::mutex> lock(ld->mu);
    ld->stopping = true;
    ld->cv_slot_free.notify_all();
    ld->cv_slot_ready.notify_all();
    // A consumer between its predicate check and memcpy (mutex released)
    // still touches slot buffers; wait until no prefetch_next is in flight
    // before tearing the Loader down. Calls that START after this point are
    // the caller's responsibility (use prefetch_stop to end a foreign
    // consumer loop first).
    ld->cv_consumer_done.wait(lock, [&] { return ld->consumers_inflight == 0; });
  }
  // Unblock any worker waiting to fill by draining claims.
  ld->next_claim.store(ld->n_batches);
  ld->cv_slot_free.notify_all();
  for (auto& t : ld->workers) t.join();
  delete ld;
}

}  // extern "C"
