// tpu_kvstore — a tiny TCP key-value / rendezvous store.
//
// Native (C++) twin of the reference's c10d TCPStore rendezvous backend
// (`--rdzv_backend c10d --rdzv_endpoint head:29500`, reference
// slurm/sbatch_run.sh:21-22; MASTER_ADDR/PORT at multigpu.py:18-19): the
// coordination primitive that the elastic agent (tpurun) uses to rendezvous
// host agents, count joins, propagate failure generations, and barrier.
//
// Protocol: line-based over TCP, one request per line, space-separated tokens
// (keys and values must not contain whitespace; the Python client
// percent-encodes arbitrary strings).
//
//   PING                 -> PONG
//   SET <key> <value> [id] -> OK                (set + wake waiters)
//   GET <key>            -> VAL <value> | NONE
//   ADD <key> <delta> [id] -> VAL <int>         (atomic add, missing key = 0)
//   WAIT <key> [ms]      -> VAL <value> | TIMEOUT   (block until key exists)
//   WAITGE <key> <n> [ms]-> VAL <int> | TIMEOUT (block until int value >= n)
//   DEL <key> [id]       -> OK
//   KEYS <prefix>        -> VAL <k1> <k2> ...   (snapshot; may be empty)
//   SHUTDOWN             -> OK (then the server exits)
//
// Mutating ops take an optional trailing request id: a client that lost the
// reply (connection reset between apply and ack) retries with the SAME id,
// and the server replays the recorded reply instead of re-applying. Without
// this, a retried ADD would double-increment rendezvous counters. Ids live in
// a bounded FIFO map — old entries are evicted once the client has long since
// given up retrying them.
//
// Threading: one detached thread per connection; a single mutex +
// condition_variable guards the map (coordination traffic is tiny — a few
// messages per agent per rendezvous round — so contention is irrelevant).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <unordered_map>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::mutex g_mu;
std::condition_variable g_cv;
std::map<std::string, std::string> g_store;
bool g_shutdown = false;
int g_listen_fd = -1;

// Replay memory for deduplicated mutating ops. Guarded by g_mu.
std::unordered_map<std::string, std::string> g_dedup;
std::deque<std::string> g_dedup_order;  // FIFO eviction order
constexpr size_t kDedupCap = 8192;

// Both helpers require g_mu held.
const std::string* dedup_lookup(const std::string& id) {
  auto it = g_dedup.find(id);
  return it == g_dedup.end() ? nullptr : &it->second;
}

void dedup_record(const std::string& id, const std::string& resp) {
  if (g_dedup.emplace(id, resp).second) {
    g_dedup_order.push_back(id);
    if (g_dedup_order.size() > kDedupCap) {
      g_dedup.erase(g_dedup_order.front());
      g_dedup_order.pop_front();
    }
  }
}

std::string handle_command(const std::vector<std::string>& tok) {
  if (tok.empty()) return "ERR empty";
  const std::string& cmd = tok[0];

  if (cmd == "PING") return "PONG";

  if (cmd == "SET" && (tok.size() == 3 || tok.size() == 4)) {
    std::lock_guard<std::mutex> lk(g_mu);
    if (tok.size() == 4)
      if (const std::string* prior = dedup_lookup(tok[3])) return *prior;
    g_store[tok[1]] = tok[2];
    g_cv.notify_all();
    if (tok.size() == 4) dedup_record(tok[3], "OK");
    return "OK";
  }

  if (cmd == "GET" && tok.size() == 2) {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_store.find(tok[1]);
    return it == g_store.end() ? "NONE" : "VAL " + it->second;
  }

  if (cmd == "ADD" && (tok.size() == 3 || tok.size() == 4)) {
    long delta = strtol(tok[2].c_str(), nullptr, 10);
    std::lock_guard<std::mutex> lk(g_mu);
    if (tok.size() == 4)
      if (const std::string* prior = dedup_lookup(tok[3])) return *prior;
    long cur = 0;
    auto it = g_store.find(tok[1]);
    if (it != g_store.end()) cur = strtol(it->second.c_str(), nullptr, 10);
    cur += delta;
    g_store[tok[1]] = std::to_string(cur);
    g_cv.notify_all();
    std::string resp = "VAL " + std::to_string(cur);
    if (tok.size() == 4) dedup_record(tok[3], resp);
    return resp;
  }

  if (cmd == "WAIT" && (tok.size() == 2 || tok.size() == 3)) {
    long timeout_ms = tok.size() == 3 ? strtol(tok[2].c_str(), nullptr, 10) : -1;
    std::unique_lock<std::mutex> lk(g_mu);
    auto pred = [&] { return g_shutdown || g_store.count(tok[1]) > 0; };
    if (timeout_ms < 0) {
      g_cv.wait(lk, pred);
    } else if (!g_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
      return "TIMEOUT";
    }
    auto it = g_store.find(tok[1]);
    return it == g_store.end() ? "TIMEOUT" : "VAL " + it->second;
  }

  if (cmd == "WAITGE" && (tok.size() == 3 || tok.size() == 4)) {
    long target = strtol(tok[2].c_str(), nullptr, 10);
    long timeout_ms = tok.size() == 4 ? strtol(tok[3].c_str(), nullptr, 10) : -1;
    std::unique_lock<std::mutex> lk(g_mu);
    auto value = [&]() -> long {
      auto it = g_store.find(tok[1]);
      return it == g_store.end() ? 0 : strtol(it->second.c_str(), nullptr, 10);
    };
    auto pred = [&] { return g_shutdown || value() >= target; };
    if (timeout_ms < 0) {
      g_cv.wait(lk, pred);
    } else if (!g_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
      return "TIMEOUT";
    }
    if (value() < target) return "TIMEOUT";
    return "VAL " + std::to_string(value());
  }

  if (cmd == "DEL" && (tok.size() == 2 || tok.size() == 3)) {
    std::lock_guard<std::mutex> lk(g_mu);
    if (tok.size() == 3)
      if (const std::string* prior = dedup_lookup(tok[2])) return *prior;
    g_store.erase(tok[1]);
    if (tok.size() == 3) dedup_record(tok[2], "OK");
    return "OK";
  }

  if (cmd == "KEYS" && tok.size() <= 2) {
    const std::string prefix = tok.size() == 2 ? tok[1] : "";
    std::lock_guard<std::mutex> lk(g_mu);
    std::string out = "VAL";
    for (const auto& kv : g_store)
      if (kv.first.rfind(prefix, 0) == 0) out += " " + kv.first;
    return out;
  }

  if (cmd == "SHUTDOWN") {
    {
      std::lock_guard<std::mutex> lk(g_mu);
      g_shutdown = true;
      g_cv.notify_all();
    }
    // Wake the accept() loop so the process actually exits (a blocked accept
    // would otherwise only notice g_shutdown at the next connection).
    if (g_listen_fd >= 0) shutdown(g_listen_fd, SHUT_RDWR);
    return "OK";
  }

  return "ERR bad-command";
}

void serve_connection(int fd) {
  std::string buf;
  char chunk[4096];
  for (;;) {
    // Process any complete lines already buffered.
    size_t nl;
    while ((nl = buf.find('\n')) != std::string::npos) {
      std::string line = buf.substr(0, nl);
      buf.erase(0, nl + 1);
      if (!line.empty() && line.back() == '\r') line.pop_back();
      std::istringstream iss(line);
      std::vector<std::string> tok;
      std::string t;
      while (iss >> t) tok.push_back(t);
      std::string resp = handle_command(tok) + "\n";
      const char* p = resp.data();
      size_t left = resp.size();
      while (left > 0) {
        ssize_t n = send(fd, p, left, MSG_NOSIGNAL);
        if (n <= 0) { close(fd); return; }
        p += n;
        left -= static_cast<size_t>(n);
      }
      {
        std::lock_guard<std::mutex> lk(g_mu);
        if (g_shutdown) { close(fd); return; }
      }
    }
    ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) { close(fd); return; }
    buf.append(chunk, static_cast<size_t>(n));
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <port> [bind_addr]\n", argv[0]);
    return 2;
  }
  int port = atoi(argv[1]);
  const char* bind_addr = argc > 2 ? argv[2] : "0.0.0.0";

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) { perror("socket"); return 1; }
  g_listen_fd = srv;
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));

  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) {
    fprintf(stderr, "bad bind addr %s\n", bind_addr);
    return 2;
  }
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 128) < 0) { perror("listen"); return 1; }
  // Readiness line on stdout: the Python server wrapper waits for it.
  printf("LISTENING %d\n", port);
  fflush(stdout);

  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    {
      std::lock_guard<std::mutex> lk(g_mu);
      if (g_shutdown) { if (fd >= 0) close(fd); break; }
    }
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve_connection, fd).detach();
  }
  close(srv);
  return 0;
}
