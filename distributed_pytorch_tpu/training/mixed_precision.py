"""Mixed-precision policy + loss scaling.

The reference delegates precision policy entirely to torch (fp32 end to end;
AMP would be ``torch.cuda.amp`` — never used in the ladder). On TPU the
idiomatic policy is *bf16 compute over f32 master weights*: flax modules take
``dtype=`` (compute) and keep ``param_dtype=f32``, and bf16's f32-sized
exponent needs no loss scaling. This module makes that policy an explicit,
testable object — and adds the scaling machinery fp16 *does* need, so the
framework's precision story is complete rather than implicit:

* :class:`Policy` — named dtype triple (param/compute/output) with pytree
  cast helpers. ``Model(dtype=policy.compute_dtype)`` is the wiring.
* :class:`StaticLossScale` / :class:`DynamicLossScale` — loss-scale state
  that rides IN :class:`~.train_step.TrainState` (``loss_scale`` field).
  When present, the train step scales the loss before ``jax.grad``,
  unscales the gradients, and **skips the parameter/optimizer/model-state
  update on any non-finite gradient** (the step counter still advances —
  it counts attempted steps, mirroring how a torch AMP loop calls
  ``scaler.step`` every iteration). Dynamic scaling halves on overflow and
  multiplies by ``factor`` after ``growth_interval`` consecutive finite
  steps — the standard GradScaler schedule — as pure traced arithmetic
  (no host round-trip, elastic-snapshot friendly: the scale is checkpointed
  with the rest of the state).

Everything here is a pytree of scalars; under a mesh the scale replicates
with the state and the finiteness check is a global reduction XLA inserts.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp


def _cast_floating(tree: Any, dtype) -> Any:
    """Cast floating-point leaves to ``dtype``; leave ints/bools untouched."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
        else x,
        tree,
    )


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype triple: parameters (master), compute, output.

    ``bf16`` is the TPU default policy; ``fp16`` exists for interop and
    requires a loss scale (5-bit exponent underflows real gradients).
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    output_dtype: Any = jnp.float32

    def cast_to_param(self, tree: Any) -> Any:
        return _cast_floating(tree, self.param_dtype)

    def cast_to_compute(self, tree: Any) -> Any:
        return _cast_floating(tree, self.compute_dtype)

    def cast_to_output(self, tree: Any) -> Any:
        return _cast_floating(tree, self.output_dtype)


F32_POLICY = Policy(compute_dtype=jnp.float32)
BF16_POLICY = Policy()
FP16_POLICY = Policy(compute_dtype=jnp.float16)


def all_finite(tree: Any) -> jnp.ndarray:
    """Scalar bool: every element of every leaf is finite."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.array(True)
    return jnp.all(
        jnp.stack([jnp.all(jnp.isfinite(leaf)) for leaf in leaves])
    )


class _ScaleOps:
    """Shared scale/unscale arithmetic (both loss-scale structs carry a
    float32 scalar ``scale``)."""

    def scale_loss(self, loss: jnp.ndarray) -> jnp.ndarray:
        # Scale in float32: a float16 loss times a scale near 2**16 would
        # overflow float16 before the cast. The scaled value only seeds the
        # backward pass, so promoting it costs nothing.
        return loss.astype(jnp.float32) * self.scale

    def unscale(self, grads: Any) -> Any:
        inv = (1.0 / self.scale).astype(jnp.float32)
        return jax.tree_util.tree_map(
            lambda g: (g * inv.astype(g.dtype)), grads
        )


@flax.struct.dataclass
class StaticLossScale(_ScaleOps):
    """Fixed loss scale. ``adjust`` is a no-op; non-finite steps are still
    skipped (a fixed scale can overflow transiently on loss spikes)."""

    scale: jnp.ndarray

    @classmethod
    def create(cls, scale: float) -> "StaticLossScale":
        return cls(scale=jnp.asarray(scale, jnp.float32))

    def adjust(self, grads_finite: jnp.ndarray) -> "StaticLossScale":
        del grads_finite
        return self


@flax.struct.dataclass
class DynamicLossScale(_ScaleOps):
    """GradScaler-schedule dynamic loss scale: halve on overflow, grow by
    ``factor`` after ``growth_interval`` consecutive finite steps."""

    scale: jnp.ndarray
    good_steps: jnp.ndarray
    growth_interval: int = flax.struct.field(pytree_node=False, default=2000)
    factor: float = flax.struct.field(pytree_node=False, default=2.0)
    min_scale: float = flax.struct.field(pytree_node=False, default=1.0)

    @classmethod
    def create(
        cls,
        initial_scale: float = 2.0**15,
        growth_interval: int = 2000,
        factor: float = 2.0,
        min_scale: float = 1.0,
    ) -> "DynamicLossScale":
        return cls(
            scale=jnp.asarray(initial_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            growth_interval=growth_interval,
            factor=factor,
            min_scale=min_scale,
        )

    def adjust(self, grads_finite: jnp.ndarray) -> "DynamicLossScale":
        grown = self.good_steps + 1 >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grown, self.scale * self.factor, self.scale),
            jnp.maximum(self.scale / self.factor, self.min_scale),
        )
        new_good = jnp.where(grads_finite & ~grown, self.good_steps + 1, 0)
        return self.replace(scale=new_scale, good_steps=new_good)
