"""Loss functions.

The reference uses ``CrossEntropyLoss`` on a single logit in most rungs
(``single_gpu.py:24`` — a quirk: softmax of one logit is identically 1, so that
loss is constant 0) and ``MSELoss`` only in the multinode rung
(``multinode_torchrun.py:46`` — the only loss that matches the
``Linear(20,1)`` regression head). We standardize on MSE for the toy
regression task and real softmax cross-entropy for classification models.
"""

import weakref

import jax
import jax.numpy as jnp
import optax


def mse_loss(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean squared error over the (global) batch."""
    return jnp.mean(jnp.square(predictions - targets))


def softmax_cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Cross entropy; integer targets -> sparse labels, float targets -> soft labels."""
    if jnp.issubdtype(targets.dtype, jnp.integer):
        per_example = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    else:
        per_example = optax.softmax_cross_entropy(logits, targets)
    return jnp.mean(per_example)


def smoothed_cross_entropy_loss(smoothing: float):
    """Factory: cross entropy with label smoothing ``smoothing`` (the
    standard ViT/Inception regularizer — each target distributes
    ``smoothing`` mass uniformly over the other classes). Returns a
    ``(logits, int_targets) -> scalar`` with the same signature as
    :func:`softmax_cross_entropy_loss`, so it drops into ``Trainer`` /
    ``make_train_step`` unchanged. ``smoothing=0`` reduces exactly to the
    sparse loss."""
    if not 0.0 <= smoothing < 1.0:
        raise ValueError(f"smoothing must be in [0, 1), got {smoothing}")

    def per_sample(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
        n_classes = logits.shape[-1]
        soft = optax.smooth_labels(
            jax.nn.one_hot(targets, n_classes, dtype=logits.dtype), smoothing
        )
        per = optax.softmax_cross_entropy(logits, soft)
        # Sequence models produce per-TOKEN values [B, S]; the per-sample
        # contract is [batch] (mean over everything else), exactly like
        # per_sample_cross_entropy below.
        return per.reshape(per.shape[0], -1).mean(axis=-1)

    def loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
        # The batch loss IS the twin's mean — one body, contract by
        # construction.
        return jnp.mean(per_sample(logits, targets))

    # Register the exact-eval twin so Trainer.evaluate keeps its unbiased
    # wrap-pad-corrected path for this loss too (same mechanism as the
    # stock losses below; the registry holds weak keys, so losses built in
    # a sweep loop don't accumulate forever).
    PER_SAMPLE_TWINS[loss] = per_sample
    return loss


# -- per-sample twins (exact evaluation) -------------------------------------
#
# The batch-mean losses above are opaque reductions: under wrap-padded batches
# (the DistributedSampler pad-by-repeat semantic) their mean over-counts the
# duplicated rows. Evaluation uses these per-sample forms instead and weights
# pad rows to zero, so eval metrics are EXACT on any dataset size / mesh shape
# (see Trainer.evaluate). PER_SAMPLE_TWINS maps a batch loss to its per-sample
# form so the Trainer can derive the exact path automatically.


def per_sample_mse(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Squared error per sample: mean over feature dims only -> [batch]."""
    se = jnp.square(predictions - targets)
    return se.reshape(se.shape[0], -1).mean(axis=-1)


def per_sample_cross_entropy(
    logits: jnp.ndarray, targets: jnp.ndarray
) -> jnp.ndarray:
    """Cross entropy per sample -> [batch] (mean over any token dims)."""
    if jnp.issubdtype(targets.dtype, jnp.integer):
        per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    else:
        per = optax.softmax_cross_entropy(logits, targets)
    return per.reshape(per.shape[0], -1).mean(axis=-1)


def per_sample_accuracy(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """1.0 where argmax(logits) == integer target, else 0.0 -> [batch...]."""
    correct = jnp.argmax(logits, axis=-1) == targets
    return correct.reshape(correct.shape[0], -1).mean(axis=-1).astype(jnp.float32)


# Weak keys: factory-built losses (smoothed_cross_entropy_loss) register
# here too, and a sweep that builds many must not pin them all in memory.
# The module-level stock losses live for the process anyway.
PER_SAMPLE_TWINS = weakref.WeakKeyDictionary(
    {
        mse_loss: per_sample_mse,
        softmax_cross_entropy_loss: per_sample_cross_entropy,
    }
)
