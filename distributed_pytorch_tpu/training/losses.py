"""Loss functions.

The reference uses ``CrossEntropyLoss`` on a single logit in most rungs
(``single_gpu.py:24`` — a quirk: softmax of one logit is identically 1, so that
loss is constant 0) and ``MSELoss`` only in the multinode rung
(``multinode_torchrun.py:46`` — the only loss that matches the
``Linear(20,1)`` regression head). We standardize on MSE for the toy
regression task and real softmax cross-entropy for classification models.
"""

import jax.numpy as jnp
import optax


def mse_loss(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean squared error over the (global) batch."""
    return jnp.mean(jnp.square(predictions - targets))


def softmax_cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Cross entropy; integer targets -> sparse labels, float targets -> soft labels."""
    if jnp.issubdtype(targets.dtype, jnp.integer):
        per_example = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    else:
        per_example = optax.softmax_cross_entropy(logits, targets)
    return jnp.mean(per_example)
