"""The Trainer: epoch loop -> batch loop -> jitted train step, plus
checkpoint/snapshot I/O.

Capability twin of the reference's ``Trainer`` in all five ladder rungs
(serial ``single_gpu.py:6-45``; DDP ``multigpu.py:22-62``; elastic
``multigpu_torchrun.py:15-68``; multinode ``multinode_torchrun.py:15-69``;
profiled ``multigpu_profile.py:30-91``) — one class covers all rungs because
the SPMD design makes "how many chips / hosts" a property of the mesh, not of
the training code:

* no mesh           -> serial rung (1 chip);
* mesh, 1 process   -> single-host data parallel (DDP twin);
* mesh, N processes -> multi-host pod (multinode twin) — each host feeds its
  local loader shard into a globally sharded batch.

Elasticity contract (identical to ``multigpu_torchrun.py:30-40,57-65``): if a
snapshot exists at construction it is loaded and ``train()`` resumes from
``epochs_run``; snapshots are written every ``save_every`` epochs by process 0
only, with a cross-host barrier after the write.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from distributed_pytorch_tpu.chaos import on_step as _chaos_on_step
from distributed_pytorch_tpu.checkpoint import (
    load_snapshot_with_fallback,
    save_checkpoint,
    save_snapshot,
)
from distributed_pytorch_tpu.metrics import MetricLogger, ReservoirHistogram
from distributed_pytorch_tpu.parallel.bootstrap import is_main_process
from distributed_pytorch_tpu.parallel.sharding import (
    put_global_batch,
    replicated_sharding,
)
from distributed_pytorch_tpu.training.losses import mse_loss
from distributed_pytorch_tpu.training.train_step import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
)
from distributed_pytorch_tpu.utils.data import ShardedLoader


def _put_host_state(state, sharding):
    """Place a host-loaded (numpy) state tree onto a possibly multi-process
    sharding.

    ``jax.device_put(host_array, multi_process_sharding)`` runs a
    cross-process value-equality collective, which the CPU backend does not
    implement (and which is redundant here: every process read the same
    snapshot file). ``make_array_from_callback`` assembles the global array
    from locally-computed shards with no collective, so snapshot resume works
    on any backend. ``sharding`` may be a single Sharding or a state-shaped
    tree of them.
    """
    if jax.process_count() == 1:
        return jax.device_put(state, sharding)

    def put(x, s):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, s, lambda idx: x[idx])

    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree_util.tree_map(lambda x: put(x, sharding), state)
    return jax.tree_util.tree_map(put, state, sharding)


class Trainer:
    """Drives training of a flax model over a ShardedLoader.

    Parameters mirror the reference ctor
    (``model, train_data, optimizer, save_every[, snapshot_path]``,
    e.g. ``multigpu_torchrun.py:16-23``) with TPU-native additions:
    ``mesh`` (in place of gpu_id / process-group), ``loss_fn``, and
    ``checkpoint_path``.
    """

    def __init__(
        self,
        model,
        train_data: ShardedLoader,
        optimizer: optax.GradientTransformation,
        save_every: int,
        *,
        snapshot_path: Optional[str] = None,
        checkpoint_path: str = "checkpoint.npz",
        mesh: Optional[Mesh] = None,
        loss_fn: Callable = mse_loss,
        rng_seed: int = 0,
        profiler=None,
        metrics: Optional[MetricLogger] = None,
        log_every: int = 0,
        grad_accum: int = 1,
        async_save: bool = False,
        paranoid: bool = False,
        loss_scale=None,
        partition_specs=None,
        keep_checkpoints: int = 0,
        dropout_seed: Optional[int] = None,
        registry=None,
    ):
        self.model = model
        self.train_data = train_data
        self.optimizer = optimizer
        self.save_every = save_every
        self.snapshot_path = snapshot_path
        self.checkpoint_path = checkpoint_path
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.profiler = profiler
        self.metrics = metrics or MetricLogger()
        # Per-batch wall time (dispatch + any sync the loop already does) in
        # a bounded reservoir; p50/p95 logged at every epoch boundary. Tail
        # percentiles are where stragglers, recompiles, and host stalls show
        # up — the mean hides them.
        self.step_times = ReservoirHistogram(1024)
        # Optional unified-observability hookup: expose the step-time
        # reservoir and global step through a shared MetricsRegistry
        # alongside the serving/elastic metrics. Pull-based — the registry
        # reads these attributes at snapshot time, nothing is double-booked.
        if registry is not None:
            registry.reservoir(
                "trainer_step_time_seconds", lambda: self.step_times
            )
            registry.counter_fn(
                "trainer_steps_total", lambda: int(self.state.step)
            )
        self.log_every = log_every
        self.grad_accum = grad_accum
        # async_save: overlap snapshot disk writes with the next epoch's
        # compute; paranoid: replica-consistency check before every snapshot
        # (the race detector, SURVEY.md §5).
        self.checkpointer = None
        if async_save:
            from distributed_pytorch_tpu.checkpoint import AsyncCheckpointer

            self.checkpointer = AsyncCheckpointer()
        self.paranoid = paranoid
        # keep_checkpoints > 0: rotate instead of overwriting —
        # checkpoint_path becomes a DIRECTORY managed by CheckpointManager
        # (newest K by write time + the best-by-epoch-loss protected);
        # 0 keeps the reference's single-file overwrite semantics.
        self.manager = None
        if keep_checkpoints > 0:
            if snapshot_path is not None:
                # train() routes periodic saves to the snapshot when
                # snapshot_path is set; a manager built here would silently
                # never run — refuse the combination instead.
                raise ValueError(
                    "keep_checkpoints rotates checkpoint_path saves, but "
                    "snapshot_path is also set (snapshots are single-file "
                    "by design — the elastic resume contract); use one or "
                    "the other"
                )
            from distributed_pytorch_tpu.checkpoint import CheckpointManager

            self.manager = CheckpointManager(
                checkpoint_path, keep=keep_checkpoints, mode="min"
            )
        self.epochs_run = 0
        # Mid-epoch (drain snapshot) resume point, consumed by the first
        # _run_epoch after a resume: skip the first _resume_step batches of
        # epoch _resume_epoch and seed the epoch-loss mean with the partial
        # sums accumulated before the drain (so the logged epoch_loss of a
        # preempted-and-resumed epoch equals the un-preempted one).
        self._resume_epoch = 0
        self._resume_step = 0
        self._resume_loss_sum = 0.0
        self._resume_loss_count = 0

        if mesh is not None:
            data_size = mesh.shape.get("data", 1)
            if train_data.batch_size % data_size != 0:
                raise ValueError(
                    f"batch_size {train_data.batch_size} is not divisible by the "
                    f"mesh's data axis ({data_size}); P('data') cannot place it"
                )
            if not train_data.drop_last and not train_data.pad_final_batch:
                # Static shapes under jit: wrap-pad any ragged final batch
                # (DistributedSampler's pad-by-repeat semantic).
                train_data.pad_final_batch = True
        if grad_accum > 1:
            if train_data.batch_size % grad_accum != 0:
                raise ValueError(
                    f"batch_size {train_data.batch_size} is not divisible by "
                    f"grad_accum {grad_accum}"
                )
            if not train_data.drop_last and not train_data.pad_final_batch:
                # A ragged final batch would break the microbatch split even
                # in the serial (mesh-free) case.
                train_data.pad_final_batch = True

        sample_x, _ = next(iter(train_data))
        # loss_scale: a mixed_precision.{Static,Dynamic}LossScale for fp16
        # compute policies; rides in TrainState (see train_step.TrainState).
        # dropout_seed arms the step's stochastic path (the model must set
        # dropout_rate > 0 for it to have any effect; eval/decode stay
        # deterministic either way — see train_step.TrainState.rng).
        self.state: TrainState = create_train_state(
            model, optimizer, sample_x, rng_seed=rng_seed,
            loss_scale=loss_scale, dropout_rng=dropout_seed,
        )
        # partition_specs opens the sharding zoo through the flagship API:
        # either a params-shaped PartitionSpec tree (TP/FSDP rule output —
        # lifted onto the whole TrainState, Adam moments following their
        # params) or a full TrainState-shaped spec tree (e.g.
        # make_zero1_state_specs). None = plain replicated DP.
        self.state_sharding = None
        if partition_specs is not None:
            if mesh is None:
                raise ValueError("partition_specs requires mesh=")
            from distributed_pytorch_tpu.parallel.partitioning import (
                make_state_specs,
                specs_to_shardings,
            )

            specs = (
                partition_specs
                if isinstance(partition_specs, TrainState)
                else make_state_specs(self.state, partition_specs)
            )
            self.state_sharding = specs_to_shardings(mesh, specs)
            self.state = jax.device_put(self.state, self.state_sharding)
        elif mesh is not None:
            # Replicate state across the mesh (the DDP-construction broadcast,
            # reference multigpu.py:36, minus the network traffic: every
            # process computes identical init from the same seed).
            self.state = jax.device_put(self.state, replicated_sharding(mesh))

        # Snapshot probe-on-init: the elasticity contract
        # (reference multigpu_torchrun.py:30-32). _load_snapshot handles the
        # whole fallback chain, including "only <path>.prev exists" (a crash
        # between rotation and write) and "latest is corrupt".
        if snapshot_path is not None:
            self._load_snapshot(snapshot_path)

        self.train_step = make_train_step(
            model.apply, optimizer, loss_fn, mesh=mesh, grad_accum=grad_accum,
            state_sharding=self.state_sharding,
        )
        self._eval_step = None  # built lazily on first evaluate()
        self._eval_step_fns = None  # metric-fn set the cached step was built for
        # tpurun's hung-worker detector (--worker-heartbeat-timeout): when the
        # agent sets this env var, touch the file every batch so a wedged
        # worker (stuck in a collective whose peer died) is distinguishable
        # from a slow one. None outside tpurun — zero overhead.
        self._heartbeat_file = os.environ.get("TPURUN_HEARTBEAT_FILE")
        # Preemption drain (tpurun's SIGTERM grace path): the agent touches
        # TPURUN_DRAIN_FILE and soft-signals SIGTERM when the node is being
        # reclaimed; either signal sets _drain_flag, and the batch loop then
        # finishes the in-flight step, takes a just-in-time step-granular
        # snapshot, and exits with the distinguished drain exit code so the
        # agent classifies the death as a preemption (restart budget intact).
        # Armed only when there is a snapshot to drain into.
        self._drain_file = os.environ.get("TPURUN_DRAIN_FILE")
        self._drain_exit_code = int(
            os.environ.get("TPURUN_DRAIN_EXIT_CODE", "121")
        )
        self._drain_flag = False
        self._drain_armed = snapshot_path is not None
        if (
            self._drain_armed
            and threading.current_thread() is threading.main_thread()
        ):
            try:
                signal.signal(signal.SIGTERM, self._on_sigterm)
            except (ValueError, OSError):
                pass  # embedded in a host that owns signals: file-poll only

    # ---------------------------------------------------------------- persistence

    def _load_snapshot(self, path: str) -> None:
        loaded = load_snapshot_with_fallback(path, self.state)
        if loaded is None:
            # Nothing loadable: either a first run (silent) or every
            # candidate was corrupt (load_snapshot_with_fallback already
            # warned loudly and quarantined) — train from scratch.
            return
        state, meta, used = loaded
        self.epochs_run = int(meta.get("epochs_run", 0))
        if self.state_sharding is not None:
            state = _put_host_state(state, self.state_sharding)
        elif self.mesh is not None:
            state = _put_host_state(state, replicated_sharding(self.mesh))
        else:
            state = jax.device_put(state)
        self.state = state
        # Mid-epoch (drain) snapshot: step_in_epoch batches of epoch
        # epochs_run are already in the restored state. Resuming at the exact
        # batch is only sound if the loader reproduces the drained run's
        # batch order — otherwise (e.g. num_shards changed after a
        # scale-down) replay the epoch from batch 0: re-applying a batch is
        # safe for coverage, skipping one is not.
        step = int(meta.get("step_in_epoch", 0))
        step_note = ""
        if step > 0:
            if self.train_data.matches_order_state(meta.get("order")):
                self._resume_epoch = self.epochs_run
                self._resume_step = step
                self._resume_loss_sum = float(meta.get("loss_sum", 0.0))
                self._resume_loss_count = int(meta.get("loss_count", 0))
                step_note = f", step {step}"
            elif is_main_process():
                print(
                    f"[drain] snapshot was taken at step {step} of epoch "
                    f"{self.epochs_run} under a different loader geometry; "
                    f"replaying the epoch from step 0",
                    flush=True,
                )
        if is_main_process():
            note = "" if used == path else f" (fell back to {used})"
            print(
                f"Resuming training from snapshot at Epoch {self.epochs_run}"
                f"{step_note}{note}",
                flush=True,
            )

    def _save_snapshot(self, epoch: int) -> None:
        # Long synchronous saves (and the paranoid replica check) run no
        # batches; beat before and after so the hung-worker detector doesn't
        # mistake a big checkpoint for a wedge.
        self._touch_heartbeat()
        if self.paranoid:
            from distributed_pytorch_tpu.parallel.consistency import (
                assert_replicas_consistent,
            )

            assert_replicas_consistent(self.state, name="TrainState")
        if self.checkpointer is not None:
            self.checkpointer.save_snapshot(
                self.snapshot_path, self.state, epochs_run=epoch + 1
            )
            note = "snapshot write started (async)"
        else:
            save_snapshot(self.snapshot_path, self.state, epochs_run=epoch + 1)
            note = "Training snapshot saved"
        if is_main_process():
            print(
                f"Epoch {epoch} | {note} at {self.snapshot_path}",
                flush=True,
            )
        self._touch_heartbeat()

    def _save_checkpoint(self, epoch: int, metric=None) -> None:
        # Params AND non-trainable model state (BatchNorm running stats):
        # the reference's state_dict includes both (multigpu.py:54). Beat
        # around the synchronous save, same as _save_snapshot.
        self._touch_heartbeat()
        tree = {
            "params": self.state.params,
            "model_state": self.state.model_state,
        }
        # ONE metadata schema for both modes: {"epoch": N (0-based, the
        # reference's convention), "epochs_run": N+1}; rotated files add
        # "metric".
        if self.manager is not None:
            where = self.manager.save(
                tree, step=epoch + 1, metric=metric, epochs_run=epoch + 1,
                extra_metadata={"epoch": epoch},
            )
        else:
            where = self.checkpoint_path
            save_checkpoint(
                where, tree,
                metadata={"epoch": epoch, "epochs_run": epoch + 1},
            )
        if is_main_process():
            print(
                f"Epoch {epoch} | Training checkpoint saved at {where}",
                flush=True,
            )
        self._touch_heartbeat()

    # ---------------------------------------------------------------- training

    def _put_batch(self, xs: np.ndarray, ys: np.ndarray):
        """Host numpy -> device, globally sharded along the data axis."""
        if self.mesh is None:
            return jax.device_put((xs, ys))
        return put_global_batch(self.mesh, (xs, ys))

    def _run_batch(self, batch) -> float:
        """One optimizer step (twin of ``_run_batch``, ``single_gpu.py:21-26``)."""
        # Chaos hook: deterministic "kill/hang worker N at step S" fires here
        # (exact no-op unless TPURUN_FAULT_PLAN is armed).
        _chaos_on_step()
        self.state, loss = self.train_step(self.state, batch)
        self._touch_heartbeat()
        return loss

    def _touch_heartbeat(self) -> None:
        """Liveness beat for tpurun's hung-worker detector. Called at every
        point of progress (each train/eval batch, around snapshot writes) —
        no-op outside tpurun, and never allowed to kill training."""
        if self._heartbeat_file is None:
            return
        try:
            os.close(os.open(self._heartbeat_file, os.O_CREAT | os.O_WRONLY))
            os.utime(self._heartbeat_file)
        except OSError:
            pass

    # ------------------------------------------------------------------ drain

    def _on_sigterm(self, signum, frame) -> None:
        """Preemption notice via direct signal delivery. Under tpurun a BARE
        SIGTERM (drain file absent) is the agent tearing the group down for a
        failure-restart — die immediately, as before this handler existed,
        so restarts stay fast; only a SIGTERM accompanying a touched drain
        file (or any SIGTERM outside tpurun) means "snapshot and go"."""
        if self._drain_file is not None and not os.path.exists(self._drain_file):
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
            return
        self._drain_flag = True

    def _drain_due(self) -> bool:
        """Poll the drain flag at a step boundary. Multi-process runs under
        the agent agree on the flag COLLECTIVELY every batch: the notice can
        reach ranks at different wall times, and a rank stopping to snapshot
        (a barrier) while another still runs the train step (a collective)
        would deadlock the world — the allgather is the in-band drain
        barrier that makes every rank stop at the identical step."""
        local = self._drain_flag or (
            self._drain_file is not None and os.path.exists(self._drain_file)
        )
        if (
            self._drain_armed
            and self._drain_file is not None
            and jax.process_count() > 1
        ):
            from jax.experimental import multihost_utils

            flags = multihost_utils.process_allgather(
                np.asarray(local, dtype=np.int32)
            )
            return bool(np.max(flags))
        return local

    def _drain_exit(self, epoch: int, steps_done: int, loss_sum: float,
                    loss_count: int) -> None:
        """Just-in-time snapshot at the current step, then exit with the
        drain code (the agent classifies it as a preemption, not a failure).
        Always a SYNCHRONOUS save — the process is about to die, so the
        write must be durable before the grace window closes."""
        self._touch_heartbeat()
        if self.checkpointer is not None:
            self.checkpointer.wait()  # order behind any in-flight async write
        extra = {
            "order": self.train_data.order_state(),
            "loss_sum": float(loss_sum),
            "loss_count": int(loss_count),
        }
        save_snapshot(
            self.snapshot_path, self.state, epochs_run=epoch,
            step_in_epoch=steps_done, extra_meta=extra,
        )
        self._touch_heartbeat()
        if is_main_process():
            print(
                f"[drain] just-in-time snapshot at epoch {epoch}, step "
                f"{steps_done} -> {self.snapshot_path}; exiting with code "
                f"{self._drain_exit_code}",
                flush=True,
            )
        # SystemExit (not os._exit) so train()'s finally still stops the
        # profiler and closes metrics before the interpreter exits.
        raise SystemExit(self._drain_exit_code)

    # ---------------------------------------------------------------- epochs

    def _run_epoch(self, epoch: int) -> float:
        """One pass over this process's shard (twin of ``_run_epoch``,
        ``single_gpu.py:28-34``). Returns the mean loss over the epoch."""
        self.train_data.set_epoch(epoch)
        n_batches = len(self.train_data)
        start = 0
        carry_sum, carry_count = 0.0, 0
        if self._resume_step and epoch == self._resume_epoch:
            # Resuming from a mid-epoch drain snapshot: the first
            # _resume_step batches are already in the state; their losses are
            # carried so this epoch's logged mean spans the whole epoch.
            start = self._resume_step
            carry_sum = self._resume_loss_sum
            carry_count = self._resume_loss_count
        self._resume_step = 0  # one-shot: later epochs start at batch 0
        if is_main_process():
            resume_note = f" (resuming at step {start})" if start else ""
            print(
                f"[proc{jax.process_index()}] Epoch {epoch} | "
                f"Batchsize: {self.train_data.batch_size} | Steps: {n_batches}"
                f"{resume_note}",
                flush=True,
            )
        losses = []
        last_loss = None
        for i, (xs, ys) in enumerate(
            self.train_data.iter_batches(start), start=start
        ):
            t0 = time.perf_counter()
            batch = self._put_batch(xs, ys)
            loss = self._run_batch(batch)
            losses.append(loss)
            self.step_times.record(time.perf_counter() - t0)
            if self.profiler is not None:
                # Device sync so the profiled window reflects real step time.
                jax.block_until_ready(loss)
                self.profiler.step()
            if self.log_every and (i + 1) % self.log_every == 0:
                last_loss = float(loss)
                self.metrics.log(int(self.state.step), loss=last_loss, epoch=epoch)
            if self._drain_armed and self._drain_due():
                host_losses = [float(l) for l in losses]
                self._drain_exit(
                    epoch,
                    steps_done=i + 1,
                    loss_sum=carry_sum + float(np.sum(host_losses)),
                    loss_count=carry_count + len(host_losses),
                )
        total = carry_sum + (
            float(np.sum([float(l) for l in losses])) if losses else 0.0
        )
        count = carry_count + len(losses)
        epoch_loss = total / count if count else 0.0
        self.metrics.log(
            int(self.state.step),
            epoch_loss=epoch_loss,
            epoch=epoch,
            step_time_s_p50=self.step_times.quantile(0.5),
            step_time_s_p95=self.step_times.quantile(0.95),
        )
        return epoch_loss

    def _eval_apply(self, variables, inputs, **kwargs):
        """Forward in eval mode. Models whose ``__call__`` takes a ``train``
        flag (BatchNorm family) get ``train=False`` so running statistics are
        used; models without one are called plainly. Detected via the call
        signature — a try/except on TypeError would mask real errors and
        silently fall back to train mode."""
        import inspect

        signature = inspect.signature(type(self.model).__call__)
        if "train" in signature.parameters:
            kwargs["train"] = False
        return self.model.apply(variables, inputs, **kwargs)

    def _prepare_eval_loader(self, eval_data: ShardedLoader) -> ShardedLoader:
        """Mesh divisibility checks + pad-final-batch on a COPY (the caller's
        loader must not change behavior)."""
        if self.mesh is not None:
            data_size = self.mesh.shape.get("data", 1)
            if eval_data.batch_size % data_size != 0:
                raise ValueError(
                    f"eval batch_size {eval_data.batch_size} is not divisible "
                    f"by the mesh's data axis ({data_size})"
                )
            if not eval_data.drop_last and not eval_data.pad_final_batch:
                import copy

                eval_data = copy.copy(eval_data)
                eval_data.pad_final_batch = True
        return eval_data

    def evaluate(self, eval_data: ShardedLoader, metric_fns=None):
        """Forward-only evaluation over ``eval_data`` (no gradients, no state
        mutation). No reference analog — the reference never evaluates
        (SURVEY.md §5: loss is computed but not even logged).

        When ``loss_fn`` has a per-sample twin (``losses.PER_SAMPLE_TWINS`` —
        both stock losses do) the mean is EXACT on any dataset size / mesh
        shape: per-sample losses are computed and the loader's wrap-pad
        duplicate rows (shard- and batch-level) are weighted to zero, so no
        padding bias enters. ``metric_fns`` adds further per-sample metrics
        (``{name: (predictions, targets) -> [batch]}``, e.g.
        ``losses.per_sample_accuracy``); passing it returns a dict of means
        instead of the bare loss float.

        Custom opaque ``loss_fn``s (no per-sample twin, no ``metric_fns``)
        fall back to the weighted-batch-mean path, which over-counts wrapped
        duplicates on a non-divisible eval set under a mesh — the
        DistributedSampler semantic the training path deliberately keeps."""
        from distributed_pytorch_tpu.training.losses import PER_SAMPLE_TWINS

        fns = dict(metric_fns or {})
        if "loss" not in fns:
            per_sample_loss = PER_SAMPLE_TWINS.get(self.loss_fn)
            if per_sample_loss is not None:
                fns["loss"] = per_sample_loss

        if not fns:
            return self._evaluate_batch_mean(eval_data)

        # Key the cached step by the metric FUNCTIONS, not just their names:
        # a different fn under the same name must rebuild, or it would
        # silently return the old metric under the new label.
        fns_key = frozenset(fns.items())
        if self._eval_step is None or self._eval_step_fns != fns_key:
            from distributed_pytorch_tpu.training.train_step import (
                make_metrics_eval_step,
            )

            self._eval_step = make_metrics_eval_step(
                self._eval_apply, fns, mesh=self.mesh,
                state_sharding=self.state_sharding,
            )
            self._eval_step_fns = fns_key

        eval_data = self._prepare_eval_loader(eval_data)
        totals = None
        weight_rows = eval_data.batch_weight_table()
        for (xs, ys), w in zip(eval_data, weight_rows):
            if self.mesh is None:
                batch, weights = jax.device_put(((xs, ys), w))
            else:
                batch, weights = put_global_batch(self.mesh, ((xs, ys), w))
            out = self._eval_step(self.state, batch, weights)
            self._touch_heartbeat()
            totals = (
                out
                if totals is None
                else jax.tree_util.tree_map(jnp.add, totals, out)
            )
        if totals is None:
            return 0.0 if metric_fns is None else {}
        # One host fetch for all sums (a fetch per scalar would cost a round
        # trip per metric on remote-tunnel backends).
        names = sorted(totals)
        host = np.asarray(jnp.stack([totals[k] for k in names]))
        sums = dict(zip(names, host))
        weight = max(float(sums.pop("__weight__")), 1e-9)
        results = {name: float(value) / weight for name, value in sums.items()}
        self.metrics.log(
            int(self.state.step),
            **{f"eval_{k}" if k != "loss" else "eval_loss": v
               for k, v in results.items()},
        )
        return results if metric_fns is not None else results["loss"]

    def _evaluate_batch_mean(self, eval_data: ShardedLoader) -> float:
        """Legacy weighted-batch-mean eval for opaque loss functions (wrapped
        duplicates count toward the mean — see ``evaluate``)."""
        if self._eval_step is None or self._eval_step_fns is not None:
            self._eval_step = make_eval_step(
                self._eval_apply, self.loss_fn, mesh=self.mesh,
                state_sharding=self.state_sharding,
            )
            self._eval_step_fns = None
        eval_data = self._prepare_eval_loader(eval_data)
        losses, weights = [], []
        for xs, ys in eval_data:
            losses.append(self._eval_step(self.state, self._put_batch(xs, ys)))
            self._touch_heartbeat()
            weights.append(xs.shape[0])
        if losses:
            host_losses = np.asarray(jnp.stack(losses))
            eval_loss = float(np.average(host_losses, weights=weights))
        else:
            eval_loss = 0.0
        self.metrics.log(int(self.state.step), eval_loss=eval_loss)
        return eval_loss

    def train(self, max_epochs: int) -> None:
        """Epoch loop with snapshot/checkpoint cadence (twin of ``train``,
        ``multigpu_torchrun.py:64-68``: resumes from ``epochs_run``)."""
        if self.profiler is not None:
            self.profiler.start()
        try:
            for epoch in range(self.epochs_run, max_epochs):
                epoch_loss = self._run_epoch(epoch)
                self.epochs_run = epoch + 1
                if self.save_every and (epoch + 1) % self.save_every == 0:
                    if self.snapshot_path is not None:
                        self._save_snapshot(epoch)
                    else:
                        self._save_checkpoint(epoch, metric=epoch_loss)
        finally:
            try:
                if self.checkpointer is not None:
                    # Snapshot must be durable before returning; a surfaced
                    # write error must not skip profiler/metrics cleanup.
                    self.checkpointer.wait()
            finally:
                if self.profiler is not None:
                    self.profiler.stop()
                self.metrics.close()
