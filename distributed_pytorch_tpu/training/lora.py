"""LoRA: low-rank adaptation as a TrainState-native wrapper.

No reference analog (the reference is a from-scratch training tutorial);
parameter-efficient fine-tuning is table stakes for a complete framework,
and on a TPU mesh its payoff is DISTRIBUTED: gradients, optimizer moments,
and checkpoint deltas shrink to the adapter tree (rank x (m + n) per
matched kernel instead of m x n), so the cross-replica grad all-reduce,
ZeRO-sharded moment memory, and snapshot bytes all scale with the
adapters, not the model.

Design — adapters ARE ``TrainState.params``; the frozen base rides in
``model_state``:

* :class:`LoraModel` wraps any flax model: its ``init`` returns
  ``{"params": <adapters>, "lora_base": <frozen base>, ...}``, so
  ``create_train_state`` puts the adapters where gradients flow and the
  base where they don't — ``make_train_step``/``Trainer`` need ZERO
  changes, and no ``stop_gradient`` is ever needed (the base is simply
  not the differentiated argument).
* The base is a traced step input (donated, checkpointed, shardable),
  NOT a closure constant — closing over it would bake the full model
  into the executable and double its memory.
* ``merge_lora`` computes ``W + (alpha/rank) * A @ B`` per matched
  kernel inside the jitted step; XLA fuses the rank-r outer product into
  the surrounding graph. B starts at zero, so step 0 is exactly the base
  model.

Matricization: attention kernels here are 3D (``DenseGeneral``), so each
rule names how a kernel flattens to a matrix — ``in_first`` ([in, rest],
q/k/v-shaped) or ``out_last`` ([rest, out], out-projection and every 2D
kernel). The low-rank factors live in that matrix view and reshape back.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import traverse_util

# (path regex, matricization) — first match wins; kernels matching no rule
# stay frozen with no adapter. The default set covers TransformerLM's
# attention + MLP + head; embeddings stay frozen (standard LoRA practice).
DEFAULT_LORA_RULES: Tuple[Tuple[str, str], ...] = (
    (r".*/attention/(query|key|value)/kernel$", "in_first"),
    (r".*/attention/out/kernel$", "out_last"),
    (r".*/mlp/(up|down|gate)/kernel$", "out_last"),
    (r"(.*/)?lm_head/kernel$", "out_last"),
)


def _matricize_shape(shape: Tuple[int, ...], mode: str) -> Tuple[int, int]:
    if len(shape) < 2:
        raise ValueError(f"cannot adapt a {len(shape)}-D leaf")
    if mode == "in_first":
        return shape[0], int(np.prod(shape[1:]))
    if mode == "out_last":
        return int(np.prod(shape[:-1])), shape[-1]
    raise ValueError(f"unknown matricization {mode!r}")


def _match(path: str, rules) -> Optional[str]:
    for pattern, mode in rules:
        if re.match(pattern + r"\Z", path):
            return mode
    return None


def init_lora(
    params,
    rank: int,
    rng,
    *,
    rules: Sequence[Tuple[str, str]] = DEFAULT_LORA_RULES,
) -> Any:
    """Build the adapter tree for ``params``: for every kernel matching a
    rule, ``{"lora_a": [m, rank] ~ N(0, 1/sqrt(m)), "lora_b": [rank, n]
    zeros}`` in the rule's matrix view. B at zero makes the initial merged
    model EXACTLY the base. Raises if nothing matches (a silently empty
    adapter tree would train nothing)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    flat = traverse_util.flatten_dict(params)
    out = {}
    for path, leaf in flat.items():
        mode = _match("/".join(str(p) for p in path), rules)
        if mode is None:
            continue
        m, n = _matricize_shape(leaf.shape, mode)
        rng, sub = jax.random.split(rng)
        out[path + ("lora_a",)] = (
            jax.random.normal(sub, (m, rank), jnp.float32) / np.sqrt(m)
        )
        out[path + ("lora_b",)] = jnp.zeros((rank, n), jnp.float32)
    if not out:
        raise ValueError(
            "no parameter matched the LoRA rules — adapters would be empty; "
            f"rules={tuple(r for r, _ in rules)}"
        )
    return traverse_util.unflatten_dict(out)


def merge_lora(
    params,
    adapters,
    *,
    rank: int,
    alpha: Optional[float] = None,
    rules: Sequence[Tuple[str, str]] = DEFAULT_LORA_RULES,
):
    """``W + (alpha/rank) * A @ B`` for every adapted kernel (pure and
    jit-friendly — call it inside a step, or once to export a merged
    model for ``generation.generate``/eval). ``alpha`` defaults to
    ``rank`` (scale 1)."""
    scale = (rank if alpha is None else alpha) / rank
    flat = dict(traverse_util.flatten_dict(params))
    flat_a = traverse_util.flatten_dict(adapters)
    for path, leaf in flat_a.items():
        if path[-1] != "lora_a":
            continue
        base_path = path[:-1]
        w = flat[base_path]
        # Both matricizations are C-order reshapes of W with different
        # split points, so reshaping the [m, n] delta back to w.shape is
        # the exact inverse either way — no mode lookup needed here.
        delta = (leaf @ flat_a[base_path + ("lora_b",)]) * scale
        flat[base_path] = (w + delta.reshape(w.shape).astype(w.dtype))
    return traverse_util.unflatten_dict(flat)


@dataclasses.dataclass(frozen=True)
class LoraModel:
    """Drop-in flax-model facade: ``init`` splits variables into trainable
    adapters (``"params"``) and the frozen base (``"lora_base"``), ``apply``
    merges on the fly — so ``create_train_state(LoraModel(model, rank=8),
    optimizer, sample)`` and the unchanged ``make_train_step``/``Trainer``
    machinery fine-tune the adapters only. For inference, merge once:
    ``generate(model, lora.merged_params(state), ...)``."""

    model: Any
    rank: int
    alpha: Optional[float] = None
    rules: Tuple[Tuple[str, str], ...] = DEFAULT_LORA_RULES

    def init(self, rng, *args, **kw):
        variables = dict(self.model.init(rng, *args, **kw))
        base = variables.pop("params")
        adapters = init_lora(
            base, self.rank, jax.random.fold_in(rng, 0x10AA),
            rules=self.rules,
        )
        return {"params": adapters, "lora_base": base, **variables}

    def apply(self, variables, *args, mutable=False, **kw):
        vs = dict(variables)
        base = vs.pop("lora_base")
        merged = merge_lora(
            base, vs.pop("params"), rank=self.rank, alpha=self.alpha,
            rules=self.rules,
        )
        # mutable=False is flax's only "return bare output" form; every
        # other value — list (INCLUDING []), tuple, True, str — makes
        # flax return (out, state), and the facade must re-attach
        # lora_base to that state so the standard round-trip that rebuilds
        # variables from the returned collections re-applies cleanly.
        inner_mutable = mutable
        if isinstance(mutable, (list, tuple)):
            inner_mutable = [m for m in mutable if m != "lora_base"]
        elif isinstance(mutable, str):
            inner_mutable = [] if mutable == "lora_base" else mutable
        out = self.model.apply(
            {"params": merged, **vs}, *args, mutable=inner_mutable, **kw
        )
        if mutable is False:
            return out
        preds, new_state = out
        return preds, {**dict(new_state), "lora_base": base}

    def merged_params(self, state_or_variables):
        """Merged full-model params from a ``TrainState`` (adapters in
        ``.params``, base in ``.model_state``) or an ``init``-style
        variables dict — feed to ``generation.generate`` / plain eval."""
        if hasattr(state_or_variables, "params"):
            adapters = state_or_variables.params
            base = state_or_variables.model_state["lora_base"]
        else:
            adapters = state_or_variables["params"]
            base = state_or_variables["lora_base"]
        return merge_lora(
            base, adapters, rank=self.rank, alpha=self.alpha,
            rules=self.rules,
        )

    def __getattr__(self, name):
        # Transparent passthrough (dtype, vocab_size, ...) so downstream
        # code that inspects model attributes keeps working.
        return getattr(self.model, name)
