"""Knowledge distillation: the forward-KL step used to train speculative
draft models.

One canonical implementation (round-5 review: the draft-distillation rung
and the decode bench each carried a copy) of the step that makes
``speculative_generate`` actually fast: train a small student on the
teacher's next-token DISTRIBUTIONS (forward KL, teacher logits computed on
the fly — no logit dataset to stage), so the student's greedy/sampled
proposals match the teacher often enough for long accepted chunks.
``examples/draft_distill.py`` is the runnable story (acceptance
1.00 -> 4.00 of gamma=4); ``tools/decode_bench.py --speculative`` is the
measurement instrument.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def make_distill_step(teacher, student, optimizer):
    """Build the jitted forward-KL distillation step.

    Returns ``step(student_params, opt_state, batch, teacher_params) ->
    (student_params, opt_state, kl)``: the mean over positions of
    ``KL(teacher || student)`` up to the teacher-entropy constant (i.e.
    teacher-probability-weighted student cross-entropy), differentiated
    for the student only. ``teacher_params`` is a step ARGUMENT, not a
    closure — closing over it would bake the full teacher into the
    executable as a constant.

    Both models are applied as plain LMs (``apply({"params": ...},
    batch)`` -> ``[B, T, V]`` logits); softmaxes run in f32 whatever the
    models' compute dtypes.
    """

    @jax.jit
    def step(student_params, opt_state, batch, teacher_params):
        t_probs = jax.nn.softmax(
            teacher.apply({"params": teacher_params}, batch).astype(
                jnp.float32
            ),
            axis=-1,
        )

        def kl(sp):
            s_logp = jax.nn.log_softmax(
                student.apply({"params": sp}, batch).astype(jnp.float32),
                axis=-1,
            )
            return -jnp.mean(jnp.sum(t_probs * s_logp, axis=-1))

        loss, grads = jax.value_and_grad(kl)(student_params)
        updates, opt_state = optimizer.update(
            grads, opt_state, student_params
        )
        return optax.apply_updates(student_params, updates), opt_state, loss

    return step
