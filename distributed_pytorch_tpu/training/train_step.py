"""The fused, jitted SPMD train step — the heart of the framework.

TPU-native restatement of the reference hot loop (``single_gpu.py:21-26``:
``zero_grad -> forward -> loss -> backward -> step``, plus DDP's implicit
bucketed NCCL gradient allreduce under ``loss.backward()`` at
``multigpu.py:42``): the whole thing is ONE pure function compiled by XLA.

* "zero_grad" vanishes — gradients are pure values, not mutable buffers.
* The allreduce vanishes *as user code* — the batch is sharded ``P("data")``
  while parameters are replicated ``P()``, so XLA's SPMD partitioner inserts a
  cross-replica reduce for the gradients inside the compiled executable,
  overlapping it with the backward pass exactly as DDP's bucketing does — but
  scheduled by the compiler onto ICI/DCN rather than hand-tuned buckets.
* Because the loss is a *global-batch mean*, gradient semantics match DDP's
  mean-of-grads when shards are equal-sized (they always are: the loader pads
  by wrapping, mirroring DistributedSampler).
* Non-trainable collections (e.g. BatchNorm ``batch_stats``) ride along in
  ``TrainState.model_state``; under a sharded batch their reductions become
  *global-batch* statistics (SyncBN semantics — stronger than DDP's default
  per-replica stats).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.training import mixed_precision as mp

from distributed_pytorch_tpu.parallel.sharding import (
    batch_sharding,
    replicated_sharding,
)


@flax.struct.dataclass
class TrainState:
    """Replicated training state: parameters, non-trainable model state (e.g.
    BatchNorm stats), optimizer state, step counter.

    The checkpointable unit — unlike the reference, optimizer state is part of
    it (the reference never saves optimizer state, a resume-fidelity gap noted
    in SURVEY.md §5; harmless for SGD, wrong for Adam).

    ``loss_scale`` is ``None`` (no scaling — the bf16/f32 default) or a
    :mod:`.mixed_precision` loss-scale struct; when present the train step
    scales/unscales around ``jax.grad`` and skips non-finite updates. It
    lives here, not in the step builder, so it checkpoints/replicates with
    everything else and the step's behavior follows the state's structure.
    """

    params: Any
    model_state: Any
    opt_state: Any
    step: jnp.ndarray
    loss_scale: Any = None
    # Base PRNG key for stochastic layers (dropout): None (the default
    # structure) means no rngs are threaded and the step compiles exactly
    # as before. Per-step keys are DERIVED as fold_in(rng, step) — the base
    # never advances, so resuming from a snapshot replays the identical
    # mask sequence (reproducibility survives restarts for free).
    rng: Any = None


def create_train_state(
    model,
    optimizer: optax.GradientTransformation,
    sample_input,
    *,
    rng_seed: int = 0,
    loss_scale: Any = None,
    dropout_rng: Any = None,
) -> TrainState:
    """Initialize params + optimizer state from a sample input batch.

    ``dropout_rng`` (an int seed or a PRNG key) arms the step's stochastic
    path: the model is applied with ``rngs={"dropout": fold_in(rng, step)}``
    each step. Leave None for deterministic models/training."""
    rng = jax.random.PRNGKey(rng_seed)
    # Params are batch-size independent: init from a single row, under jit, so
    # startup cost doesn't scale with the global batch (matters for ResNet-50
    # at batch 32*n_chips).
    sample = jnp.asarray(sample_input)[:1]
    variables = dict(jax.jit(model.init)(rng, sample))
    params = variables.pop("params")
    # "losses" holds per-apply sown penalty terms (e.g. MoE load-balance
    # loss), not persistent state — it must not ride in TrainState or flax's
    # sow would append to it every step and change the pytree structure.
    variables.pop("losses", None)
    opt_state = optimizer.init(params)
    if isinstance(dropout_rng, (int, np.integer)):
        # Positive classification: plain/numpy integer seeds become keys;
        # anything else (a typed or raw PRNG key array) passes through.
        dropout_rng = jax.random.PRNGKey(int(dropout_rng))
    return TrainState(
        params=params,
        model_state=variables,
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
        loss_scale=loss_scale,
        rng=dropout_rng,
    )


def make_train_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    *,
    mesh: Optional[Mesh] = None,
    data_axis: str = "data",
    state_sharding: Optional[Any] = None,
    batch_spec: Optional[Any] = None,
    grad_accum: int = 1,
    apply_takes_targets: bool = False,
) -> Callable[[TrainState, Tuple], Tuple[TrainState, jnp.ndarray]]:
    """Build the jitted ``(state, (inputs, targets)) -> (state', loss)`` step.

    With ``mesh``, inputs/targets are expected sharded along ``data_axis`` and
    state replicated; XLA inserts the gradient all-reduce. Without a mesh it is
    the serial rung (``single_gpu.py`` twin) — same code, no collectives.

    ``state_sharding`` (a TrainState-shaped NamedSharding pytree from
    :func:`..parallel.partitioning.make_state_shardings`) switches the state
    from replicated to sharded — tensor-parallel / FSDP placement with the
    SAME step function; only the annotations change and XLA re-plans the
    collectives. ``batch_spec`` (a ``PartitionSpec``) overrides the default
    dim-0-only batch sharding — e.g. ``P("data", "sequence")`` to co-shard
    tokens along the ring-attention sequence axis.

    ``grad_accum > 1`` splits the batch into that many equal microbatches and
    accumulates gradients over a ``lax.scan`` before the single optimizer
    update — same math as the full batch (mean-of-means), at 1/grad_accum the
    peak activation memory. Under a mesh, each microbatch keeps the batch
    sharding (the reshape adds a leading replicated accum dim). Stateful
    collections (BatchNorm stats) update sequentially per microbatch, like N
    consecutive forward passes.

    ``donate_argnums=(0,)`` lets XLA reuse the old state's buffers for the new
    state (in-place update semantics, halving peak parameter memory).

    When ``state.loss_scale`` is a :mod:`.mixed_precision` loss-scale struct
    (set via ``create_train_state(..., loss_scale=...)``), the step
    differentiates the scaled loss, unscales the gradients, skips the
    param/opt/model-state update on non-finite gradients, and carries the
    adjusted scale forward — fp16-style training with zero change to this
    builder's arguments (the behavior keys off the state's pytree structure).

    ``apply_takes_targets=True`` is for models that fuse the loss into the
    forward pass (e.g. ``TransformerLM(fused_head_chunk=...)``, whose fused LM
    head never materializes the logits): ``apply_fn`` is called as
    ``apply_fn(variables, inputs, targets, mutable=...)`` and its output feeds
    ``loss_fn(predictions, targets)`` as usual — pass
    ``loss_fn=lambda out, _: out`` when the model already returns the scalar
    loss.
    """
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    def step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        inputs, targets = batch
        mutable = list(state.model_state.keys())  # static at trace time
        # None vs a loss-scale struct is pytree STRUCTURE, so this branch is
        # resolved at trace time — the unscaled path compiles identically to
        # a build without mixed_precision.
        loss_scale = state.loss_scale

        # Per-step dropout key: derived, never advanced (see TrainState.rng).
        step_rng = (
            jax.random.fold_in(state.rng, state.step)
            if state.rng is not None
            else None
        )

        def micro_loss(params, model_state, mb_inputs, mb_targets, mb_rng=None):
            variables = {"params": params, **model_state}
            # "losses" is always mutable so sown penalty terms surface here;
            # it is popped before the aux state re-enters TrainState (it is
            # per-apply, not persistent — see create_train_state).
            apply_args = (
                (mb_inputs, mb_targets) if apply_takes_targets else (mb_inputs,)
            )
            apply_kw = {"mutable": mutable + ["losses"]}
            if mb_rng is not None:
                apply_kw["rngs"] = {"dropout": mb_rng}
            predictions, new_model_state = apply_fn(
                variables, *apply_args, **apply_kw
            )
            new_model_state = dict(new_model_state)
            loss = loss_fn(predictions, mb_targets)
            for term in jax.tree_util.tree_leaves(new_model_state.pop("losses", {})):
                loss = loss + jnp.sum(term)
            # Differentiate the SCALED loss; report the true one via aux.
            scaled = (
                loss_scale.scale_loss(loss) if loss_scale is not None else loss
            )
            return scaled, (loss, new_model_state)

        grad_fn = jax.grad(micro_loss, has_aux=True)

        if grad_accum == 1:
            grads, (loss, new_model_state) = grad_fn(
                state.params, state.model_state, inputs, targets, step_rng
            )
        else:
            if inputs.shape[0] % grad_accum != 0:
                raise ValueError(
                    f"batch {inputs.shape[0]} not divisible by grad_accum "
                    f"{grad_accum}"
                )
            if mesh is not None:
                axes = (batch_spec or P(data_axis))[0]
                shards = 1
                for ax in axes if isinstance(axes, tuple) else (axes,):
                    shards *= mesh.shape.get(ax, 1) if ax else 1
                if (inputs.shape[0] // grad_accum) % shards != 0:
                    raise ValueError(
                        f"microbatch {inputs.shape[0] // grad_accum} "
                        f"(batch {inputs.shape[0]} / grad_accum {grad_accum}) "
                        f"not divisible by the {shards} batch shards"
                    )

            def split(x):
                x = x.reshape((grad_accum, x.shape[0] // grad_accum) + x.shape[1:])
                if mesh is not None:
                    # Keep each microbatch sharded like the full batch; the
                    # accum dim is replicated (scanned over).
                    spec = batch_spec if batch_spec is not None else P(data_axis)
                    x = jax.lax.with_sharding_constraint(
                        x, NamedSharding(mesh, P(None, *spec))
                    )
                return x

            micro_in, micro_tgt = split(inputs), split(targets)

            def body(carry, mb):
                model_state, grad_sum, loss_sum = carry
                grads, (loss, new_ms) = grad_fn(state.params, model_state, *mb)
                grad_sum = jax.tree_util.tree_map(jnp.add, grad_sum, grads)
                return (new_ms, grad_sum, loss_sum + loss), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
            micro_xs = (micro_in, micro_tgt)
            if step_rng is not None:
                # Distinct dropout mask per microbatch, all derived from the
                # per-step key.
                micro_xs = micro_xs + (
                    jax.vmap(lambda i: jax.random.fold_in(step_rng, i))(
                        jnp.arange(grad_accum)
                    ),
                )
            (new_model_state, grad_sum, loss_sum), _ = jax.lax.scan(
                body,
                (state.model_state, zeros, jnp.zeros((), jnp.float32)),
                micro_xs,
            )
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / grad_accum).astype(p.dtype),
                grad_sum,
                state.params,
            )
            loss = loss_sum / grad_accum

        new_loss_scale = None
        new_model_state = dict(new_model_state)
        if loss_scale is not None:
            grads = loss_scale.unscale(grads)
            finite = mp.all_finite(grads)
            new_loss_scale = loss_scale.adjust(finite)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if loss_scale is not None:
            # Overflow step: keep params/opt/model-state; only the scale and
            # the attempted-step counter move (torch GradScaler semantics).
            sel = lambda n, o: jnp.where(finite, n, o)  # noqa: E731
            new_params = jax.tree_util.tree_map(sel, new_params, state.params)
            new_opt_state = jax.tree_util.tree_map(
                sel, new_opt_state, state.opt_state
            )
            new_model_state = jax.tree_util.tree_map(
                sel, new_model_state, state.model_state
            )
        new_state = TrainState(
            params=new_params,
            model_state=new_model_state,
            opt_state=new_opt_state,
            step=state.step + 1,
            loss_scale=new_loss_scale,
            rng=state.rng,
        )
        return new_state, loss

    return _jit_sharded(
        step,
        mesh=mesh,
        data_axis=data_axis,
        state_sharding=state_sharding,
        batch_spec=batch_spec,
        donate=True,
        out_includes_state=True,
    )


def _jit_sharded(
    fn: Callable,
    *,
    mesh: Optional[Mesh],
    data_axis: str,
    state_sharding: Optional[Any],
    batch_spec: Optional[Any],
    donate: bool,
    out_includes_state: bool,
) -> Callable:
    """Shared jit wiring for the train and eval steps: state + (inputs,
    targets) in, state sharded-or-replicated, batch sharded along the data
    axis (or an explicit spec)."""
    donate_argnums = (0,) if donate else ()
    if mesh is None:
        if state_sharding is not None or batch_spec is not None:
            raise ValueError(
                "state_sharding/batch_spec require mesh=; without one the "
                "computation would silently run unsharded"
            )
        return jax.jit(fn, donate_argnums=donate_argnums)

    replicated = replicated_sharding(mesh)
    state_sh = state_sharding if state_sharding is not None else replicated
    sharded_batch = batch_sharding(mesh, data_axis, spec=batch_spec)
    out_sh = (state_sh, replicated) if out_includes_state else replicated
    return jax.jit(
        fn,
        in_shardings=(state_sh, (sharded_batch, sharded_batch)),
        out_shardings=out_sh,
        donate_argnums=donate_argnums,
    )


def make_eval_step(
    apply_fn: Callable,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    *,
    mesh: Optional[Mesh] = None,
    data_axis: str = "data",
    state_sharding: Optional[Any] = None,
    batch_spec: Optional[Any] = None,
    apply_takes_targets: bool = False,
) -> Callable[[TrainState, Tuple], jnp.ndarray]:
    """Jitted forward-only ``(state, (inputs, targets)) -> loss``.

    ``apply_fn(variables, inputs, mutable=...) -> (predictions, aux)`` should
    already be in eval mode (e.g. ``partial(model.apply, train=False)`` for
    BatchNorm models, so running statistics are used). The ``"losses"``
    collection is collected the same way the train step does, so sown penalty
    terms (MoE load balance) appear in eval loss too and train-vs-eval
    comparisons stay apples-to-apples. No state is donated (evaluation must
    not consume the training state's buffers).
    """

    def eval_step(state: TrainState, batch) -> jnp.ndarray:
        inputs, targets = batch
        apply_args = (inputs, targets) if apply_takes_targets else (inputs,)
        predictions, aux = apply_fn(
            {"params": state.params, **state.model_state},
            *apply_args,
            mutable=["losses"],
        )
        loss = loss_fn(predictions, targets)
        for term in jax.tree_util.tree_leaves(dict(aux).get("losses", {})):
            loss = loss + jnp.sum(term)
        return loss

    return _jit_sharded(
        eval_step,
        mesh=mesh,
        data_axis=data_axis,
        state_sharding=state_sharding,
        batch_spec=batch_spec,
        donate=False,
        out_includes_state=False,
    )


def make_metrics_eval_step(
    apply_fn: Callable,
    metric_fns,
    *,
    mesh: Optional[Mesh] = None,
    data_axis: str = "data",
    state_sharding: Optional[Any] = None,
) -> Callable:
    """Jitted exact-eval step: ``(state, (inputs, targets), weights) -> dict``.

    Each ``metric_fns[name]`` maps ``(predictions, targets) -> [batch]``
    per-sample values (see ``losses.PER_SAMPLE_TWINS``); the step returns
    ``{name: sum(values * weights)}`` plus ``"__weight__": sum(weights)``.
    Pad rows (wrap-padded duplicates from the loader, shard- or batch-level)
    carry weight 0, so accumulating these sums over an epoch and dividing by
    the total weight gives the EXACT distinct-sample mean on any dataset
    size / mesh shape — closing the wrap-pad bias of the opaque-reduction
    eval path. Sown penalty terms ("losses" collection, e.g. MoE load
    balance) are batch-level, not per-sample: they are added to the ``loss``
    metric scaled by the batch's weight so the epoch mean matches the train
    step's accounting.
    """

    def eval_step(state: TrainState, batch, weights) -> dict:
        inputs, targets = batch
        predictions, aux = apply_fn(
            {"params": state.params, **state.model_state},
            inputs,
            mutable=["losses"],
        )
        weight_sum = jnp.sum(weights)
        out = {"__weight__": weight_sum}
        for name, fn in metric_fns.items():
            per = fn(predictions, targets)
            out[name] = jnp.sum(per * weights.astype(per.dtype))
        if "loss" in out:
            for term in jax.tree_util.tree_leaves(dict(aux).get("losses", {})):
                out["loss"] = out["loss"] + jnp.sum(term) * weight_sum
        return out

    if mesh is None:
        return jax.jit(eval_step)
    replicated = replicated_sharding(mesh)
    state_sh = state_sharding if state_sharding is not None else replicated
    sharded = batch_sharding(mesh, data_axis)
    return jax.jit(
        eval_step,
        in_shardings=(state_sh, (sharded, sharded), sharded),
        out_shardings=replicated,
    )
