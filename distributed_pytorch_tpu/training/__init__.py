from distributed_pytorch_tpu.training.losses import (
    mse_loss,
    smoothed_cross_entropy_loss,
    softmax_cross_entropy_loss,
)
from distributed_pytorch_tpu.training.mixed_precision import (
    BF16_POLICY,
    F32_POLICY,
    FP16_POLICY,
    DynamicLossScale,
    Policy,
    StaticLossScale,
)
from distributed_pytorch_tpu.training.train_step import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
)
from distributed_pytorch_tpu.training.trainer import Trainer

__all__ = [
    "BF16_POLICY",
    "F32_POLICY",
    "FP16_POLICY",
    "DynamicLossScale",
    "Policy",
    "StaticLossScale",
    "TrainState",
    "Trainer",
    "create_train_state",
    "make_eval_step",
    "make_train_step",
    "mse_loss",
    "smoothed_cross_entropy_loss",
    "softmax_cross_entropy_loss",
]
