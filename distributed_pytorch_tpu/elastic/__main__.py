"""``python -m distributed_pytorch_tpu.elastic`` — the tpurun CLI."""

import sys

from distributed_pytorch_tpu.elastic.agent import main

sys.exit(main())
