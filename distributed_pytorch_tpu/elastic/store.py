"""Python client + server wrapper for the native ``tpu_kvstore`` rendezvous
store (see ``native/kvstore.cpp``).

This pair replaces the reference's c10d TCPStore: the store process runs on the
rendezvous host (``--rdzv_endpoint head:29500`` in reference
``slurm/sbatch_run.sh:21-22``), every elastic agent connects as a client, and
all coordination — join counting, failure-generation broadcast, barriers —
happens through these few primitives.

Robustness contract of :class:`KVStoreClient` (the "hardened" client):

* any transport failure — refused connect, reset, timeout mid-reply — drops
  the socket AND clears the receive buffer, so a half-read reply can never be
  parsed as the answer to the *next* request (the poisoned-buffer bug);
* idempotent ops (``PING``/``GET``/``WAIT``/``WAITGE``/``KEYS``) are retried
  transparently with capped exponential backoff + jitter until
  ``retry_deadline`` seconds have elapsed, reconnecting between attempts;
* mutating ops (``SET``/``ADD``/``DEL``) are only retried because each carries
  a client-generated request id that the server deduplicates: "applied but the
  reply was lost on the wire" replays the recorded reply instead of
  re-applying (an un-deduped retried ``ADD`` would corrupt every rendezvous
  counter);
* ``retry_deadline=0`` restores fail-fast single-attempt behaviour — the
  agent's heartbeat thread wants a dropped beat over a blocked one.

The distinction the agent builds on top: a store that answers again within
``retry_deadline`` was a *blip* (invisible to callers); one that does not is
treated as *rendezvous host dead* — the eventual ``ConnectionError`` surfaces
through the agent's existing fatal/world-completed paths.
"""

from __future__ import annotations

import os
import random
import socket
import subprocess
import time
import urllib.parse
from typing import Callable, List, Optional, Tuple

from distributed_pytorch_tpu.native import kvstore_binary

# Backoff: 0.05, 0.1, 0.2, ... capped at 1s, each scaled by jitter in [0.5, 1).
_BACKOFF_BASE = 0.05
_BACKOFF_CAP = 1.0


def _encode(s: str) -> str:
    """Keys/values must be whitespace-free on the wire; percent-encode."""
    return urllib.parse.quote(s, safe="")


def _decode(s: str) -> str:
    return urllib.parse.unquote(s)


class KVStoreServer:
    """Runs the native store binary as a child process and waits for readiness."""

    def __init__(self, port: int, bind_addr: str = "0.0.0.0"):
        self.port = port
        self._proc = subprocess.Popen(
            [kvstore_binary(), str(port), bind_addr],
            stdout=subprocess.PIPE,
            text=True,
        )
        line = self._proc.stdout.readline()
        if "LISTENING" not in line:
            raise RuntimeError(f"tpu_kvstore failed to start (got {line!r})")

    def close(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()
        if self._proc.stdout is not None:
            self._proc.stdout.close()  # the readiness PIPE otherwise leaks an fd

    def __enter__(self) -> "KVStoreServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class KVStoreClient:
    """Blocking line-protocol client with transparent reconnect. Methods are
    synchronous and return decoded values; see the module docstring for the
    retry/dedup contract."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        connect_timeout: float = 60.0,
        retry_deadline: float = 30.0,
    ):
        self.host = host
        self.port = port
        self.retry_deadline = retry_deadline
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        # Request ids must be unique across every client that ever talks to
        # one server (pids recycle, agents restart): pid + random tag + counter.
        self._req_tag = f"{os.getpid():x}.{random.getrandbits(48):012x}"
        self._req_n = 0
        self._jitter = random.Random(self._req_tag)
        self._connect(connect_timeout)

    # ---------------------------------------------------------- transport
    def _connect(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        last_err: Optional[Exception] = None
        while True:
            try:
                sock = socket.create_connection((self.host, self.port), timeout=5)
                sock.settimeout(None)  # requests manage their own timeouts
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = sock
                self._buf = b""
                return
            except OSError as e:  # server may not be up yet (agent races store)
                last_err = e
                if time.monotonic() + 0.1 >= deadline:
                    raise ConnectionError(
                        f"cannot reach kvstore at {self.host}:{self.port}: {last_err}"
                    ) from last_err
                time.sleep(0.1)

    def _drop_connection(self) -> None:
        """Poisoned-buffer reset: after any transport error the stream may
        hold a partial or stale frame — discard both socket and buffer so the
        next request starts on a clean stream."""
        self._buf = b""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _request_once(self, tokens: List[str], timeout: Optional[float]) -> List[str]:
        if self._sock is None:
            self._connect(2.0)  # short: the outer retry loop owns the deadline
        line = " ".join(tokens) + "\n"
        self._sock.settimeout(timeout)
        try:
            self._sock.sendall(line.encode())
            while b"\n" not in self._buf:
                chunk = self._sock.recv(4096)
                if not chunk:
                    raise ConnectionError("kvstore connection closed")
                self._buf += chunk
            self._sock.settimeout(None)
        except Exception:
            self._drop_connection()
            raise
        raw, self._buf = self._buf.split(b"\n", 1)
        parts = raw.decode().split(" ")
        if parts[0] == "ERR":
            raise RuntimeError(f"kvstore error: {' '.join(parts[1:])}")
        return parts

    def _request(
        self,
        build: Callable[[], Tuple[List[str], Optional[float]]],
        *,
        mutating: bool = False,
        retry: bool = True,
    ) -> List[str]:
        """Run one logical request through the retry loop.

        ``build`` produces (tokens, per-attempt timeout) fresh on every
        attempt so WAIT-style ops can shrink their server-side timeout to the
        time remaining. A mutating request gets ONE id for its lifetime —
        reused across retries, which is what makes the retry safe.
        """
        reqid: Optional[str] = None
        if mutating:
            self._req_n += 1
            reqid = f"{self._req_tag}.{self._req_n}"
        start = time.monotonic()
        attempt = 0
        while True:
            tokens, timeout = build()
            if reqid is not None:
                tokens = tokens + [reqid]
            try:
                return self._request_once(tokens, timeout)
            except (ConnectionError, OSError) as e:
                if not retry or self.retry_deadline <= 0:
                    raise
                elapsed = time.monotonic() - start
                remaining = self.retry_deadline - elapsed
                if remaining <= 0:
                    raise ConnectionError(
                        f"kvstore at {self.host}:{self.port} unreachable for "
                        f"{elapsed:.1f}s (retry deadline {self.retry_deadline}s): {e}"
                    ) from e
                backoff = min(_BACKOFF_CAP, _BACKOFF_BASE * (2 ** attempt))
                backoff *= 0.5 + 0.5 * self._jitter.random()
                attempt += 1
                time.sleep(min(backoff, remaining))

    def _simple(
        self,
        *tokens: str,
        mutating: bool = False,
        retry: bool = True,
        timeout: Optional[float] = None,
    ) -> List[str]:
        return self._request(
            lambda: (list(tokens), timeout), mutating=mutating, retry=retry
        )

    # ----------------------------------------------------------- protocol
    def ping(self) -> bool:
        return self._simple("PING")[0] == "PONG"

    def set(self, key: str, value: str) -> None:
        self._simple("SET", _encode(key), _encode(value), mutating=True)

    def get(self, key: str) -> Optional[str]:
        parts = self._simple("GET", _encode(key))
        return _decode(parts[1]) if parts[0] == "VAL" else None

    def add(self, key: str, delta: int = 1) -> int:
        return int(self._simple("ADD", _encode(key), str(delta), mutating=True)[1])

    def wait(self, key: str, timeout: Optional[float] = None) -> Optional[str]:
        """Block until ``key`` exists; None on timeout. Survives reconnects:
        each retry re-issues WAIT with only the time still remaining."""
        deadline = None if timeout is None else time.monotonic() + timeout

        def build() -> Tuple[List[str], Optional[float]]:
            if deadline is None:
                return ["WAIT", _encode(key)], None
            rem = max(0.0, deadline - time.monotonic())
            return ["WAIT", _encode(key), str(int(rem * 1000))], rem + 5

        parts = self._request(build)
        return _decode(parts[1]) if parts[0] == "VAL" else None

    def wait_ge(
        self, key: str, target: int, timeout: Optional[float] = None
    ) -> Optional[int]:
        """Block until int value of ``key`` >= target; None on timeout.
        Survives reconnects the same way as :meth:`wait`."""
        deadline = None if timeout is None else time.monotonic() + timeout

        def build() -> Tuple[List[str], Optional[float]]:
            args = ["WAITGE", _encode(key), str(target)]
            if deadline is None:
                return args, None
            rem = max(0.0, deadline - time.monotonic())
            return args + [str(int(rem * 1000))], rem + 5

        parts = self._request(build)
        return int(parts[1]) if parts[0] == "VAL" else None

    def delete(self, key: str) -> None:
        self._simple("DEL", _encode(key), mutating=True)

    def keys(self, prefix: str = "") -> List[str]:
        parts = (
            self._simple("KEYS", _encode(prefix)) if prefix else self._simple("KEYS")
        )
        return [_decode(p) for p in parts[1:]]

    def shutdown_server(self) -> None:
        try:
            # Bounded: the reply races the server's own exit (and any proxy in
            # between), and a lost reply must not wedge agent shutdown.
            self._simple("SHUTDOWN", retry=False, timeout=5.0)
        except (ConnectionError, OSError):
            pass  # server exiting mid-reply is fine

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __enter__(self) -> "KVStoreClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
