"""Python client + server wrapper for the native ``tpu_kvstore`` rendezvous
store (see ``native/kvstore.cpp``).

This pair replaces the reference's c10d TCPStore: the store process runs on the
rendezvous host (``--rdzv_endpoint head:29500`` in reference
``slurm/sbatch_run.sh:21-22``), every elastic agent connects as a client, and
all coordination — join counting, failure-generation broadcast, barriers —
happens through these few primitives.
"""

from __future__ import annotations

import socket
import subprocess
import time
import urllib.parse
from typing import List, Optional

from distributed_pytorch_tpu.native import kvstore_binary


def _encode(s: str) -> str:
    """Keys/values must be whitespace-free on the wire; percent-encode."""
    return urllib.parse.quote(s, safe="")


def _decode(s: str) -> str:
    return urllib.parse.unquote(s)


class KVStoreServer:
    """Runs the native store binary as a child process and waits for readiness."""

    def __init__(self, port: int, bind_addr: str = "0.0.0.0"):
        self.port = port
        self._proc = subprocess.Popen(
            [kvstore_binary(), str(port), bind_addr],
            stdout=subprocess.PIPE,
            text=True,
        )
        line = self._proc.stdout.readline()
        if "LISTENING" not in line:
            raise RuntimeError(f"tpu_kvstore failed to start (got {line!r})")

    def close(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()

    def __enter__(self) -> "KVStoreServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class KVStoreClient:
    """Blocking line-protocol client. One TCP connection per client; methods
    are synchronous and return decoded values."""

    def __init__(self, host: str, port: int, *, connect_timeout: float = 60.0):
        deadline = time.monotonic() + connect_timeout
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                self._sock.settimeout(None)  # requests manage their own timeouts
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._buf = b""
                return
            except OSError as e:  # server may not be up yet (agent races store)
                last_err = e
                time.sleep(0.1)
        raise ConnectionError(f"cannot reach kvstore at {host}:{port}: {last_err}")

    def _request(self, *tokens: str, timeout: Optional[float] = None) -> List[str]:
        line = " ".join(tokens) + "\n"
        self._sock.settimeout(timeout)
        try:
            self._sock.sendall(line.encode())
            while b"\n" not in self._buf:
                chunk = self._sock.recv(4096)
                if not chunk:
                    raise ConnectionError("kvstore connection closed")
                self._buf += chunk
        finally:
            self._sock.settimeout(None)
        raw, self._buf = self._buf.split(b"\n", 1)
        parts = raw.decode().split(" ")
        if parts[0] == "ERR":
            raise RuntimeError(f"kvstore error: {' '.join(parts[1:])}")
        return parts

    def ping(self) -> bool:
        return self._request("PING")[0] == "PONG"

    def set(self, key: str, value: str) -> None:
        self._request("SET", _encode(key), _encode(value))

    def get(self, key: str) -> Optional[str]:
        parts = self._request("GET", _encode(key))
        return _decode(parts[1]) if parts[0] == "VAL" else None

    def add(self, key: str, delta: int = 1) -> int:
        return int(self._request("ADD", _encode(key), str(delta))[1])

    def wait(self, key: str, timeout: Optional[float] = None) -> Optional[str]:
        """Block until ``key`` exists; None on timeout."""
        args = ["WAIT", _encode(key)]
        if timeout is not None:
            args.append(str(int(timeout * 1000)))
        parts = self._request(*args, timeout=None if timeout is None else timeout + 5)
        return _decode(parts[1]) if parts[0] == "VAL" else None

    def wait_ge(self, key: str, target: int, timeout: Optional[float] = None) -> Optional[int]:
        """Block until int value of ``key`` >= target; None on timeout."""
        args = ["WAITGE", _encode(key), str(target)]
        if timeout is not None:
            args.append(str(int(timeout * 1000)))
        parts = self._request(*args, timeout=None if timeout is None else timeout + 5)
        return int(parts[1]) if parts[0] == "VAL" else None

    def delete(self, key: str) -> None:
        self._request("DEL", _encode(key))

    def keys(self, prefix: str = "") -> List[str]:
        parts = self._request("KEYS", _encode(prefix)) if prefix else self._request("KEYS")
        return [_decode(p) for p in parts[1:]]

    def shutdown_server(self) -> None:
        try:
            self._request("SHUTDOWN")
        except (ConnectionError, OSError):
            pass  # server exiting mid-reply is fine

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "KVStoreClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
