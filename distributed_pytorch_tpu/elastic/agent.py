"""``tpurun`` — the elastic launch agent (torchrun twin).

The reference's rungs 3-4 lean on ``torchrun`` for everything the scripts
don't do themselves (SURVEY.md §3.3): env-var rendezvous, per-node worker
spawning, failure detection, and restart-the-world recovery. This module is
that machinery, TPU-native:

* **Env contract** — workers receive ``COORDINATOR_ADDRESS`` /
  ``NUM_PROCESSES`` / ``PROCESS_ID`` / ``LOCAL_RANK`` /
  ``TPURUN_RESTART_COUNT`` (the torchrun ``MASTER_ADDR:PORT`` / ``WORLD_SIZE``
  / ``RANK`` / ``LOCAL_RANK`` / ``TORCHELASTIC_RESTART_COUNT`` analog,
  reference ``multigpu_torchrun.py:24``); ``setup_distributed()`` consumes
  them (``parallel/bootstrap.py``).
* **Rendezvous** — agents meet at a native C++ TCP store (``elastic/store.py``,
  the c10d TCPStore twin) on the rendezvous host; node 0's agent runs the
  store. Joins are counted per generation; everyone proceeds when all
  ``nnodes`` agents have joined. JAX's own coordination service is a second
  port on the same host, bound by *worker* 0 (``--jax-coordinator-port``,
  default: rendezvous port + 1).
* **Failure detection** — local: the agent polls its workers; any nonzero exit
  is a failure, and with ``--worker-heartbeat-timeout`` a worker that is
  alive but silent (wedged in a collective, SIGSTOPped) is declared hung
  when it stops touching its ``TPURUN_HEARTBEAT_FILE`` (the Trainer touches
  it every batch). Remote: each agent heartbeats ``hb/<node>`` into the store
  and the monitor watches the failure-generation key and peer heartbeats.
* **Recovery** — torchrun's restart-all policy: on any failure the detecting
  agent bumps the generation key; every agent kills its local workers,
  re-rendezvouses at the new generation, and respawns, up to
  ``--max-restarts``. Training survives because the Trainer's snapshot
  contract (probe-on-init, epoch-offset resume — reference
  ``multigpu_torchrun.py:30-40,57-65``) makes workers idempotent.
* **Preemption drain** — SIGTERM on an agent (a maintenance event / spot
  reclaim notice) starts a graceful drain instead of a teardown: the agent
  publishes ``drain/<gen>`` in the store, touches each worker's
  ``TPURUN_DRAIN_FILE`` and soft-signals SIGTERM; the Trainer finishes the
  in-flight step, takes a just-in-time STEP-granular snapshot (all ranks
  agree on the stop step via a per-batch collective, so no survivor ever
  issues a collective against a vanished peer), and exits with
  ``--preempt-exit-code``. The monitor classifies that exit — and any
  drain-marked generation bump — as a *preemption*: the world restarts
  WITHOUT spending ``--max-restarts`` budget, and the reclaimed node's agent
  exits after its workers drain (survivors re-form via MIN:MAX scale-down).
  Workers get ``--drain-grace`` seconds before SIGKILL.
* **Elastic world size** — ``--nnodes MIN:MAX`` (the torchrun elastic form,
  reference launcher surface ``slurm/sbatch_run.sh:17-23``): when a node is
  lost for good, the next rendezvous waits ``--scale-down-grace`` seconds
  for the full world and then re-forms with the >= MIN nodes that joined —
  dense re-ranks, smaller ``NUM_PROCESSES``, loaders re-shard from the new
  env on snapshot resume (every sample still visited exactly once per
  epoch). A node that revives later triggers one restart and scales the
  world back up.

Single node (``--standalone``) and multi-node (``--nnodes``/``--node-rank``/
``--rdzv-endpoint host:port``, the ``sbatch_run.sh:17-23`` shape) use the
identical code path; single-node simply has ``nnodes=1`` and the store on
localhost.

Usage::

    python -m distributed_pytorch_tpu.elastic.agent --nproc-per-node 4 \
        --max-restarts 3 train.py --epochs 10
    # multi-node, on every node:
    python -m distributed_pytorch_tpu.elastic.agent --nnodes 4 --node-rank $I \
        --nproc-per-node 1 --rdzv-endpoint head:29400 train.py
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from distributed_pytorch_tpu.chaos import FaultProxy, get_plan as _get_fault_plan
from distributed_pytorch_tpu.elastic.store import KVStoreClient, KVStoreServer
from distributed_pytorch_tpu.obs import MetricsRegistry

GEN_KEY = "tpurun/generation"  # bumped on every failure -> restart-the-world
FATAL_KEY = "tpurun/fatal"  # set when restarts are exhausted or world aborts
DONE_PREFIX = "tpurun/done/"  # done/<gen> counts agents whose workers finished
FINISHED_PREFIX = "tpurun/finished/"  # finished/<gen> terminal marker: done/<gen> reached the world size
# How long the done counter may STALL (no new adds) after a generation bump
# before a locally-succeeded agent honors the bump and restarts: a bump can
# race the last DONE adds (agents add DONE unconditionally once their
# workers succeed, within one ~0.2s poll cycle), so honoring it instantly
# could split the world between "done" and "restart" verdicts. The deadline
# EXTENDS while the counter advances — completion in flight — and a counter
# that stalls (a member truly failed: its DONE will never come) restarts
# after only this long, so genuine failures aren't delayed by a fixed
# worst-case grace.
DONE_BUMP_GRACE = 3.0
ACK_PREFIX = "tpurun/ack/"  # ack/<gen> exit barrier: node 0 keeps the store up until all ack
JOIN_PREFIX = "tpurun/join/"  # join/<gen> counts agents present at <gen>
MEMBER_PREFIX = "tpurun/member/"  # member/<gen>/<orig_rank> -> "1" (who joined)
WORLD_PREFIX = "tpurun/world/"  # world/<gen> -> "0,2,..." settled membership
HB_PREFIX = "tpurun/hb/"  # hb/<node_rank> -> monotonically increasing beat
# drain/<gen> -> "node<rank>": generation <gen> is ending by PREEMPTION, not
# failure. Set by the SIGTERM-caught agent BEFORE it bumps the generation, so
# every peer (a) forwards the soft drain signal to its own workers — the
# in-band drain barrier that stops all ranks at the same step — and (b)
# classifies the coming restart as a preemption (restart budget intact).
DRAIN_PREFIX = "tpurun/drain/"


@dataclass
class ElasticConfig:
    nproc_per_node: int = 1
    nnodes: int = 1
    # torchrun's ``--nnodes MIN:MAX`` lower bound: when a node dies for good,
    # the next rendezvous waits ``scale_down_grace`` seconds for the full
    # world, then re-forms with every node that DID join (>= min_nnodes) —
    # dense re-ranked, workers respawned with the smaller NUM_PROCESSES, and
    # the loader re-sharding from the new env on resume. min_nnodes == nnodes
    # (the default) disables scale-down: rendezvous insists on a full world.
    # Node 0 can never be scaled out — it hosts the store (exactly like
    # torchrun's c10d endpoint host).
    min_nnodes: int = 0  # 0 -> nnodes (fixed-size world)
    scale_down_grace: float = 30.0
    node_rank: int = 0
    rdzv_host: str = "127.0.0.1"
    rdzv_port: int = 29400
    # Port for JAX's own coordination service (run by global process 0 of the
    # *workers*, not by the agent). Defaults to rdzv_port + 1; set explicitly
    # (--jax-coordinator-port) when that neighbor port may be taken.
    jax_coordinator_port: Optional[int] = None
    max_restarts: int = 3
    heartbeat_interval: float = 2.0
    heartbeat_timeout: float = 30.0
    # > 0 enables HUNG-worker detection (exit-code polling only catches death;
    # a worker wedged in a collective whose peer vanished, or SIGSTOPped,
    # would otherwise hang the world silently): each worker gets a
    # TPURUN_HEARTBEAT_FILE env var and must touch that file at least this
    # often once training starts (the Trainer does so every batch). The clock
    # starts at spawn, so set it above worst-case startup + compile time.
    worker_heartbeat_timeout: float = 0.0
    # The blip/dead boundary for the rendezvous store: transport failures are
    # retried transparently inside KVStoreClient for this many seconds (a
    # store restart or network partition shorter than this is INVISIBLE to
    # the agent); only after the deadline does a ConnectionError surface, and
    # the agent then treats the rendezvous host as dead (WorldCompleted /
    # abort, the pre-existing paths).
    store_retry_deadline: float = 30.0
    # Preemption drain: on SIGTERM the agent publishes drain/<gen>, touches
    # each worker's TPURUN_DRAIN_FILE, and soft-signals SIGTERM; workers have
    # this many seconds to finish the in-flight step and snapshot before the
    # group is killed (size it to the platform's reclaim grace minus margin).
    drain_grace: float = 30.0
    # The distinguished exit code a draining worker uses (exported to workers
    # as TPURUN_DRAIN_EXIT_CODE). The monitor classifies this exit as a
    # preemption — restart-the-world WITHOUT decrementing --max-restarts.
    preempt_exit_code: int = 121
    env: Dict[str, str] = field(default_factory=dict)

    @property
    def max_world_size(self) -> int:
        """Worker count of a FULL world. With ``--nnodes MIN:MAX`` the live
        world can be smaller — the per-generation size is always
        ``len(members) * nproc_per_node`` (see :class:`WorkerGroup`)."""
        return self.nnodes * self.nproc_per_node

    @property
    def min_world_nodes(self) -> int:
        return self.min_nnodes or self.nnodes

    @property
    def coordinator_address(self) -> str:
        port = self.jax_coordinator_port
        if port is None:
            port = self.rdzv_port + 1
        return f"{self.rdzv_host}:{port}"


class WorkerGroup:
    """The local workers of one agent: spawn, poll, terminate.

    ``members`` is the settled node membership of this generation (original
    node ranks, sorted): with scale-down it can be smaller than
    ``cfg.nnodes``, and the env contract is computed from the DENSE rank of
    this node within it — workers always see a contiguous, gap-free
    PROCESS_ID space sized to the live world.
    """

    def __init__(
        self,
        cfg: ElasticConfig,
        cmd: List[str],
        restart_count: int,
        members: Optional[List[int]] = None,
    ):
        members = members if members is not None else list(range(cfg.nnodes))
        world_size = len(members) * cfg.nproc_per_node
        dense_rank = members.index(cfg.node_rank)
        self.procs: List[subprocess.Popen] = []
        self.hb_dir: Optional[str] = None
        self.hb_files: List[str] = []
        self.spawned_at = time.monotonic()
        # Per-worker (last observed mtime, monotonic time it changed) — the
        # staleness clock starts at spawn (mtime None until the first touch).
        self._beats: List[tuple] = [
            (None, self.spawned_at) for _ in range(cfg.nproc_per_node)
        ]
        if cfg.worker_heartbeat_timeout > 0:
            import tempfile

            self.hb_dir = tempfile.mkdtemp(prefix="tpurun_hb_")
        # Drain contract: each worker gets a TPURUN_DRAIN_FILE path; the
        # agent touching it (request_drain) is the soft preemption notice the
        # Trainer polls every batch, and TPURUN_DRAIN_EXIT_CODE is the
        # distinguished code a drained worker exits with. The file ALSO
        # disambiguates SIGTERM for the worker: SIGTERM with the file touched
        # means "snapshot and go"; bare SIGTERM (a failure teardown) means
        # "die now" — so failure restarts stay fast.
        import tempfile as _tempfile

        self.drain_dir = _tempfile.mkdtemp(prefix="tpurun_drain_")
        self.drain_files: List[str] = []
        self._drain_sent = False
        for local_rank in range(cfg.nproc_per_node):
            env = dict(os.environ)
            env.update(cfg.env)
            drain_file = os.path.join(self.drain_dir, f"drain_{local_rank}")
            self.drain_files.append(drain_file)
            env.update(
                COORDINATOR_ADDRESS=cfg.coordinator_address,
                NUM_PROCESSES=str(world_size),
                PROCESS_ID=str(dense_rank * cfg.nproc_per_node + local_rank),
                LOCAL_RANK=str(local_rank),
                TPURUN_RESTART_COUNT=str(restart_count),
                TPURUN_DRAIN_FILE=drain_file,
                TPURUN_DRAIN_EXIT_CODE=str(cfg.preempt_exit_code),
            )
            if self.hb_dir is not None:
                hb_file = os.path.join(self.hb_dir, f"hb_{local_rank}")
                env["TPURUN_HEARTBEAT_FILE"] = hb_file
                self.hb_files.append(hb_file)
            self.procs.append(subprocess.Popen(cmd, env=env))

    def request_drain(self) -> None:
        """Deliver the soft preemption notice to every live worker: touch its
        drain file FIRST (so the worker's SIGTERM handler reads this as a
        drain, not a teardown), then SIGTERM. Idempotent."""
        if self._drain_sent:
            return
        self._drain_sent = True
        for drain_file in self.drain_files:
            try:
                with open(drain_file, "w") as f:
                    f.write("drain")
            except OSError:
                pass
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.terminate()  # SIGTERM; the drain file makes it soft
                except OSError:
                    pass

    def hung_worker(self, timeout: float) -> Optional[int]:
        """Local rank of a live worker whose heartbeat file went stale.

        Staleness is judged on THIS process's monotonic clock: we record when
        the observed mtime last *changed* (same pattern as the agent-level
        ``_peer_dead``), so NTP clock steps can neither declare a healthy
        worker hung nor mask a real hang. A worker that never touched its
        file is measured from spawn (startup and first-compile count against
        the timeout — size it accordingly). Finished workers are exempt: no
        more beats are expected of them."""
        now = time.monotonic()
        for local_rank, (proc, hb_file) in enumerate(
            zip(self.procs, self.hb_files)
        ):
            if proc.poll() is not None:
                continue
            try:
                mtime = os.path.getmtime(hb_file)
            except OSError:
                mtime = None
            last_mtime, seen_at = self._beats[local_rank]
            if mtime != last_mtime:
                self._beats[local_rank] = (mtime, now)
            elif now - seen_at > timeout:
                return local_rank
        return None

    def poll(self) -> Optional[int]:
        """None while all run / after all succeeded; first nonzero exit code if
        any worker failed."""
        for p in self.procs:
            code = p.poll()
            if code is not None and code != 0:
                return code
        return None

    def all_done(self) -> bool:
        return all(p.poll() == 0 for p in self.procs)

    def terminate(self, grace: float = 10.0) -> None:
        """SIGTERM every live worker, then ESCALATE to SIGKILL for any still
        alive past the shared ``grace`` deadline. The escalation is
        load-bearing: a worker mid-drain-snapshot (or wedged inside one, or
        one that installed a SIGTERM handler and got stuck) must not block
        agent teardown forever. SIGKILL cannot be caught, so the post-kill
        ``wait`` always returns — but it is still bounded defensively (a
        zombie reparented by a dying init, an uninterruptible-D-state worker)
        rather than allowed to wedge the whole restart loop."""
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + grace
        for p in self.procs:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
                try:
                    p.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass  # unreapable (kernel-stuck); do not block teardown
        import shutil

        if self.hb_dir is not None:
            shutil.rmtree(self.hb_dir, ignore_errors=True)
            self.hb_dir = None
        if self.drain_dir is not None:
            shutil.rmtree(self.drain_dir, ignore_errors=True)
            self.drain_dir = None


class _Retry(Exception):
    """Internal: loop the rendezvous again; carries the grace clock, which
    resets when a NEW generation was joined during the pass."""

    def __init__(self, grace_start: float):
        self.grace_start = grace_start


class WorldCompleted(Exception):
    """The world finished without this node: either the rendezvous store
    vanished mid-rendezvous (it lives on node 0's agent, which tears it
    down only when the world finished), or — ``finished=True`` — the store
    is still up and the settled generation's done counter already reached
    its member count. A node still trying to (re)join (e.g. one revived
    after a scale-down) should exit cleanly, not crash or force a restart
    of a world that has nothing left to restart."""

    def __init__(self, finished: bool = False):
        super().__init__()
        self.finished = finished


class ElasticAgent:
    """One per node. Runs the rendezvous/spawn/monitor/restart loop."""

    def __init__(self, cfg: ElasticConfig, cmd: List[str]):
        self.cfg = cfg
        self.cmd = cmd
        self.server: Optional[KVStoreServer] = None
        if cfg.node_rank == 0:
            self.server = KVStoreServer(cfg.rdzv_port)
        # Chaos: when the armed FaultPlan carries store_partition faults,
        # route this agent's store traffic through a local FaultProxy so the
        # partition can be injected without touching the real store. The
        # server (above) still binds the real rdzv port for the other agents.
        self._chaos_proxy: Optional[FaultProxy] = None
        store_host, store_port = cfg.rdzv_host, cfg.rdzv_port
        plan = _get_fault_plan()
        if plan is not None and plan.store_partitions():
            self._chaos_proxy = FaultProxy(cfg.rdzv_host, cfg.rdzv_port).start()
            self._chaos_proxy.apply_plan(plan)
            store_host, store_port = self._chaos_proxy.host, self._chaos_proxy.port
            print(
                f"[tpurun] chaos: store traffic via FaultProxy "
                f"{store_host}:{store_port}",
                flush=True,
            )
        self._store_endpoint = (store_host, store_port)
        self.store = KVStoreClient(
            store_host, store_port, retry_deadline=cfg.store_retry_deadline
        )
        self._stop_hb = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._group: Optional[WorkerGroup] = None
        self._joined_generations: set = set()
        # rank -> (last beat value, local monotonic time it changed)
        self._peer_beats: Dict[int, tuple] = {}
        # Set by the SIGTERM handler (main()): THIS node is being reclaimed.
        # Signal handlers must not touch the store client (the main thread
        # may be mid-request on the same socket), so the handler only sets
        # the event; the monitor loop performs the store publish + worker
        # drain on its next 0.2s pass.
        self._drain_requested = threading.Event()
        # Unified observability: restart/drain/chaos counters in an
        # ``elastic_``-namespaced registry (push-style Counters — the run
        # loop's locals keep their budget semantics, these are the export
        # surface). Incremented alongside, never instead.
        self.registry = MetricsRegistry(namespace="elastic")
        self._c_spawns = self.registry.counter("spawns_total")
        self._c_restarts = self.registry.counter("restarts_total")
        self._c_preempt_restarts = self.registry.counter(
            "preempt_restarts_total"
        )
        self._c_drains = self.registry.counter("drains_requested_total")
        # Goodput accounting, agent half: wall-clock lost to respawn
        # churn — from the moment a generation's failure is classified to
        # the next spawn — split by whether the restart was a free
        # preemption or burned the failure budget.
        self._c_restart_downtime = self.registry.counter(
            "restart_downtime_seconds_total",
            help="Wall-clock between failure classification and respawn",
        )
        self._c_preempt_downtime = self.registry.counter(
            "preempt_downtime_seconds_total",
            help="Restart downtime attributable to preemption drains",
        )
        self.registry.gauge(
            "chaos_faults_armed",
            float(len(plan.faults)) if plan is not None else 0.0,
        )

    def request_drain(self) -> bool:
        """Begin a graceful preemption drain (signal-handler safe: flag only).
        Returns False if a drain was already in progress — the caller should
        then escalate to an immediate exit (second SIGTERM = die now)."""
        if self._drain_requested.is_set():
            return False
        self._drain_requested.set()
        self._c_drains.inc()
        return True

    # ------------------------------------------------------------- heartbeat
    def _heartbeat_loop(self) -> None:
        """Publish a monotonically increasing beat counter on one persistent
        connection; reconnect on transient store errors (a dropped beat must
        not look like a dead node)."""
        beat = 0
        client: Optional[KVStoreClient] = None
        while not self._stop_hb.wait(self.cfg.heartbeat_interval):
            beat += 1
            try:
                if client is None:
                    # retry_deadline=0: a beat is time-sensitive — better to
                    # drop it and reconnect next interval than block the
                    # loop retrying (this loop IS the liveness signal).
                    client = KVStoreClient(
                        *self._store_endpoint,
                        connect_timeout=5.0,
                        retry_deadline=0.0,
                    )
                client.set(f"{HB_PREFIX}{self.cfg.node_rank}", str(beat))
            except (ConnectionError, OSError):
                if client is not None:
                    client.close()
                client = None  # retry with a fresh connection next beat
        if client is not None:
            client.close()

    def _peer_dead(self, members: Optional[List[int]] = None) -> Optional[int]:
        """Node rank of a peer whose heartbeat went stale, if any.

        Only peers in ``members`` (this generation's settled world) are
        consulted: after a scale-down, the long-dead node's stale beat must
        not re-trigger a restart every generation.

        Staleness is judged purely on this node's monotonic clock — the beat
        value is an opaque counter, never a timestamp — so cross-host clock
        skew cannot declare a healthy peer dead. A peer with no beat at all is
        measured from when monitoring began (``_seed_peer_clocks``): every
        agent rendezvoused before workers spawned, so a node frozen before its
        first heartbeat write must still be declared dead, not waited on
        forever."""
        now = time.monotonic()
        ranks = members if members is not None else range(self.cfg.nnodes)
        for rank in ranks:
            if rank == self.cfg.node_rank:
                continue
            beat = self.store.get(f"{HB_PREFIX}{rank}")
            last_beat, seen_at = self._peer_beats.get(rank, (None, None))
            if seen_at is None or beat != last_beat:
                self._peer_beats[rank] = (beat, now)
            elif now - seen_at > self.cfg.heartbeat_timeout:
                return rank
        return None

    def _seed_peer_clocks(self, members: Optional[List[int]] = None) -> None:
        """(Re)start every peer's staleness clock at monitor start.

        Each generation grants each peer a fresh ``heartbeat_timeout`` window:
        a peer declared dead last generation has just re-rendezvoused, and its
        pre-freeze beat value must not count as already-stale (that would
        re-declare a recovered node dead instantly and burn extra restarts)."""
        now = time.monotonic()
        ranks = members if members is not None else range(self.cfg.nnodes)
        for rank in ranks:
            if rank != self.cfg.node_rank:
                last_beat = self._peer_beats.get(rank, (None, None))[0]
                self._peer_beats[rank] = (last_beat, now)

    # ------------------------------------------------------------- lifecycle
    def _rendezvous(self, timeout: float = 600.0) -> tuple:
        """Join the current generation and block until the world settles;
        returns ``(generation, members)`` where ``members`` is the sorted
        list of original node ranks in this generation's world.

        Concurrent failures can bump the generation while we wait (two
        agents may each bump for the same incident — ADD is atomic, so the
        world just skips a number); re-join whatever the latest generation
        is, joining each at most once so counts stay exact.

        Membership is decided by ONE writer — node 0, which hosts the store
        and is therefore always present — and published under
        ``world/<gen>``, so every agent sees the identical member list with
        no read races. Node 0 publishes the moment all ``nnodes`` agents
        join; with ``min_nnodes < nnodes`` (torchrun's ``MIN:MAX``) it also
        publishes after ``scale_down_grace`` seconds once at least
        ``min_nnodes`` joined — the scale-down path. An agent that joins
        AFTER the world settled without it (a node revived past the grace
        window) bumps the generation, forcing a fresh rendezvous that
        includes it — torchrun's join-triggers-restart, which is also the
        scale-UP path. While a world is degraded, every later rendezvous
        pays the grace wait for the missing nodes before re-settling small;
        keep ``scale_down_grace`` modest.
        """
        cfg = self.cfg
        deadline = time.monotonic() + timeout
        grace_start = time.monotonic()
        while time.monotonic() < deadline:
            try:
                return self._rendezvous_once(cfg, grace_start)
            except _Retry as r:
                grace_start = r.grace_start
            except (ConnectionError, OSError):
                # Store gone: node 0's agent tears it down only after the
                # world completed. A node still (re)joining — e.g. revived
                # after a scale-down — should exit cleanly, not crash.
                raise WorldCompleted() from None
        raise RuntimeError(
            f"rendezvous timed out ({self.cfg.nnodes} nodes expected)"
        )

    def _rendezvous_once(self, cfg, grace_start):
        """One pass of the join/settle/read protocol; raises ``_Retry`` to
        loop (carrying the possibly-reset grace clock)."""
        generation = int(self.store.get(GEN_KEY) or 0)
        if generation not in self._joined_generations:
            # Membership mark BEFORE the join count: when the counter
            # reads n, all n member keys are already visible.
            self.store.set(f"{MEMBER_PREFIX}{generation}/{cfg.node_rank}", "1")
            self.store.add(f"{JOIN_PREFIX}{generation}", 1)
            self._joined_generations.add(generation)
            grace_start = time.monotonic()
        world = self.store.get(f"{WORLD_PREFIX}{generation}")
        if world is None:
            joined = self.store.wait_ge(
                f"{JOIN_PREFIX}{generation}", cfg.nnodes, timeout=2.0
            )
            if int(self.store.get(GEN_KEY) or 0) != generation:
                raise _Retry(grace_start)  # bumped: rejoin at the new gen
            if cfg.node_rank != 0:
                raise _Retry(grace_start)  # await node 0's decision
            present = sorted(
                r
                for r in range(cfg.nnodes)
                if self.store.get(f"{MEMBER_PREFIX}{generation}/{r}")
            )
            if joined is not None or (
                time.monotonic() - grace_start > cfg.scale_down_grace
                and len(present) >= cfg.min_world_nodes
            ):
                if joined is None:
                    print(
                        f"[tpurun] scale-down: only {len(present)}/"
                        f"{cfg.nnodes} node(s) joined gen {generation} "
                        f"within {cfg.scale_down_grace:.0f}s grace; "
                        f"re-forming with nodes {present}",
                        flush=True,
                    )
                self.store.set(
                    f"{WORLD_PREFIX}{generation}",
                    ",".join(str(r) for r in present),
                )
            raise _Retry(grace_start)
        members = [int(r) for r in world.split(",")]
        if cfg.node_rank not in members:
            # The world settled without us (we are a revived latecomer).
            # If that world has ALREADY completed (every member reported
            # done), bumping the generation would split the finishing
            # agents between exit-0 and restart-into-a-dead-store (ADVICE
            # r04) — and there is nothing left to restart anyway.
            done = int(self.store.get(f"{DONE_PREFIX}{generation}") or 0)
            if done >= len(members) or self.store.get(
                f"{FINISHED_PREFIX}{generation}"
            ):
                raise WorldCompleted(finished=True)
            # Otherwise force a fresh generation that includes everyone.
            self.store.add(GEN_KEY, 1)
            raise _Retry(grace_start)
        return generation, members

    def run(self) -> int:
        cfg = self.cfg
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()
        # Two counters, deliberately separate: ``spawns`` feeds the workers'
        # TPURUN_RESTART_COUNT (the spawn GENERATION — chaos plans and any
        # restart-keyed worker logic must see it advance on every respawn,
        # free or not), while ``restarts`` is the --max-restarts BUDGET and
        # only advances on real failures — a preemption drain restarts the
        # world for free.
        spawns = 0
        restarts = 0
        # (monotonic time failure was classified, was it a preemption) —
        # closed when the replacement WorkerGroup spawns, so the downtime
        # counters cover terminate + re-rendezvous, the full gap.
        fail_at: Optional[tuple] = None
        try:
            while True:
                try:
                    generation, members = self._rendezvous()
                except WorldCompleted as wc:
                    if wc.finished:
                        # The settled world (which excludes us) has fully
                        # completed — the job succeeded without this node.
                        # Clean exit, whether or not we ran workers in an
                        # earlier generation.
                        print(
                            "[tpurun] world completed without this "
                            "(excluded) node; exiting",
                            flush=True,
                        )
                        return 0
                    if self._group is None:
                        # Never spawned workers in this process: we are a
                        # revived latecomer and the world finished without
                        # us — a clean no-op exit.
                        print(
                            "[tpurun] rendezvous store gone — the world "
                            "completed without this (revived) node; exiting",
                            flush=True,
                        )
                        return 0
                    # We WERE part of this world (workers ran and the job
                    # is unfinished, or we'd have exited via the done
                    # barrier): losing the store mid-run means node 0 died.
                    # That is a failure, never silent success.
                    print(
                        "[tpurun] rendezvous store lost mid-run (node 0 "
                        "dead?); aborting",
                        file=sys.stderr,
                    )
                    return 1
                if cfg.node_rank == 0:
                    print(
                        f"[tpurun] generation {generation}: {len(members)} "
                        f"node(s) x {cfg.nproc_per_node} proc(s), "
                        f"world={len(members) * cfg.nproc_per_node}",
                        flush=True,
                    )
                group = self._group = WorkerGroup(
                    cfg, self.cmd, spawns, members=members
                )
                if fail_at is not None:
                    downtime = time.monotonic() - fail_at[0]
                    self._c_restart_downtime.inc(downtime)
                    if fail_at[1]:
                        self._c_preempt_downtime.inc(downtime)
                    fail_at = None
                failure = self._monitor(group, generation, members)
                if failure is None:
                    # Local workers all succeeded; wait for every live agent.
                    done_count = self.store.add(f"{DONE_PREFIX}{generation}", 1)
                    if done_count is not None and int(done_count) >= len(members):
                        # We are the DECIDER (our add completed the count):
                        # publish the terminal marker so agents that later
                        # observe a stray generation bump (revived-latecomer
                        # race, ADVICE r04) still agree the world finished.
                        self.store.set(f"{FINISHED_PREFIX}{generation}", "1")
                    result = self._await_world_done(generation, len(members))
                    if result == "done":
                        # Exit barrier: the store lives on node 0, so node 0
                        # must not tear it down until every agent has seen
                        # "done" (else their final waits die mid-request).
                        try:
                            self.store.add(f"{ACK_PREFIX}{generation}", 1)
                            if self.cfg.node_rank == 0:
                                self.store.wait_ge(
                                    f"{ACK_PREFIX}{generation}",
                                    len(members),
                                    timeout=60.0,
                                )
                        except (ConnectionError, OSError):
                            pass  # store already gone -> world is done anyway
                        return 0
                    # else: someone failed after we finished -> fall through to restart
                    failure = "restart requested elsewhere"
                # Classify BEFORE terminate: a drain-marked generation ended
                # by preemption, not failure, however this agent noticed it
                # (drain exit code, generation bump, or its own SIGTERM).
                preempt = failure.startswith("preempt")
                if not preempt:
                    try:
                        preempt = bool(
                            self.store.get(f"{DRAIN_PREFIX}{generation}")
                        )
                    except (ConnectionError, OSError):
                        pass
                group.terminate()
                if self.store.get(FATAL_KEY):
                    print("[tpurun] aborting: world marked fatal", file=sys.stderr)
                    return 1
                if self._drain_requested.is_set():
                    # THIS node is the one being reclaimed: workers drained
                    # (or were reaped at the grace deadline) — exit instead
                    # of respawning, so the survivors can re-form without us
                    # (the MIN:MAX scale-down path).
                    print(
                        "[tpurun] drain complete; exiting (node preempted)",
                        flush=True,
                    )
                    return 143  # 128 + SIGTERM: conventional reclaim exit
                spawns += 1
                self._c_spawns.inc()
                fail_at = (time.monotonic(), preempt)
                if preempt:
                    self._c_preempt_restarts.inc()
                    print(
                        f"[tpurun] preempt detected (gen {generation}): "
                        f"{failure}; restart budget intact "
                        f"({restarts}/{cfg.max_restarts} used)",
                        flush=True,
                    )
                    continue
                restarts += 1
                self._c_restarts.inc()
                if restarts > cfg.max_restarts:
                    self.store.set(FATAL_KEY, f"node{cfg.node_rank}-restarts-exhausted")
                    print(
                        f"[tpurun] giving up after {cfg.max_restarts} restarts",
                        file=sys.stderr,
                    )
                    return 1
                print(
                    f"[tpurun] failure detected (gen {generation}): "
                    f"{failure}; restart {restarts}/{cfg.max_restarts}",
                    flush=True,
                )
        finally:
            self._stop_hb.set()
            self.close()

    def _monitor(
        self,
        group: WorkerGroup,
        generation: int,
        members: Optional[List[int]] = None,
    ) -> Optional[str]:
        """Poll local workers + the store until success (None) or failure (str).

        On local failure, bumps the generation so every other agent restarts
        too (torchrun's restart-the-world semantics).

        Preemption drain: a SIGTERM on THIS agent (``_drain_requested``) or a
        ``drain/<gen>`` mark from a preempted peer starts a drain — the soft
        notice is forwarded to the local workers (``request_drain``), which
        finish the in-flight step, snapshot, and exit with the distinguished
        drain code within ``--drain-grace``. A drain exit returns a
        ``"preempt: ..."`` failure string so ``run()`` restarts the world
        WITHOUT spending budget; the drain mark is published before the
        generation bump so peers classify identically.
        """
        cfg = self.cfg
        last_peer_check = 0.0
        n_peers = len(members) if members is not None else cfg.nnodes
        self._seed_peer_clocks(members)
        drain_key = f"{DRAIN_PREFIX}{generation}"
        drain_signaled = False
        drain_deadline: Optional[float] = None
        while True:
            code = group.poll()
            if code is not None:
                if code == cfg.preempt_exit_code:
                    # Drain exit: publish the mark BEFORE the bump so every
                    # peer sees "preemption", then restart-the-world.
                    self.store.set(drain_key, f"node{cfg.node_rank}")
                    self.store.add(GEN_KEY, 1)
                    return f"preempt: local worker drained (exit {code})"
                self.store.add(GEN_KEY, 1)
                return f"local worker exited with {code}"
            if group.all_done():
                return None
            if self._drain_requested.is_set() and not drain_signaled:
                # This node is being reclaimed: publish, then soft-signal.
                drain_signaled = True
                drain_deadline = time.monotonic() + cfg.drain_grace
                print(
                    f"[tpurun] drain: SIGTERM received; workers have "
                    f"{cfg.drain_grace:.0f}s to snapshot and exit",
                    flush=True,
                )
                self.store.set(drain_key, f"node{cfg.node_rank}")
                group.request_drain()
            if not drain_signaled and self.store.get(drain_key):
                # A peer is being reclaimed: forward the soft notice so our
                # ranks join the drain at the same step (the Trainer's
                # per-batch allgather agreement) instead of later issuing a
                # collective against the vanished peer.
                drain_signaled = True
                drain_deadline = time.monotonic() + cfg.drain_grace
                print(
                    f"[tpurun] drain: peer preemption published for gen "
                    f"{generation}; draining local workers",
                    flush=True,
                )
                group.request_drain()
            if drain_deadline is not None and time.monotonic() > drain_deadline:
                # Wedged mid-drain (e.g. stuck in a snapshot barrier against
                # a peer already gone): reap and still classify as preempt —
                # the node WAS preempted; the resume falls back to the last
                # durable snapshot.
                self.store.add(GEN_KEY, 1)
                return "preempt: drain grace expired (workers killed)"
            current_gen = int(self.store.get(GEN_KEY) or 0)
            if current_gen != generation:
                if self.store.get(drain_key):
                    # The preempted node finished draining and bumped; our
                    # workers are mid-drain — keep monitoring (bounded by
                    # drain_deadline) so their just-in-time snapshot lands
                    # instead of being torn apart by an instant teardown.
                    if not drain_signaled:
                        drain_signaled = True
                        drain_deadline = time.monotonic() + cfg.drain_grace
                        group.request_drain()
                else:
                    return "remote failure (generation bumped)"
            if self.store.get(FATAL_KEY):
                return "fatal"
            now = time.monotonic()
            if n_peers > 1 and now - last_peer_check > cfg.heartbeat_interval:
                last_peer_check = now
                dead = self._peer_dead(members)
                if dead is not None:
                    self.store.add(GEN_KEY, 1)
                    return f"node {dead} heartbeat lost"
            if cfg.worker_heartbeat_timeout > 0:
                hung = group.hung_worker(cfg.worker_heartbeat_timeout)
                if hung is not None:
                    self.store.add(GEN_KEY, 1)
                    return f"local worker {hung} hung (heartbeat file stale)"
            time.sleep(0.2)

    def _await_world_done(self, generation: int, n_members: int) -> str:
        """After local success: block until all live agents report done
        ('done') or a failure elsewhere bumps the generation ('restart').

        A generation bump is NOT immediately terminal: members add DONE
        unconditionally once their workers succeed (their monitor checks
        completion before the bump flag), so a bump can race the last DONE
        adds — e.g. a revived latecomer bumping while the world finishes
        (ADVICE r04). On seeing a bump, keep waiting only while the counter
        is still ADVANCING (completion in flight); once it stalls for
        ``DONE_BUMP_GRACE`` the missing member has truly failed — restart.
        FATAL is honored immediately (the counter cannot save a world whose
        restart budget is spent)."""
        last_done = -1
        stall_deadline = None
        while True:
            try:
                done = self.store.wait_ge(
                    f"{DONE_PREFIX}{generation}", n_members, timeout=1.0
                )
                if done is not None or self.store.get(
                    f"{FINISHED_PREFIX}{generation}"
                ):
                    return "done"
                if self.store.get(FATAL_KEY):
                    return "restart"
                if int(self.store.get(GEN_KEY) or 0) != generation:
                    done_now = int(
                        self.store.get(f"{DONE_PREFIX}{generation}") or 0
                    )
                    now = time.monotonic()
                    if done_now != last_done:
                        last_done = done_now
                        stall_deadline = now + DONE_BUMP_GRACE
                    elif now > stall_deadline:
                        return "restart"
                else:
                    last_done = -1
                    stall_deadline = None
            except (ConnectionError, OSError):
                # The store dies only when node 0's agent exits — and after our
                # own workers succeeded that means the world completed.
                return "done"

    def close(self) -> None:
        self._stop_hb.set()
        if self._group is not None:
            self._group.terminate()
            self._group = None
        try:
            if self.server is not None:
                self.store.shutdown_server()
        finally:
            self.store.close()
            if self.server is not None:
                self.server.close()
            if self._chaos_proxy is not None:
                self._chaos_proxy.stop()
                self._chaos_proxy = None


def _parse_endpoint(endpoint: str) -> tuple:
    host, _, port = endpoint.rpartition(":")
    return host or "127.0.0.1", int(port)


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpurun",
        description="Elastic launcher for distributed_pytorch_tpu (torchrun twin)",
    )
    p.add_argument("--nproc-per-node", type=int, default=1)
    p.add_argument(
        "--nnodes",
        default="1",
        help="node count N, or MIN:MAX (torchrun elastic form): start with "
        "up to MAX nodes and allow the world to re-form with as few as MIN "
        "when nodes are lost for good (--scale-down-grace)",
    )
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument(
        "--scale-down-grace",
        type=float,
        default=30.0,
        help="with --nnodes MIN:MAX, how long each rendezvous waits for the "
        "full MAX world before settling for the >= MIN nodes that joined",
    )
    p.add_argument(
        "--rdzv-endpoint",
        default="127.0.0.1:29400",
        help="host:port of the rendezvous store (runs on node 0)",
    )
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument(
        "--jax-coordinator-port",
        type=int,
        default=None,
        help="port for jax.distributed's coordination service, which worker 0 "
        "binds on the rendezvous host (default: rendezvous port + 1)",
    )
    p.add_argument(
        "--heartbeat-interval",
        type=float,
        default=2.0,
        help="seconds between agent heartbeats into the store",
    )
    p.add_argument(
        "--heartbeat-timeout",
        type=float,
        default=30.0,
        help="declare a peer node dead after this many seconds without a "
        "fresh heartbeat (restart-the-world follows)",
    )
    p.add_argument(
        "--worker-heartbeat-timeout",
        type=float,
        default=0.0,
        help="> 0: declare a LOCAL worker hung (and restart the world) when "
        "it has not touched its TPURUN_HEARTBEAT_FILE for this many seconds "
        "(the Trainer touches it every batch); the clock starts at spawn, so "
        "allow for startup + first compile",
    )
    p.add_argument(
        "--store-retry-deadline",
        type=float,
        default=30.0,
        help="seconds the store client transparently retries a transport "
        "failure (reconnect + backoff) before the agent concludes the "
        "rendezvous host is dead; 0 disables retry (fail fast)",
    )
    p.add_argument(
        "--drain-grace",
        type=float,
        default=30.0,
        help="seconds workers get to finish the in-flight step and take a "
        "just-in-time snapshot after a preemption SIGTERM, before the group "
        "is killed (size to the platform's reclaim grace minus a margin)",
    )
    p.add_argument(
        "--preempt-exit-code",
        type=int,
        default=121,
        help="the distinguished exit code of a gracefully drained worker "
        "(exported as TPURUN_DRAIN_EXIT_CODE); the agent classifies it as a "
        "preemption and restarts the world WITHOUT spending --max-restarts "
        "budget",
    )
    p.add_argument(
        "--standalone",
        action="store_true",
        help="single-node shorthand: nnodes=1, store on an ephemeral local port",
    )
    p.add_argument("script", help="training script to launch")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _free_ports(n: int) -> List[int]:
    """``n`` distinct free ports, all held open while picking so two calls
    cannot hand back the same just-released port."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _parse_nnodes(spec: str) -> tuple:
    """``"4"`` -> (4, 4); ``"1:4"`` -> (1, 4) (torchrun MIN:MAX)."""
    s = str(spec)
    if ":" in s:
        lo, hi = s.split(":", 1)
        lo, hi = int(lo), int(hi)
    else:
        lo = hi = int(s)
    if not (1 <= lo <= hi):
        raise ValueError(f"invalid --nnodes {spec!r}: need 1 <= MIN <= MAX")
    return lo, hi


def main(argv: Optional[List[str]] = None) -> int:
    args = make_parser().parse_args(argv)
    min_nnodes, max_nnodes = _parse_nnodes(args.nnodes)
    args.nnodes = max_nnodes
    if args.standalone:
        args.nnodes, args.node_rank, min_nnodes = 1, 0, 1
        # The ephemeral store port's neighbor may be in use; pick two distinct
        # free ports rather than gambling on rdzv_port + 1.
        rdzv_port, coord_port = _free_ports(2)
        args.rdzv_endpoint = f"127.0.0.1:{rdzv_port}"
        if args.jax_coordinator_port is None:
            args.jax_coordinator_port = coord_port
    host, port = _parse_endpoint(args.rdzv_endpoint)
    cfg = ElasticConfig(
        nproc_per_node=args.nproc_per_node,
        nnodes=args.nnodes,
        min_nnodes=min_nnodes,
        scale_down_grace=args.scale_down_grace,
        node_rank=args.node_rank,
        rdzv_host=host,
        rdzv_port=port,
        jax_coordinator_port=args.jax_coordinator_port,
        max_restarts=args.max_restarts,
        heartbeat_interval=args.heartbeat_interval,
        heartbeat_timeout=args.heartbeat_timeout,
        worker_heartbeat_timeout=args.worker_heartbeat_timeout,
        store_retry_deadline=args.store_retry_deadline,
        drain_grace=args.drain_grace,
        preempt_exit_code=args.preempt_exit_code,
    )
    agent = ElasticAgent(cfg, [sys.executable, args.script] + args.script_args)

    def _forward_signal(signum, frame):
        agent.close()
        sys.exit(128 + signum)

    def _graceful_drain(signum, frame):
        # First SIGTERM: begin the preemption drain (publish + soft-signal
        # happen on the monitor thread — a signal handler must not touch the
        # store socket the main thread may be mid-request on). A SECOND
        # SIGTERM escalates to the immediate teardown, exactly the pre-drain
        # behavior (and what a reclaim's follow-up SIGKILL would force anyway).
        if not agent.request_drain():
            _forward_signal(signum, frame)

    signal.signal(signal.SIGTERM, _graceful_drain)
    signal.signal(signal.SIGINT, _forward_signal)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
