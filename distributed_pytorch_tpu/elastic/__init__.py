"""Elastic launch: the ``tpurun`` agent and its native rendezvous store.

TPU-native replacement for the torchrun + c10d layer the reference leans on
(SURVEY.md §3.3; ``slurm/sbatch_run.sh:17-23``).
"""

from distributed_pytorch_tpu.elastic.agent import (
    ElasticAgent,
    ElasticConfig,
    main as tpurun_main,
)
from distributed_pytorch_tpu.elastic.store import KVStoreClient, KVStoreServer

__all__ = [
    "ElasticAgent",
    "ElasticConfig",
    "KVStoreClient",
    "KVStoreServer",
    "tpurun_main",
]
