"""Replica-consistency checking — the framework's race detector.

The reference has no sanitizer layer (SURVEY.md §5) and in fact contains the
class of bug this module detects: divergent "replicated" state across workers
(its multi-writer snapshot race at ``multinode_torchrun.py:68`` can leave
ranks resuming from different checkpoints, after which DDP's replicas silently
disagree forever). In JAX, replica divergence is structural rather than
memory-level — it enters through non-deterministic host code, per-process RNG
misuse, or inconsistent resume — and once present it invalidates every
"replicated" annotation the compiler relies on.

:func:`assert_replicas_consistent` checks both layers:

* **across devices** (one process): every addressable shard of a
  replicated-sharding array must be byte-identical;
* **across hosts** (multi-process): float-sum checksums of every leaf are
  all-gathered and compared, so process 0's state equals every other
  process's without shipping the tensors.

Cost is one host transfer of each checked leaf — call it at checkpoint
cadence (the Trainer does so before snapshots when ``paranoid=True``), not
per step.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


class ReplicaDivergenceError(AssertionError):
    """Raised when replicas of supposedly replicated state disagree."""


def _leaf_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        yield jax.tree_util.keystr(path), leaf


def check_device_replicas(tree: Any) -> None:
    """Assert every replicated ``jax.Array`` leaf has byte-identical shards
    on all addressable devices (single-host layer)."""
    for path, leaf in _leaf_paths(tree):
        if not isinstance(leaf, jax.Array) or not hasattr(leaf, "sharding"):
            continue
        if not leaf.sharding.is_fully_replicated or len(leaf.addressable_shards) < 2:
            continue
        reference = np.asarray(leaf.addressable_shards[0].data)
        for shard in leaf.addressable_shards[1:]:
            if not np.array_equal(
                reference, np.asarray(shard.data), equal_nan=True
            ):
                raise ReplicaDivergenceError(
                    f"leaf {path} marked replicated but devices "
                    f"{leaf.addressable_shards[0].device} and {shard.device} "
                    "hold different values"
                )


def _checksummable_leaves(tree):
    """Leaves whose values are a replica-consistency subject: everything
    except jax.Arrays that are deliberately sharded (ZeRO-1/FSDP/TP states
    via ``Trainer(partition_specs=)``) — a sharded leaf's per-process local
    data legitimately differs, and gathering a non-addressable array for a
    checksum would crash multi-host. Sharded placement correctness is the
    compiler's contract, not a replica property."""
    for path, leaf in _leaf_paths(tree):
        if (
            isinstance(leaf, jax.Array)
            and getattr(leaf, "sharding", None) is not None
            and not leaf.sharding.is_fully_replicated
        ):
            continue
        yield path, leaf


def _to_host(leaf) -> np.ndarray:
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        # Fully replicated (guaranteed by _checksummable_leaves): any local
        # shard holds the complete value.
        return np.asarray(leaf.addressable_shards[0].data)
    return np.asarray(jax.device_get(leaf))


def tree_checksum(tree: Any) -> np.ndarray:
    """Order-stable float64 checksum vector over the tree's replicated leaves
    (one entry per leaf: sum of values; NaN-safe via nansum + NaN count).
    Deliberately sharded leaves are excluded — see
    :func:`_checksummable_leaves`."""
    sums = []
    for _, leaf in _checksummable_leaves(tree):
        arr = _to_host(leaf).astype(np.float64, copy=False)
        sums.append(np.nansum(arr) + 1e12 * np.count_nonzero(np.isnan(arr)))
    return np.asarray(sums, np.float64)


def check_host_replicas(tree: Any, *, name: str = "state") -> None:
    """Assert all processes hold identical checksums for ``tree``
    (multi-host layer). No-op in single-process runs."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    local = tree_checksum(tree)
    gathered = np.asarray(
        multihost_utils.process_allgather(local)
    )  # [n_processes, n_leaves]
    if not np.allclose(gathered, gathered[0], rtol=0, atol=0, equal_nan=True):
        bad = np.where(~np.all(gathered == gathered[0], axis=0))[0]
        paths = [p for p, _ in _checksummable_leaves(tree)]
        raise ReplicaDivergenceError(
            f"{name} diverges across processes at leaves "
            f"{[paths[i] for i in bad[:5]]} (checksum matrix row 0 != others)"
        )


def assert_replicas_consistent(tree: Any, *, name: str = "state") -> None:
    """Full consistency check: device layer then host layer."""
    check_device_replicas(tree)
    check_host_replicas(tree, name=name)
