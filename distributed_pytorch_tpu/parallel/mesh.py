"""Device-mesh construction.

The mesh is the TPU-native replacement for the reference's implicit
"world of ranks" (``world_size`` at ``multigpu.py:95``, torchrun's
``WORLD_SIZE``): instead of N independent processes coordinating through NCCL,
we lay all addressable chips out on a named logical mesh and let XLA place
collectives onto ICI/DCN from the mesh topology.

Axis convention (reserved up front so later parallelism is additive — see
SURVEY.md §2b):

* ``data``     — data parallelism (the only axis the reference exercises, via DDP)
* ``fsdp``     — sharded-parameter data parallelism (ZeRO analog)
* ``tensor``   — tensor/model parallelism
* ``sequence`` — sequence/context parallelism (ring attention)
* ``expert``   — expert parallelism (MoE)
"""

from __future__ import annotations

import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

MESH_AXES = ("data", "fsdp", "tensor", "sequence", "expert")


def make_mesh(
    axes: Optional[Mapping[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named device mesh.

    ``axes`` maps axis name -> size; at most one size may be ``-1`` (absorbs all
    remaining devices). Default is a 1-D ``data`` mesh over every device — the
    moral equivalent of ``init_process_group`` + DDP over
    ``torch.cuda.device_count()`` chips (reference ``multigpu.py:95-96``).

    Examples::

        make_mesh()                              # {"data": all}
        make_mesh({"data": -1, "tensor": 4})     # 2-D DP x TP
        make_mesh({"data": 2, "sequence": 4})    # DP x ring-attention SP
    """
    explicit_devices = devices is not None
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"data": n}

    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may have size -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n % known != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes product {known}")
        sizes[sizes.index(-1)] = n // known
    needed = math.prod(sizes)
    if needed > n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} needs {needed} devices, have {n}")
    if needed < n:
        # Using fewer devices than exist is almost always a typo when the
        # device list was implicit (and would strand whole processes in
        # multi-host runs, where the prefix may exclude a host's chips);
        # require the caller to pass `devices=` to opt in to a submesh.
        if not explicit_devices:
            raise ValueError(
                f"mesh shape {dict(zip(names, sizes))} uses {needed} of {n} "
                "devices; pass devices= explicitly to build a submesh"
            )
        devices = list(devices)[:needed]

    if len(sizes) == 1:
        # Keep explicit device order for 1-D meshes (predictable shard placement).
        device_array = np.asarray(devices)
    else:
        device_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    return Mesh(device_array, tuple(names))


def data_axis_size(mesh: Mesh) -> int:
    """Number of data-parallel replicas in the mesh (1 if no ``data`` axis)."""
    return mesh.shape.get("data", 1)
