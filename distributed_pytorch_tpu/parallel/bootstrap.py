"""Process bootstrap and rendezvous.

TPU-native replacement for both forms of the reference's ``ddp_setup``:

* explicit-rank form — ``MASTER_ADDR``/``MASTER_PORT`` env +
  ``init_process_group("nccl", rank, world_size)`` (reference ``multigpu.py:12-20``);
* env-driven (torchrun) form — bare ``init_process_group("nccl")`` with topology
  from ``RANK``/``WORLD_SIZE``/``MASTER_*`` env vars (reference
  ``multigpu_torchrun.py:12-13``, ``multinode_torchrun.py:12-13``).

Here both collapse onto ``jax.distributed.initialize`` against a coordinator
(process 0's address — the moral equivalent of ``head_node_ip:29500`` in
``slurm/sbatch_run.sh:12,22``). Env vars understood, mirroring the torchrun
contract one-to-one:

=================  =======================  =================================
torchrun env       ours                     meaning
=================  =======================  =================================
MASTER_ADDR:PORT   COORDINATOR_ADDRESS      host:port of process 0
WORLD_SIZE         NUM_PROCESSES            number of host processes
RANK               PROCESS_ID               this process's global id
LOCAL_RANK         (none needed)            JAX owns local device binding
=================  =======================  =================================

On a real TPU pod slice none of these are required: ``jax.distributed
.initialize()`` autodetects topology from the TPU metadata server, so
``setup_distributed()`` with no env set simply does the right thing.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def setup_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-process JAX. Safe to call in single-process runs (no-op).

    Explicit args take priority; otherwise ``COORDINATOR_ADDRESS`` /
    ``NUM_PROCESSES`` / ``PROCESS_ID`` env vars are used (torchrun-style);
    otherwise, if neither is present, this is a single-process run and we skip
    initialization entirely (the serial rung needs no rendezvous, like
    ``single_gpu.py``).
    """
    global _initialized
    if _initialized:
        return

    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        if not _on_tpu_pod():
            return  # serial / single-process: nothing to rendezvous
        # Real TPU pod slice: initialize() autodetects topology from the TPU
        # metadata server (the torchrun-env machinery has no analog here).
        jax.distributed.initialize()
    else:
        # Multi-process on the forced-CPU test rig: XLA's CPU client refuses
        # cross-process computations unless a collectives transport is
        # selected (gloo ships in jaxlib). Must be set before the backend
        # initializes; never touched on real TPU.
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass  # older jax: CPU multiprocess either works or is absent
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True


def _on_tpu_pod() -> bool:
    """Heuristic for 'running as one worker of a multi-HOST TPU slice': the
    Cloud TPU runtime exports worker topology env vars on every pod VM.

    A single-host slice also exports ``TPU_WORKER_HOSTNAMES`` (one entry), and
    there ``jax.distributed.initialize()``'s autodetection is pointless — and
    breaks off-cloud single-host rigs with no metadata server — so when the
    hostname list is present it must name more than one worker. Runtimes that
    export only a task/worker id (no hostname list) are trusted to be pods.
    """
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES")
    if hostnames is not None:
        return len([h for h in hostnames.split(",") if h.strip()]) > 1
    return any(k in os.environ for k in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"))


def shutdown_distributed() -> None:
    """Tear down the coordination service (twin of ``destroy_process_group()``,
    reference ``multigpu.py:88``)."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def is_main_process() -> bool:
    """True on process 0 — the single checkpoint writer.

    Replaces the reference's rank-0 gates (``multigpu.py:61``) and fixes the
    multi-writer race at ``multinode_torchrun.py:68`` (which gated on
    *local* rank 0, so every node wrote the shared snapshot file).
    """
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (used after checkpoint writes so no process races
    ahead and reads a half-written snapshot)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
