"""Pipeline parallelism: a GPipe-style microbatched pipeline over a ``stage``
mesh axis, built from ``shard_map`` + ``lax.ppermute``.

No reference analog (SURVEY.md §2b: PP absent) — a beyond-parity capability,
built the TPU way rather than as a torch-style stage-process runtime:

* Every device runs the SAME program (SPMD). Stage identity is
  ``lax.axis_index("stage")``; stacked per-stage parameters ``[S, ...]`` are
  sharded ``P("stage")`` so each device physically holds only its stage's
  weights.
* The schedule is the classic collective-permute pipeline: at step ``t``,
  stage ``s`` processes microbatch ``t - s`` (masked out during fill/drain
  bubbles), then pushes its activation one hop along the ring with
  ``ppermute`` — nearest-neighbor ICI traffic, no host involvement.
* The loop is a ``lax.scan`` (reverse-differentiable, single XLA trace);
  gradients flow back through the permutes (the transpose of ``ppermute`` is
  the reverse permute) and arrive stage-sharded, exactly where the optimizer
  needs them.
* Composes with data parallelism: batch dim sharded over ``data``, each
  data-shard pipelines independently over ``stage``. (Stages run under
  shard_map, so compiler-driven TP inside a stage does not apply — TP inside
  PP stages would require manual collectives in the stage body.)

Bubble accounting: with ``M`` microbatches and ``S`` stages the pipeline runs
``M + S - 1`` steps, efficiency ``M / (M + S - 1)`` — pick ``M >= 4*S`` for
>80% utilization.

Two schedules live here:

* :func:`pipeline_apply` — GPipe: forward-only primitive, differentiated by
  XLA's autodiff. Activation residuals for ALL ``M`` microbatches are alive
  when the (autodiff-scheduled) backward begins: memory ``O(M)``.
* :func:`pipeline_1f1b_grads` — 1F1B (PipeDream-flush): a gradient-PRODUCING
  primitive. The loss head runs INSIDE the last stage's schedule slot, so
  microbatch ``i``'s backward starts while later microbatches are still
  streaming forward; each stage keeps at most ``2(S-1-s)+1`` residual
  inputs alive (the classic 1F1B bound). Backward recomputes the stage
  forward from the stored INPUT (recompute-vjp, the standard large-model
  configuration: full remat costs one extra stage-forward per microbatch
  and makes the residual a single activation tensor instead of the whole
  autodiff tape). What shrinks from ``O(M)`` to ``O(S)`` is therefore the
  TAPE memory — ``M x (layers-per-stage x per-layer tape)`` becomes
  ``O(S) boundary activations + ONE stage's tape`` — which is the term
  that dominates for real multi-layer stages. The batch-boundary arrays
  (``x``, ``targets``, and the optional ``dx`` output) remain ``O(M)`` by
  nature: they ARE the caller's batch. The bubble fraction
  ``2(S-1) / (M + 2(S-1))`` matches GPipe-with-remat; the win is memory —
  which is what decides whether a deep model FITS.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_pytorch_tpu.parallel.partitioning import Rules

#: Partition rule for stacked pipeline-stage params created by
#: :class:`PipelinedBlocks` (leading dim = stage). ``P("stage")`` shards dim 0
#: of any rank. Compose with other rules; first match wins.
PIPELINE_STAGE_RULES: Rules = ((r"(.*/)?stages/.*", P("stage")),)


def _pipeline_shard_body(
    stage_fn: Callable,
    stage_params: Any,
    x: jnp.ndarray,
    *,
    axis: str,
    num_microbatches: int,
):
    """Per-device body (under shard_map). ``stage_params``: this device's
    stage slice with leading dim 1; ``x``: local batch ``[B_local, ...]``."""
    n_stages = jax.lax.psum(1, axis)
    stage_idx = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    m = num_microbatches
    microbatches = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    mb_shape = microbatches.shape[1:]

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    n_steps = m + n_stages - 1

    def body(carry, t):
        recv, out = carry
        # Stage 0 injects microbatch t (clamped; masked during drain).
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(stage_idx == 0, inject, recv)
        y = stage_fn(params, x_in)
        # Last stage banks microbatch t-(S-1) (clamped; masked during fill).
        slot = jnp.clip(t - (n_stages - 1), 0, m - 1)
        banked = jax.lax.dynamic_update_index_in_dim(out, y, slot, axis=0)
        take = (stage_idx == n_stages - 1) & (t >= n_stages - 1)
        out = jnp.where(take, banked, out)
        recv = jax.lax.ppermute(y, axis, perm)
        return (recv, out), None

    recv0 = jnp.zeros(mb_shape, x.dtype)
    out0 = jnp.zeros((m,) + mb_shape, x.dtype)
    (_, out), _ = jax.lax.scan(body, (recv0, out0), jnp.arange(n_steps))
    # Replicate the last stage's outputs to every stage (masked all-reduce) so
    # the result leaves the shard_map with ordinary replicated-over-stage
    # semantics.
    out = jax.lax.psum(
        jnp.where(stage_idx == n_stages - 1, out, jnp.zeros_like(out)), axis
    )
    return out.reshape(x.shape[:1] + out.shape[2:])


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "stage",
    num_microbatches: int = 8,
    data_axis: Optional[str] = "data",
) -> jnp.ndarray:
    """Run ``x`` through ``n_stages`` chained applications of ``stage_fn``,
    pipelined over the mesh's ``axis``.

    ``stage_fn(params_for_one_stage, x) -> y`` must be shape-preserving
    (classic homogeneous-block pipelining; put embed/head outside the
    pipeline). ``stacked_params`` leaves have leading dim ``n_stages`` and
    should be sharded ``P(axis)`` (:data:`PIPELINE_STAGE_RULES`).
    ``x``: global ``[B, ...]``; the *per-data-shard* batch must divide
    ``num_microbatches``.
    """
    n_stages = mesh.shape[axis]
    leading = {
        leaf.shape[0] for leaf in jax.tree_util.tree_leaves(stacked_params)
    }
    if len(leading) != 1:
        raise ValueError(
            f"stacked_params leaves disagree on the leading (stage) dim: {leading}"
        )
    (n_stacked,) = leading
    if n_stages == 1:
        out = x
        for s in range(n_stacked):
            params_s = jax.tree_util.tree_map(lambda p, s=s: p[s], stacked_params)
            out = stage_fn(params_s, out)
        return out
    if n_stacked != n_stages:
        # Without this check, shard_map would hand each device an
        # n_stacked/n_stages-sized slice and the body's p[0] would silently
        # drop every other stage.
        raise ValueError(
            f"stacked_params hold {n_stacked} stages but mesh axis {axis!r} "
            f"has size {n_stages}; they must match"
        )

    d_ax = data_axis if (data_axis and data_axis in mesh.shape) else None
    local_batch = x.shape[0] // (mesh.shape[d_ax] if d_ax else 1)
    if local_batch % num_microbatches != 0:
        raise ValueError(
            f"per-data-shard batch {local_batch} not divisible by "
            f"num_microbatches {num_microbatches}"
        )

    x_spec = P(*((d_ax,) + (None,) * (x.ndim - 1)))
    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)

    body = functools.partial(
        _pipeline_shard_body,
        stage_fn,
        axis=axis,
        num_microbatches=num_microbatches,
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stacked_params, x)


def _1f1b_shard_body(
    stage_fn: Callable,
    last_fn: Callable,
    stage_params: Any,
    last_params: Any,
    x: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    axis: str,
    data_axis: Optional[str],
    num_microbatches: int,
    with_dx: bool = True,
):
    """Per-device 1F1B body (under shard_map).

    Closed-form schedule with unit F/B slots per cycle ``t``:

    * forward of microbatch ``i`` at stage ``s`` happens at cycle
      ``F(s, i) = s + i`` (same streaming as GPipe);
    * backward at ``B(s, i) = 2(S-1) - s + i`` — so the LAST stage runs
      ``B(i)`` in the same cycle as ``F(i)`` (loss head fused into its
      slot), and stage ``s`` receives the activation-gradient its
      successor produced one cycle earlier (``B(s+1, i) = B(s, i) - 1``).

    Residual inputs live from ``F(s, i)`` to ``B(s, i)`` — at most
    ``2(S-1-s) + 1`` in flight — stored in a ``min(M, 2S-1)``-slot ring
    (slot ``i mod K``; the lifetime bound proves no overwrite-before-use).
    Total cycles: ``M + 2(S-1)``.

    Stage roles are ``lax.cond`` branches on the traced stage index: no
    collectives inside the branches, so SPMD stays uniform — the two
    ``ppermute``\\ s (activations forward, gradients backward) happen
    unconditionally every cycle.
    """
    n_stages = jax.lax.psum(1, axis)
    stage_idx = jax.lax.axis_index(axis)
    is_last = stage_idx == n_stages - 1
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    m = num_microbatches
    microbatches = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    tgt_mb = targets.reshape((m, targets.shape[0] // m) + targets.shape[1:])
    mb_shape = microbatches.shape[1:]
    k_slots = min(m, 2 * n_stages - 1)

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    n_cycles = m + 2 * (n_stages - 1)

    zero_params = jax.tree_util.tree_map(jnp.zeros_like, params)
    zero_last = jax.tree_util.tree_map(jnp.zeros_like, last_params)

    # Dtype discipline for the lax.cond branches: a stage_fn/last_fn that
    # PROMOTES (bf16 activations over f32 params -> f32 output, or a
    # reduced-precision loss) must not desynchronize the branch
    # signatures. Every activation-side value — streamed y, residuals,
    # activation-gradients, their zero stubs — is cast to the promoted
    # ``act_dtype`` (an exact upcast where it applies), and the loss is
    # accumulated in f32 regardless of last_fn's output dtype.
    y_aval = jax.eval_shape(
        lambda p, xx: stage_fn(p, xx),
        params,
        jax.ShapeDtypeStruct(mb_shape, x.dtype),
    )
    act_dtype = jnp.result_type(x.dtype, y_aval.dtype)

    def composite(p, lp, xx, tt):
        y = stage_fn(p, xx).astype(act_dtype)
        return last_fn(lp, y, tt).astype(jnp.float32), y

    def f_last(xx, tt):
        # Last stage: forward + loss head + FULL backward in one slot (its
        # B(i) cycle IS its F(i) cycle) — no residual needed.
        (loss_mb, y), grads = jax.value_and_grad(
            composite, argnums=(0, 1, 2), has_aux=True
        )(params, last_params, xx, tt)
        dp, dlp, dxc = grads
        return y, loss_mb, dp, dlp, dxc

    def f_plain(xx, tt):
        return (
            stage_fn(params, xx).astype(act_dtype),
            jnp.zeros((), jnp.float32),
            zero_params,
            zero_last,
            jnp.zeros(mb_shape, act_dtype),
        )

    def f_skip(xx, tt):
        return (
            jnp.zeros(mb_shape, act_dtype),
            jnp.zeros((), jnp.float32),
            zero_params,
            zero_last,
            jnp.zeros(mb_shape, act_dtype),
        )

    def b_recompute(xx, g):
        # Non-last backward: recompute the stage forward from the stored
        # input, pull the received activation-gradient through it.
        _, vjp_fn = jax.vjp(lambda p, v: stage_fn(p, v).astype(act_dtype),
                            params, xx)
        dp, dx = vjp_fn(g)
        return dp, dx

    def b_skip(xx, g):
        return zero_params, jnp.zeros(mb_shape, act_dtype)

    def body(carry, t):
        (recv_f, recv_b, resid, gp, glp, dx_bank, loss_acc) = carry
        i_f = t - stage_idx
        i_b = t - (2 * (n_stages - 1) - stage_idx)
        f_valid = (i_f >= 0) & (i_f < m)
        b_valid = (i_b >= 0) & (i_b < m)

        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(i_f, 0, m - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(stage_idx == 0, inject, recv_f)
        tgt = jax.lax.dynamic_index_in_dim(
            tgt_mb, jnp.clip(i_f, 0, m - 1), axis=0, keepdims=False
        )

        # ---- forward slot (last stage: fused forward+loss+backward)
        y, loss_mb, dp_f, dlp, dx_last = jax.lax.cond(
            f_valid,
            lambda xx, tt: jax.lax.cond(is_last, f_last, f_plain, xx, tt),
            f_skip,
            x_in,
            tgt,
        )

        # ---- residual ring: store this cycle's input for the later backward
        slot_f = jnp.clip(i_f, 0, m - 1) % k_slots
        resid = jnp.where(
            f_valid & ~is_last,
            jax.lax.dynamic_update_index_in_dim(resid, x_in, slot_f, axis=0),
            resid,
        )

        # ---- backward slot (non-last stages; gradient arrived last cycle)
        slot_b = jnp.clip(i_b, 0, m - 1) % k_slots
        x_resid = jax.lax.dynamic_index_in_dim(
            resid, slot_b, axis=0, keepdims=False
        )
        dp_b, dx_b = jax.lax.cond(
            b_valid & ~is_last, b_recompute, b_skip, x_resid, recv_b
        )

        # ---- accumulate
        add = lambda a, b, ok: jax.tree_util.tree_map(  # noqa: E731
            lambda u, v: u + jnp.where(ok, v, jnp.zeros_like(v)), a, b
        )
        gp = add(add(gp, dp_f, f_valid & is_last), dp_b, b_valid & ~is_last)
        glp = add(glp, dlp, f_valid & is_last)
        loss_acc = loss_acc + jnp.where(f_valid & is_last, loss_mb, 0.0)
        if with_dx:
            # Stage 0 banks d(loss)/d(x_mb) for the caller (embedding
            # backward). Skipped entirely when the caller doesn't train
            # anything upstream of the pipeline — the bank is an O(M)
            # carry replicated on every stage, so don't pay it for nothing.
            dx_bank = jnp.where(
                b_valid & (stage_idx == 0),
                jax.lax.dynamic_update_index_in_dim(
                    dx_bank, dx_b, jnp.clip(i_b, 0, m - 1), axis=0
                ),
                dx_bank,
            )

        # ---- ring traffic: activations forward, gradients backward. The
        # last stage's dx leaves in ITS cycle (dx_last); others send dx_b.
        send_b = jnp.where(is_last, dx_last, dx_b)
        recv_f = jax.lax.ppermute(y, axis, fwd_perm)
        recv_b = jax.lax.ppermute(send_b, axis, bwd_perm)
        return (recv_f, recv_b, resid, gp, glp, dx_bank, loss_acc), None

    carry0 = (
        jnp.zeros(mb_shape, act_dtype),
        jnp.zeros(mb_shape, act_dtype),
        jnp.zeros((k_slots,) + mb_shape, act_dtype),
        zero_params,
        zero_last,
        jnp.zeros(((m,) + mb_shape) if with_dx else (0,), act_dtype),
        jnp.zeros((), jnp.float32),
    )
    (_, _, _, gp, glp, dx_bank, loss_acc), _ = jax.lax.scan(
        body, carry0, jnp.arange(n_cycles)
    )

    # Normalize to MEAN over microbatches, then over the data axis.
    inv_m = 1.0 / m
    gp = jax.tree_util.tree_map(lambda g: g * inv_m, gp)
    glp = jax.tree_util.tree_map(lambda g: g * inv_m, glp)
    if with_dx:
        dx_bank = dx_bank * inv_m
    loss = loss_acc * inv_m
    # Only the last stage holds the real loss / head grads — replicate over
    # the stage ring (masked psum), like pipeline_apply's output.
    mask_last = jnp.where(is_last, 1.0, 0.0)
    loss = jax.lax.psum(loss * mask_last, axis)
    glp = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g * mask_last, axis), glp
    )
    if data_axis is not None:
        n_data = jax.lax.psum(1, data_axis)
        loss = jax.lax.psum(loss, data_axis) / n_data
        mean_d = lambda g: jax.lax.psum(g, data_axis) / n_data  # noqa: E731
        gp = jax.tree_util.tree_map(mean_d, gp)
        glp = jax.tree_util.tree_map(mean_d, glp)
        if with_dx:
            # dx stays per-sample (data-sharded) but the loss it
            # differentiates is the GLOBAL mean: scale 1/n_data, no psum.
            dx_bank = dx_bank / n_data
    gp = jax.tree_util.tree_map(lambda g: g[None], gp)  # re-stack stage dim
    if not with_dx:
        return loss, gp, glp
    # Only stage 0 banked dx; replicate it over the stage ring (masked
    # psum, same as loss/glp and pipeline_apply's output) so the
    # stage-replicated out_spec is actually true on every device — a
    # downstream embedding backward on stages > 0 must not see zeros.
    dx_bank = jax.lax.psum(
        jnp.where(stage_idx == 0, dx_bank, jnp.zeros_like(dx_bank)), axis
    )
    # The caller's cotangent convention: dx matches x's dtype (the bank
    # accumulated at the promoted act_dtype; this downcast is the only
    # place precision is intentionally dropped, mirroring jax.grad).
    return loss, gp, glp, dx_bank.reshape(x.shape).astype(x.dtype)


def pipeline_1f1b_grads(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    last_fn: Callable[[Any, jnp.ndarray, jnp.ndarray], jnp.ndarray],
    last_params: Any,
    x: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "stage",
    num_microbatches: int = 8,
    data_axis: Optional[str] = "data",
    with_dx: bool = True,
):
    """One-forward-one-backward (PipeDream-flush) pipelined LOSS + GRADIENTS.

    ``stage_fn(params_for_one_stage, x) -> y`` (shape-preserving, as in
    :func:`pipeline_apply`); ``last_fn(last_params, y, targets) -> scalar``
    is the loss head, returning the MEAN loss of one microbatch — it runs
    inside the last stage's schedule slot, which is what lets backward for
    microbatch ``i`` start while microbatch ``i+1`` is still streaming
    forward (the 1F1B memory bound; see module docstring).

    Returns ``(loss, d_stacked_params, d_last_params, dx)`` where ``loss``
    and the grads are means over the global batch: loss replicated,
    ``d_stacked_params`` stage-sharded like ``stacked_params``,
    ``d_last_params`` replicated, ``dx`` sharded like ``x`` (feed it to the
    embedding/pre-pipeline backward). ``with_dx=False`` returns ``dx=None``
    and drops the O(M) input-gradient bank from the scan carry entirely —
    use it whenever nothing upstream of the pipeline is trained.

    Use this instead of autodiff-through-:func:`pipeline_apply` when
    ``M x per-stage-tape`` does not fit — the schedule holds at most
    ``2(S-1)+1`` boundary residuals per stage plus ONE recompute tape,
    regardless of ``M`` (see module docstring for the honest accounting).
    """
    n_stages = mesh.shape[axis]
    leading = {
        leaf.shape[0] for leaf in jax.tree_util.tree_leaves(stacked_params)
    }
    if len(leading) != 1:
        raise ValueError(
            f"stacked_params leaves disagree on the leading (stage) dim: {leading}"
        )
    (n_stacked,) = leading
    if n_stages == 1:
        # Serial fallback: same math, ordinary autodiff.
        def serial_loss(stacked, lp, xx):
            out = xx
            for s in range(n_stacked):
                params_s = jax.tree_util.tree_map(
                    lambda p, s=s: p[s], stacked
                )
                out = stage_fn(params_s, out)
            return last_fn(lp, out, targets)

        loss, (gp, glp, dx) = jax.value_and_grad(
            serial_loss, argnums=(0, 1, 2)
        )(stacked_params, last_params, x)
        return loss, gp, glp, (dx if with_dx else None)
    if n_stacked != n_stages:
        raise ValueError(
            f"stacked_params hold {n_stacked} stages but mesh axis {axis!r} "
            f"has size {n_stages}; they must match"
        )
    d_ax = data_axis if (data_axis and data_axis in mesh.shape) else None
    local_batch = x.shape[0] // (mesh.shape[d_ax] if d_ax else 1)
    if local_batch % num_microbatches != 0:
        raise ValueError(
            f"per-data-shard batch {local_batch} not divisible by "
            f"num_microbatches {num_microbatches}"
        )

    x_spec = P(*((d_ax,) + (None,) * (x.ndim - 1)))
    tgt_spec = P(*((d_ax,) + (None,) * (targets.ndim - 1)))
    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    last_spec = jax.tree_util.tree_map(lambda _: P(), last_params)

    body = functools.partial(
        _1f1b_shard_body,
        stage_fn,
        last_fn,
        axis=axis,
        data_axis=d_ax,
        num_microbatches=num_microbatches,
        with_dx=with_dx,
    )
    if not with_dx:
        loss, gp, glp = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(params_spec, last_spec, x_spec, tgt_spec),
            out_specs=(P(), params_spec, last_spec),
            check_vma=False,
        )(stacked_params, last_params, x, targets)
        return loss, gp, glp, None
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, last_spec, x_spec, tgt_spec),
        out_specs=(P(), params_spec, last_spec, x_spec),
        check_vma=False,
    )(stacked_params, last_params, x, targets)
