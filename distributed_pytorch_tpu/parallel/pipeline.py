"""Pipeline parallelism: a GPipe-style microbatched pipeline over a ``stage``
mesh axis, built from ``shard_map`` + ``lax.ppermute``.

No reference analog (SURVEY.md §2b: PP absent) — a beyond-parity capability,
built the TPU way rather than as a torch-style stage-process runtime:

* Every device runs the SAME program (SPMD). Stage identity is
  ``lax.axis_index("stage")``; stacked per-stage parameters ``[S, ...]`` are
  sharded ``P("stage")`` so each device physically holds only its stage's
  weights.
* The schedule is the classic collective-permute pipeline: at step ``t``,
  stage ``s`` processes microbatch ``t - s`` (masked out during fill/drain
  bubbles), then pushes its activation one hop along the ring with
  ``ppermute`` — nearest-neighbor ICI traffic, no host involvement.
* The loop is a ``lax.scan`` (reverse-differentiable, single XLA trace);
  gradients flow back through the permutes (the transpose of ``ppermute`` is
  the reverse permute) and arrive stage-sharded, exactly where the optimizer
  needs them.
* Composes with data parallelism: batch dim sharded over ``data``, each
  data-shard pipelines independently over ``stage``. (Stages run under
  shard_map, so compiler-driven TP inside a stage does not apply — TP inside
  PP stages would require manual collectives in the stage body.)

Bubble accounting: with ``M`` microbatches and ``S`` stages the pipeline runs
``M + S - 1`` steps, efficiency ``M / (M + S - 1)`` — pick ``M >= 4*S`` for
>80% utilization.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_pytorch_tpu.parallel.partitioning import Rules

#: Partition rule for stacked pipeline-stage params created by
#: :class:`PipelinedBlocks` (leading dim = stage). ``P("stage")`` shards dim 0
#: of any rank. Compose with other rules; first match wins.
PIPELINE_STAGE_RULES: Rules = ((r"(.*/)?stages/.*", P("stage")),)


def _pipeline_shard_body(
    stage_fn: Callable,
    stage_params: Any,
    x: jnp.ndarray,
    *,
    axis: str,
    num_microbatches: int,
):
    """Per-device body (under shard_map). ``stage_params``: this device's
    stage slice with leading dim 1; ``x``: local batch ``[B_local, ...]``."""
    n_stages = jax.lax.psum(1, axis)
    stage_idx = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    m = num_microbatches
    microbatches = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    mb_shape = microbatches.shape[1:]

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    n_steps = m + n_stages - 1

    def body(carry, t):
        recv, out = carry
        # Stage 0 injects microbatch t (clamped; masked during drain).
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(stage_idx == 0, inject, recv)
        y = stage_fn(params, x_in)
        # Last stage banks microbatch t-(S-1) (clamped; masked during fill).
        slot = jnp.clip(t - (n_stages - 1), 0, m - 1)
        banked = jax.lax.dynamic_update_index_in_dim(out, y, slot, axis=0)
        take = (stage_idx == n_stages - 1) & (t >= n_stages - 1)
        out = jnp.where(take, banked, out)
        recv = jax.lax.ppermute(y, axis, perm)
        return (recv, out), None

    recv0 = jnp.zeros(mb_shape, x.dtype)
    out0 = jnp.zeros((m,) + mb_shape, x.dtype)
    (_, out), _ = jax.lax.scan(body, (recv0, out0), jnp.arange(n_steps))
    # Replicate the last stage's outputs to every stage (masked all-reduce) so
    # the result leaves the shard_map with ordinary replicated-over-stage
    # semantics.
    out = jax.lax.psum(
        jnp.where(stage_idx == n_stages - 1, out, jnp.zeros_like(out)), axis
    )
    return out.reshape(x.shape[:1] + out.shape[2:])


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "stage",
    num_microbatches: int = 8,
    data_axis: Optional[str] = "data",
) -> jnp.ndarray:
    """Run ``x`` through ``n_stages`` chained applications of ``stage_fn``,
    pipelined over the mesh's ``axis``.

    ``stage_fn(params_for_one_stage, x) -> y`` must be shape-preserving
    (classic homogeneous-block pipelining; put embed/head outside the
    pipeline). ``stacked_params`` leaves have leading dim ``n_stages`` and
    should be sharded ``P(axis)`` (:data:`PIPELINE_STAGE_RULES`).
    ``x``: global ``[B, ...]``; the *per-data-shard* batch must divide
    ``num_microbatches``.
    """
    n_stages = mesh.shape[axis]
    leading = {
        leaf.shape[0] for leaf in jax.tree_util.tree_leaves(stacked_params)
    }
    if len(leading) != 1:
        raise ValueError(
            f"stacked_params leaves disagree on the leading (stage) dim: {leading}"
        )
    (n_stacked,) = leading
    if n_stages == 1:
        out = x
        for s in range(n_stacked):
            params_s = jax.tree_util.tree_map(lambda p, s=s: p[s], stacked_params)
            out = stage_fn(params_s, out)
        return out
    if n_stacked != n_stages:
        # Without this check, shard_map would hand each device an
        # n_stacked/n_stages-sized slice and the body's p[0] would silently
        # drop every other stage.
        raise ValueError(
            f"stacked_params hold {n_stacked} stages but mesh axis {axis!r} "
            f"has size {n_stages}; they must match"
        )

    d_ax = data_axis if (data_axis and data_axis in mesh.shape) else None
    local_batch = x.shape[0] // (mesh.shape[d_ax] if d_ax else 1)
    if local_batch % num_microbatches != 0:
        raise ValueError(
            f"per-data-shard batch {local_batch} not divisible by "
            f"num_microbatches {num_microbatches}"
        )

    x_spec = P(*((d_ax,) + (None,) * (x.ndim - 1)))
    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)

    body = functools.partial(
        _pipeline_shard_body,
        stage_fn,
        axis=axis,
        num_microbatches=num_microbatches,
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stacked_params, x)
