from distributed_pytorch_tpu.parallel.bootstrap import (
    is_main_process,
    setup_distributed,
    shutdown_distributed,
)
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.sharding import (
    batch_sharding,
    put_global_batch,
    replicated_sharding,
)

__all__ = [
    "batch_sharding",
    "is_main_process",
    "make_mesh",
    "put_global_batch",
    "replicated_sharding",
    "setup_distributed",
    "shutdown_distributed",
]
