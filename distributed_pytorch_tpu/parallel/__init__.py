from distributed_pytorch_tpu.parallel.bootstrap import (
    is_main_process,
    setup_distributed,
    shutdown_distributed,
)
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import (
    TRANSFORMER_TP_RULES,
    make_fsdp_specs,
    make_param_specs,
    make_state_shardings,
    make_state_specs,
    make_zero1_shardings,
    make_zero1_state_specs,
    shard_train_state,
)
from distributed_pytorch_tpu.parallel.pipeline import (
    PIPELINE_STAGE_RULES,
    pipeline_1f1b_grads,
    pipeline_apply,
)
from distributed_pytorch_tpu.parallel.sharding import (
    batch_sharding,
    put_global_batch,
    replicated_sharding,
)

__all__ = [
    "PIPELINE_STAGE_RULES",
    "TRANSFORMER_TP_RULES",
    "pipeline_1f1b_grads",
    "pipeline_apply",
    "batch_sharding",
    "is_main_process",
    "make_fsdp_specs",
    "make_mesh",
    "make_param_specs",
    "make_state_shardings",
    "make_state_specs",
    "make_zero1_shardings",
    "make_zero1_state_specs",
    "put_global_batch",
    "replicated_sharding",
    "setup_distributed",
    "shard_train_state",
    "shutdown_distributed",
]
