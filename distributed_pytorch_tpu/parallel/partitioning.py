"""Parameter partitioning: path-pattern rules -> PartitionSpecs -> NamedShardings
for the full TrainState (params, optimizer state, model state).

No reference analog — the reference replicates every parameter on every rank
(plain DDP, ``multigpu.py:36``; SURVEY.md §2b records TP/FSDP as absent). On
TPU, parameter sharding is a *placement annotation*, not a code change: the
jitted train step stays byte-identical, and XLA inserts the all-gathers /
reduce-scatters implied by the shardings onto ICI. This module produces those
annotations:

* :func:`make_param_specs` — regex-on-parameter-path rules (megatron-style
  tensor parallelism for the transformer family lives in
  :data:`TRANSFORMER_TP_RULES`);
* :func:`make_fsdp_specs` — ZeRO-3-style sharding: every parameter's largest
  divisible dim is split over the ``fsdp`` axis;
* :func:`make_state_specs` / :func:`make_state_shardings` — lift param specs
  onto the whole TrainState. Optimizer-state subtrees that mirror the param
  tree (optax ``trace``/``mu``/``nu``) inherit the param specs by structure
  matching, so Adam moments are sharded exactly like their parameters.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
import jax.tree_util as jtu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path-regex, PartitionSpec) pairs, first match wins. Paths are
# "/"-joined key paths, e.g. "block_0/attention/query/kernel".
Rules = Sequence[Tuple[str, P]]

#: Megatron-style tensor parallelism for :class:`TransformerLM`: QKV
#: projections split the heads dim, the output projection splits its (heads)
#: input dim — so attention needs no collective until the row-parallel ``out``
#: matmul, where XLA inserts one all-reduce. Same column-then-row split for
#: the MLP. The embedding table splits its feature (d_model) dim; the LM head
#: splits its output (vocab) dim.
TRANSFORMER_TP_RULES: Rules = (
    (r".*/attention/(query|key|value)/kernel$", P(None, "tensor", None)),
    (r".*/attention/(query|key|value)/bias$", P("tensor", None)),
    (r".*/attention/out/kernel$", P("tensor", None, None)),
    (r".*/mlp/up/kernel$", P(None, "tensor")),
    (r".*/mlp/up/bias$", P("tensor")),
    (r".*/mlp/down/kernel$", P("tensor", None)),
    (r"^embed/embedding$", P(None, "tensor")),
    (r"^lm_head/kernel$", P(None, "tensor")),
    (r"^lm_head/bias$", P("tensor")),
)


def rules_on_axis(rules: Rules, axis: str) -> Rules:
    """Rebind a single-axis rule table onto a different mesh-axis name.

    :data:`TRANSFORMER_TP_RULES` names its sharded dims ``"tensor"`` (the
    training-mesh convention); the serving mesh calls the same physical
    axis ``"model"``. The split geometry is identical — only the label
    changes — so consumers rebind the one rule table instead of keeping a
    drifting copy per axis name.
    """

    def rebind(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            return tuple(axis for _ in entry)
        return axis

    return tuple(
        (pattern, P(*(rebind(entry) for entry in spec)))
        for pattern, spec in rules
    )


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if isinstance(entry, jtu.DictKey):
            parts.append(str(entry.key))
        elif isinstance(entry, jtu.SequenceKey):
            parts.append(str(entry.idx))
        else:  # GetAttrKey / FlattenedIndexKey
            parts.append(str(getattr(entry, "name", entry)))
    return "/".join(parts)


def _check_divisible(path: str, shape, spec: P, mesh_shape) -> None:
    for dim, axes in enumerate(spec):
        if axes is None:
            continue
        for axis in (axes if isinstance(axes, tuple) else (axes,)):
            if axis not in mesh_shape:
                raise ValueError(
                    f"param {path!r} spec {spec} references mesh axis "
                    f"{axis!r}, absent from mesh axes {sorted(mesh_shape)}"
                )
            size = mesh_shape[axis]
            if dim >= len(shape) or shape[dim] % size != 0:
                raise ValueError(
                    f"param {path!r} shape {tuple(shape)} dim {dim} is not "
                    f"divisible by mesh axis {axis!r} (size {size})"
                )


def make_param_specs(params, rules: Rules, *, mesh: Optional[Mesh] = None):
    """PartitionSpec pytree for ``params``: first rule whose regex matches the
    "/"-joined param path wins; unmatched params are replicated (``P()``).

    With ``mesh``, every matched spec is validated for divisibility up front —
    a shape error here is far more readable than XLA's at compile time.
    """
    mesh_shape = dict(mesh.shape) if mesh is not None else None
    compiled = [(re.compile(pattern), spec) for pattern, spec in rules]

    def assign(path, leaf):
        path_s = _path_str(path)
        for pattern, spec in compiled:
            if pattern.match(path_s):
                if mesh_shape is not None:
                    _check_divisible(path_s, leaf.shape, spec, mesh_shape)
                return spec
        return P()

    return jtu.tree_map_with_path(assign, params)


def make_fsdp_specs(params, *, mesh: Mesh, axis: str = "fsdp"):
    """ZeRO-3-style specs: shard each parameter's largest ``axis``-divisible
    dim; parameters with no divisible dim stay replicated. XLA turns this into
    all-gather-before-use + reduce-scatter-of-grads (weight-update sharding),
    the TPU analog of FSDP (SURVEY.md §2b)."""
    size = mesh.shape[axis]

    def assign(leaf):
        shape = getattr(leaf, "shape", ())
        best = max(
            (d for d in range(len(shape)) if shape[d] % size == 0 and shape[d] >= size),
            key=lambda d: shape[d],
            default=None,
        )
        if best is None:
            return P()
        spec = [None] * len(shape)
        spec[best] = axis
        return P(*spec)

    return jtu.tree_map(assign, params)


def _replicated_like(tree):
    return jtu.tree_map(lambda _: P(), tree)


def _opt_state_specs(opt_state, params, param_specs):
    """Map param specs onto optimizer state by structure matching: any subtree
    of ``opt_state`` with the same treedef as ``params`` (optax momenta:
    ``trace``, ``mu``, ``nu``) inherits ``param_specs``; everything else
    (step counters, empty states) is replicated."""
    params_treedef = jtu.tree_structure(params)

    def is_param_like(subtree) -> bool:
        return jtu.tree_structure(subtree) == params_treedef

    return jtu.tree_map(
        lambda sub: param_specs if is_param_like(sub) else _replicated_like(sub),
        opt_state,
        is_leaf=is_param_like,
    )


def make_state_specs(state, param_specs):
    """Lift param specs to a TrainState-shaped PartitionSpec pytree."""
    return type(state)(
        params=param_specs,
        model_state=_replicated_like(state.model_state),
        opt_state=_opt_state_specs(state.opt_state, state.params, param_specs),
        step=P(),
        loss_scale=_replicated_like(state.loss_scale),
        rng=_replicated_like(state.rng),
    )


def make_zero1_state_specs(state, *, mesh: Mesh, axis: str = "data"):
    """ZeRO-1: parameters (and model state) stay fully replicated, only the
    param-shaped optimizer moments (optax ``mu``/``nu``/``trace``) shard over
    ``axis`` — each data-parallel worker keeps 1/N of the Adam moments,
    computes 1/N of the weight update, and XLA all-gathers the updates into
    the replicated new params.

    The middle rung of the sharding ladder (DP < **ZeRO-1** < FSDP/ZeRO-3 <
    TP): Adam moments are 2/3 of an f32 training state's bytes, so this cuts
    state memory nearly 3x at large N with no change to forward/backward
    communication — the gradient all-reduce is unchanged; only the optimizer
    update gains an all-gather. No reference analog (plain DDP replicates
    everything, ``multigpu.py:36``). Feed the result to
    :func:`make_state_shardings`-style lifting yourself or use
    :func:`make_zero1_shardings`.
    """
    moment_specs = make_fsdp_specs(state.params, mesh=mesh, axis=axis)
    return type(state)(
        params=_replicated_like(state.params),
        model_state=_replicated_like(state.model_state),
        opt_state=_opt_state_specs(state.opt_state, state.params, moment_specs),
        step=P(),
        loss_scale=_replicated_like(state.loss_scale),
        rng=_replicated_like(state.rng),
    )


def make_zero1_shardings(mesh: Mesh, state, *, axis: str = "data"):
    """TrainState-shaped NamedSharding pytree for ZeRO-1 (see
    :func:`make_zero1_state_specs`) — feed to ``jax.device_put`` and
    ``make_train_step(state_sharding=...)``."""
    return specs_to_shardings(
        mesh, make_zero1_state_specs(state, mesh=mesh, axis=axis)
    )


def specs_to_shardings(mesh: Mesh, specs):
    """PartitionSpec pytree -> NamedSharding pytree on ``mesh``."""
    return jtu.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def make_state_shardings(mesh: Mesh, state, param_specs):
    """TrainState-shaped NamedSharding pytree — feed to ``jax.device_put`` (to
    place/reshard a state) and to ``make_train_step(state_sharding=...)``."""
    return specs_to_shardings(mesh, make_state_specs(state, param_specs))


def shard_train_state(state, shardings):
    """Place (or reshard) a TrainState according to ``shardings``."""
    return jax.device_put(state, shardings)
