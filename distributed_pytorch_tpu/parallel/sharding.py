"""Sharding helpers: NamedShardings for batches and replicated state, and
global-batch assembly from per-process shards.

This module is the seam where the reference's two distribution mechanisms meet
their TPU-native replacements:

* ``DistributedSampler``'s per-rank shard (reference ``multigpu.py:78``) becomes
  a host-local numpy shard placed as one slice of a *globally sharded*
  ``jax.Array`` (:func:`put_global_batch`);
* DDP's parameter broadcast + gradient allreduce (reference ``multigpu.py:36,42``)
  disappears: parameters carry a replicated sharding, batches carry a
  ``P("data", ...)`` sharding, and XLA inserts the cross-replica reduce inside
  the jitted train step.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that replicates a value on every device of the mesh."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data", *, spec=None) -> NamedSharding:
    """Shard dim 0 (batch) across ``axis``; later dims replicated. An explicit
    ``spec`` overrides (e.g. ``P("data", "sequence")`` to co-shard tokens
    along the ring-attention sequence axis)."""
    return NamedSharding(mesh, spec if spec is not None else P(axis))


def put_global_batch(mesh: Mesh, local_batch, axis: str = "data", *, spec=None):
    """Turn this process's local numpy batch into a globally sharded jax.Array.

    Single-process: a straight ``device_put`` with batch sharding.
    Multi-process: each host contributes only its addressable shard; the global
    array is assembled with ``jax.make_array_from_process_local_data`` — the
    part of the design with no reference analog (the closest is each DDP rank
    holding its own sampler shard, ``multigpu.py:78``).

    ``local_batch`` may be a pytree (e.g. ``(inputs, targets)``). ``spec``
    overrides the default dim-0 sharding (e.g. ``P("data", "sequence")`` to
    co-shard the sequence dim for ring attention).
    """
    sharding = batch_sharding(mesh, axis, spec=spec)
    if jax.process_count() == 1:
        return jax.device_put(local_batch, sharding)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        local_batch,
    )
