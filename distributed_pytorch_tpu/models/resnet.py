"""ResNet family — the "real model" tier.

Capability twin of the torchvision ``resnet50()`` the reference swaps in for
profiling (``multigpu_profile.py:13-27``), re-designed TPU-first rather than
ported:

* **NHWC** layout (TPU convolutions are natively channels-last; the reference's
  NCHW is a CUDA convention);
* optional **bfloat16** compute dtype (MXU-native) with float32 parameters and
  batch statistics;
* BatchNorm reductions become *global-batch* statistics when the batch is
  sharded over the ``data`` mesh axis (XLA inserts the cross-replica mean —
  SyncBN semantics for free).

``ResNet18``/``ResNet50`` builders mirror the torchvision surface; stage
layouts are the standard He et al. configurations.
"""

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (expansion 4)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = self.conv(
            self.filters, (3, 3), self.strides, use_bias=False, name="conv2"
        )(y)
        y = self.norm(name="bn2")(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False, name="conv3")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)

        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, use_bias=False, name="conv_proj"
            )(residual)
            residual = self.norm(name="bn_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (expansion 1) for ResNet-18/34."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), use_bias=False, name="conv2")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn2")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, use_bias=False, name="conv_proj"
            )(residual)
            residual = self.norm(name="bn_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Generic ResNet over NHWC inputs ``(batch, height, width, 3)``."""

    stage_sizes: Sequence[int]
    block: Callable = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    # Small-image stem (the standard CIFAR adaptation, as in the original
    # ResNet paper's CIFAR experiments): 3x3 stride-1 conv, no maxpool —
    # a 7x7/2 stem + pool would collapse 32x32 inputs to 8x8 before stage 1.
    cifar_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        kernel, strides, pad = (
            ((3, 3), (1, 1), [(1, 1), (1, 1)])
            if self.cifar_stem
            else ((7, 7), (2, 2), [(3, 3), (3, 3)])
        )
        x = conv(self.num_filters, kernel, strides, padding=pad,
                 use_bias=False, name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block_idx in range(num_blocks):
                strides = (2, 2) if stage > 0 and block_idx == 0 else (1, 1)
                x = self.block(
                    self.num_filters * 2**stage,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    name=f"stage{stage + 1}_block{block_idx + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block=BottleneckBlock)
