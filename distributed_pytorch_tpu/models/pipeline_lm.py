"""Pipeline-parallel decoder-only LM: the TransformerLM block stack pipelined
over the mesh's ``stage`` axis via :func:`..parallel.pipeline.pipeline_apply`.

Embedding, final LayerNorm, and LM head sit outside the pipeline (replicated —
they are small next to the block stack); the body is ``n_stages *
layers_per_stage`` transformer blocks whose parameters are stacked ``[S, ...]``
and sharded ``P("stage")`` so each device holds one stage's weights.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from distributed_pytorch_tpu.models.transformer import TransformerBlock
from distributed_pytorch_tpu.parallel.pipeline import pipeline_apply


class _Stage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` dense transformer blocks."""

    n_heads: int
    d_model: int
    d_ff: int
    layers_per_stage: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i in range(self.layers_per_stage):
            x = TransformerBlock(
                self.n_heads, self.d_model, self.d_ff, self.dtype,
                name=f"layer_{i}",
            )(x)
        return x


class PipelinedTransformerLM(nn.Module):
    """GPT-style causal LM ``[B, T] -> [B, T, vocab]`` with a GPipe-pipelined
    body. ``B`` (per data shard) must be divisible by ``num_microbatches``."""

    vocab_size: int = 32000
    d_model: int = 512
    n_stages: int = 4
    layers_per_stage: int = 2
    n_heads: int = 8
    d_ff: int = 2048
    num_microbatches: int = 8
    dtype: Any = jnp.float32
    mesh: Optional[Mesh] = None
    stage_axis: str = "stage"
    data_axis: Optional[str] = "data"

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        x = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.dtype, name="embed"
        )(tokens)

        stage = _Stage(
            self.n_heads, self.d_model, self.d_ff, self.layers_per_stage,
            self.dtype,
        )

        def init_stacked(rng, sample):
            rngs = jax.random.split(rng, self.n_stages)
            return jax.vmap(lambda r: stage.init(r, sample)["params"])(rngs)

        stacked = self.param("stages", init_stacked, x[:1])

        # During init, trace the cheap serial chain instead of the pipeline:
        # same params, and the init sample batch (size 1) need not satisfy the
        # pipeline's data-axis / microbatch divisibility.
        use_pipeline = (
            not self.is_initializing()
            and self.mesh is not None
            and self.mesh.shape.get(self.stage_axis, 1) > 1
        )
        if use_pipeline:
            x = pipeline_apply(
                lambda p, xin: stage.apply({"params": p}, xin),
                stacked,
                x,
                mesh=self.mesh,
                axis=self.stage_axis,
                num_microbatches=self.num_microbatches,
                data_axis=self.data_axis,
            )
        else:
            # Serial fallback (no mesh / trivial stage axis): chain the stages.
            for s in range(self.n_stages):
                params_s = jax.tree_util.tree_map(lambda p, s=s: p[s], stacked)
                x = stage.apply({"params": params_s}, x)

        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(x)
