from distributed_pytorch_tpu.models.mlp import MLP
from distributed_pytorch_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
from distributed_pytorch_tpu.models.moe import MOE_EP_RULES, MoEMLP
from distributed_pytorch_tpu.models.pipeline_lm import PipelinedTransformerLM
from distributed_pytorch_tpu.models.toy import ToyRegressor
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.models.vit import ViT, ViT_L32

__all__ = [
    "MLP",
    "MOE_EP_RULES",
    "MoEMLP",
    "PipelinedTransformerLM",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ToyRegressor",
    "TransformerLM",
    "ViT",
    "ViT_L32",
]
