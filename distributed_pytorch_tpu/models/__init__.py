from distributed_pytorch_tpu.models.mlp import MLP
from distributed_pytorch_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
from distributed_pytorch_tpu.models.toy import ToyRegressor

__all__ = [
    "MLP",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ToyRegressor",
]
