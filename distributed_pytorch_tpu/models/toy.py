"""Toy regression model — twin of the reference's ``torch.nn.Linear(20, 1)``
(``single_gpu.py:50``, identical in every ladder script's ``load_train_objs``).
"""

import flax.linen as nn
import jax.numpy as jnp


class ToyRegressor(nn.Module):
    """A single dense layer: ``(batch, in_features) -> (batch, features)``.

    ``dtype`` is the compute dtype (``Policy.compute_dtype``); parameters
    stay float32 (flax's ``param_dtype`` default) — master weights.
    """

    features: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Dense(self.features, dtype=self.dtype, name="linear")(x)
