"""Toy regression model — twin of the reference's ``torch.nn.Linear(20, 1)``
(``single_gpu.py:50``, identical in every ladder script's ``load_train_objs``).
"""

import flax.linen as nn
import jax.numpy as jnp


class ToyRegressor(nn.Module):
    """A single dense layer: ``(batch, in_features) -> (batch, features)``."""

    features: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Dense(self.features, name="linear")(x)
