"""Decoder-only transformer language model — the long-context flagship.

No reference analog (the reference tops out at ResNet-50 / a commented-out
torchvision ViT, ``multigpu_profile.py:23-24``); this is the model family that
exercises the framework's first-class long-context machinery:

* attention is pluggable: dense (XLA-fused) or :func:`ring_attention`
  (sequence-parallel over the mesh's ``sequence`` axis with ppermute rotation);
* RoPE positions are *global* sequence positions — correct under jit whether or
  not the sequence dim is sharded, because jitted arrays have global semantics;
* ``remat=True`` wraps each block in ``jax.checkpoint`` (rematerialize
  activations in backward — the HBM-for-FLOPs trade that long sequences need);
* all matmul-bearing modules take a compute ``dtype`` (bfloat16 for the MXU),
  while parameters and layernorm statistics stay float32.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from distributed_pytorch_tpu.models.moe import MoEMLP
from distributed_pytorch_tpu.ops.attention import (
    NEG_INF,
    ring_attention,
    ulysses_attention,
)
from distributed_pytorch_tpu.ops.flash_attention import flash_attention
from distributed_pytorch_tpu.ops.fused_cross_entropy import (
    fused_linear_cross_entropy,
)


def apply_rope(
    x: jnp.ndarray,
    *,
    theta: float = 10000.0,
    positions: Optional[jnp.ndarray] = None,
    scale: float = 1.0,
) -> jnp.ndarray:
    """Rotary position embedding over [B, T, H, D].

    ``positions`` ([T] int/float) defaults to global positions 0..T-1; the
    decode path passes the cache offset so a single-token step rotates by its
    absolute position. A 2-D ``positions`` ([B, T]) gives every batch row its
    OWN absolute positions — the continuous-batching decode path, where slots
    sit at unrelated sequence offsets.

    Context extension knobs for running PAST the training length:
    ``scale > 1`` is linear position interpolation (positions divided by
    ``scale``, squeezing a longer context into the trained angle range);
    raising ``theta`` is the NTK-aware alternative (slower frequency decay).
    Both are plain parameterizations here — which to use, and any
    finetuning, is the caller's policy."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    if positions is None:
        positions = jnp.arange(x.shape[1], dtype=jnp.float32)
    positions = positions.astype(jnp.float32)
    if scale != 1.0:
        positions = positions / scale
    angles = positions[..., :, None] * freqs  # [T, D/2] or [B, T, D/2]
    if angles.ndim == 2:
        angles = angles[None]  # shared positions broadcast over batch
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :d_half], x[..., d_half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


class Attention(nn.Module):
    """Multi-head attention with RoPE and a pluggable core.

    Core selection: when the mesh has a non-trivial sequence axis
    (cross-chip long context), ``sequence_mode`` picks the sequence-parallel
    strategy — ``"ring"`` (K/V rotation, O(T/sp) memory) or ``"ulysses"``
    (all-to-all seq->head redistribution, fully local full-T attention);
    otherwise the Pallas flash-attention kernel on TPU (which itself falls
    back to the dense XLA path on other backends or non-tiling shapes).
    """

    n_heads: int
    d_model: int
    dtype: Any = jnp.float32
    causal: bool = True
    # Grouped-query attention (GQA; 0 = MHA): K/V project to n_kv_heads
    # heads and each group of n_heads/n_kv_heads query heads shares one.
    # The WIN is the decode KV cache: it stores (and HBM re-reads, every
    # generated token) n_kv_heads instead of n_heads — at n_kv_heads=2,
    # H=16 that is an 8x cache cut, multiplicative with quantized_cache's
    # int8 halving. The query-side repeat happens compute-side after the
    # cache read, so the bandwidth saving is real. n_kv_heads=1 is MQA.
    # Under TP, the K/V kernels shard over n_kv_heads: needs
    # n_kv_heads % tp == 0 (keep kv heads >= the tensor axis).
    n_kv_heads: int = 0
    # Sliding-window (Mistral-style local) attention: position q attends
    # keys in (q - window, q]. 0 = full causal. Compute per layer drops
    # toward O(T * window) — the flash kernel skips out-of-band tiles —
    # and in decode the visibility mask bounds reads the same way. Not yet
    # composed with sequence parallelism (explicit error, no silent cap).
    window: int = 0
    # RoPE context-extension knobs (see apply_rope): linear position
    # interpolation factor and frequency base.
    rope_scale: float = 1.0
    rope_theta: float = 10000.0
    mesh: Optional[Mesh] = None
    sequence_axis: Optional[str] = None
    # How to parallelize attention over the sequence axis: "ring" (K/V
    # rotate via ppermute; memory O(T/sp) per chip — for T beyond one
    # chip's HBM) or "ulysses" (two all-to-alls redistribute seq->heads;
    # attention is then fully local full-T flash — for T that fits per
    # chip, needs (H/tp) % sp == 0). See ops/attention.py.
    sequence_mode: str = "ring"
    decode: bool = False  # autoregressive KV-cache mode (see generation.py)
    # int8 KV cache: at long context the [B, T, H, D] caches — not the
    # params — dominate decode memory and HBM traffic; symmetric absmax
    # per-(token, head) quantization (scale over D) halves both. Dequant
    # happens at the attention einsum, so the loop reads int8.
    quantized_cache: bool = False
    # Paged KV cache (the serving engine's layout, see serving/kv_cache.py):
    # instead of one contiguous [B, max_len, H, D] buffer per sequence, the
    # cache is a global pool [num_pages, page_size, Hkv, D] and each batch
    # row addresses it through a block table of physical page ids. Page 0 is
    # reserved as the NULL page: inactive slots write (and padded table
    # entries read) there, and the visibility mask guarantees nothing read
    # from it ever survives the softmax. Requires decode=True and the caller
    # to pass ``block_tables`` [S, pages_per_seq] + ``seq_lens`` [S] into
    # __call__ every step.
    page_size: int = 0
    num_pages: int = 0
    # Serving decode read path for the paged cache: "" keeps the inline XLA
    # gather math below (the bitwise reference), anything else names an
    # ops/paged_attention kernel mode ("auto" | "pallas" | "interpret" |
    # "xla"). Only single-token decode steps (t_step == 1) dispatch to the
    # kernel; prefill chunks and speculative verify always use the inline
    # math, which the kernel's fp path matches bitwise by construction.
    paged_kernel: str = ""
    # "" = fp pages (pool dtype follows the activations); "int8" = symmetric
    # absmax per-(token, head) int8 pages with [num_pages, page_size, Hkv]
    # float32 scale pools, quantized at every page write, dequantized at
    # read (inline gather or inside the kernel).
    kv_quant: str = ""

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        *,
        block_tables: Optional[jnp.ndarray] = None,
        seq_lens: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        # Validate unconditionally: a typo'd mode must fail on the first
        # single-chip forward, not later when the job first meets an sp>1
        # mesh mid-launch.
        if self.sequence_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown sequence_mode {self.sequence_mode!r} "
                "(expected 'ring' or 'ulysses')"
            )
        if self.window < 0:
            raise ValueError(f"window must be >= 0, got {self.window}")
        if self.window and not self.causal:
            raise ValueError("window requires causal attention")
        if self.page_size:
            if not self.decode:
                raise ValueError("page_size > 0 requires decode=True")
            if self.num_pages < 2:
                raise ValueError(
                    "paged decode needs num_pages >= 2 (page 0 is the "
                    f"reserved null page), got {self.num_pages}"
                )
            if self.quantized_cache:
                raise ValueError(
                    "paged decode does not compose with quantized_cache yet"
                )
            if self.window:
                raise ValueError(
                    "paged decode does not compose with sliding-window "
                    "attention yet"
                )
            if self.kv_quant not in ("", "int8"):
                raise ValueError(
                    f"unknown kv_quant {self.kv_quant!r} "
                    "(expected '' or 'int8')"
                )
            if self.paged_kernel:
                # Same fail-fast rule as sequence_mode: a typo'd kernel
                # mode dies on the cache-init forward, not mid-serve.
                from distributed_pytorch_tpu.ops.paged_attention import (
                    resolve_kernel,
                )

                resolve_kernel(self.paged_kernel)
        elif self.paged_kernel or self.kv_quant:
            raise ValueError(
                "paged_kernel / kv_quant require the paged cache "
                "(page_size > 0)"
            )
        head_dim = self.d_model // self.n_heads
        kv_heads = self.n_kv_heads or self.n_heads
        if self.n_heads % kv_heads:
            raise ValueError(
                f"n_heads {self.n_heads} not divisible by n_kv_heads "
                f"{kv_heads}"
            )
        dense = lambda heads, name: nn.DenseGeneral(  # noqa: E731
            (heads, head_dim), dtype=self.dtype, name=name
        )
        q_raw = dense(self.n_heads, "query")(x)
        k_raw = dense(kv_heads, "key")(x)
        v = dense(kv_heads, "value")(x)

        if self.decode and self.has_variable("cache", "cached_key"):
            if self.page_size:
                if block_tables is None or seq_lens is None:
                    raise ValueError(
                        "paged decode requires block_tables and seq_lens "
                        "every step (the serving engine passes them)"
                    )
                out = self._paged_decode_step(
                    q_raw, k_raw, v, block_tables, seq_lens
                )
            else:
                out = self._decode_step(q_raw, k_raw, v)
            return nn.DenseGeneral(
                self.d_model, axis=(-2, -1), dtype=self.dtype, name="out"
            )(out)
        if self.decode:
            # Cache init pass: size the KV cache — to this call's (max)
            # length in contiguous mode, to the global page pool in paged
            # mode — then fall through to the normal causal forward.
            if self.page_size:
                pool = (self.num_pages, self.page_size, kv_heads, head_dim)
                pool_dtype = jnp.int8 if self.kv_quant else k_raw.dtype
                self.variable("cache", "cached_key", jnp.zeros, pool, pool_dtype)
                self.variable("cache", "cached_value", jnp.zeros, pool, pool_dtype)
                if self.kv_quant:
                    # Per-(page-slot, head) float32 scales live alongside the
                    # int8 pools; they ride every pool-shaped program (CoW
                    # copy, spill/fetch) via the same tree_map genericity.
                    self.variable(
                        "cache", "key_scale", jnp.zeros, pool[:-1], jnp.float32
                    )
                    self.variable(
                        "cache", "value_scale", jnp.zeros, pool[:-1], jnp.float32
                    )
            else:
                cache_dtype = jnp.int8 if self.quantized_cache else k_raw.dtype
                self.variable("cache", "cached_key", jnp.zeros, k_raw.shape, cache_dtype)
                self.variable("cache", "cached_value", jnp.zeros, v.shape, cache_dtype)
                if self.quantized_cache:
                    self.variable(
                        "cache", "key_scale", jnp.zeros, k_raw.shape[:-1], jnp.float32
                    )
                    self.variable(
                        "cache", "value_scale", jnp.zeros, v.shape[:-1], jnp.float32
                    )
                self.variable(
                    "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
                )

        rope = lambda x, **kw: apply_rope(  # noqa: E731
            x, theta=self.rope_theta, scale=self.rope_scale, **kw
        )
        q = rope(q_raw)
        k = rope(k_raw)
        if kv_heads != self.n_heads:
            # Compute-side broadcast for the cores that need full heads
            # (flash, ulysses). Ring and decode take the UN-repeated k/v so
            # their HBM/ICI traffic stays at the kv-head size — that is
            # where GQA pays.
            group = self.n_heads // kv_heads
            kx = jnp.repeat(k, group, axis=2)
            vx = jnp.repeat(v, group, axis=2)
        else:
            kx, vx = k, v

        use_ring = (
            self.mesh is not None
            and self.sequence_axis is not None
            and self.mesh.shape.get(self.sequence_axis, 1) > 1
        )
        if use_ring and self.sequence_mode == "ulysses":
            # Pre-repeat is structural here: the all-to-all splits the
            # (query) head dim across the axis, so K/V must carry the same
            # head count. (validated mode at __call__ top) Sliding-window
            # composes trivially: post-exchange attention is full-sequence
            # local, the band is just a mask.
            out = ulysses_attention(
                q, kx, vx, mesh=self.mesh, axis_name=self.sequence_axis,
                causal=self.causal, window=self.window,
            )
        elif use_ring:
            # Ring rotates K/V around the ICI ring every hop: hand it the
            # UN-repeated kv-head blocks (kv_groups broadcasts per hop,
            # compute-side) so GQA cuts the interconnect bytes too. With
            # window > 0, hops wholly behind the band are never rotated
            # (ring_live_hops): ICI traffic and compute are O(window).
            out = ring_attention(
                q, k, v, mesh=self.mesh, axis_name=self.sequence_axis,
                causal=self.causal, kv_groups=self.n_heads // kv_heads,
                window=self.window,
            )
        else:
            out = flash_attention(
                q, kx, vx, causal=self.causal, window=self.window,
                mesh=self.mesh,
            )
        return nn.DenseGeneral(
            self.d_model, axis=(-2, -1), dtype=self.dtype, name="out"
        )(out)

    def _decode_step(self, q_raw, k_raw, v):
        """One autoregressive step: rotate q/k by their absolute positions,
        write k/v into the cache at the running index, attend q against the
        valid cache prefix. ``q_raw``: [B, T_step, H, D] (T_step usually 1).

        ``cache_index`` may be a scalar (every row at the same offset — the
        ``generate`` loop) or a ``[B]`` vector giving every row its OWN
        offset — the speculative per-row-acceptance path, where rows advance
        by their individual accepted counts. The vector path mirrors the
        paged decode step: per-row RoPE positions, a scatter write at
        (row, position), and a per-row visibility mask; out-of-range
        positions (a fast row's replay region past the buffer) are dropped
        by the scatter, and reads past a row's index are masked, so rolling
        a row back IS lowering its index — no zeroing or copies."""
        cached_key = self.variable("cache", "cached_key", lambda: None)
        cached_value = self.variable("cache", "cached_value", lambda: None)
        cache_index = self.variable("cache", "cache_index", lambda: None)
        index = cache_index.value
        t_step = q_raw.shape[1]
        max_len = cached_key.value.shape[1]
        per_row = index.ndim == 1  # [B] per-row offsets vs one scalar
        if per_row and self.quantized_cache:
            raise ValueError(
                "per-row cache_index does not compose with quantized_cache "
                "(the int8 write path slices at one shared offset)"
            )

        if per_row:
            positions = index[:, None] + jnp.arange(t_step)  # [B, T_step]
        else:
            positions = index + jnp.arange(t_step)  # [T_step]
        q = apply_rope(
            q_raw, positions=positions, theta=self.rope_theta,
            scale=self.rope_scale,
        )
        k = apply_rope(
            k_raw, positions=positions, theta=self.rope_theta,
            scale=self.rope_scale,
        )

        if self.quantized_cache:
            keys, values = self._update_quantized_cache(
                cached_key, cached_value, k, v, index
            )
        elif per_row:
            b, _, kv_h, d_h = k_raw.shape
            rows = jnp.repeat(jnp.arange(b, dtype=jnp.int32), t_step)
            flat_pos = positions.reshape(-1)
            cached_key.value = cached_key.value.at[rows, flat_pos].set(
                k.astype(cached_key.value.dtype).reshape(-1, kv_h, d_h),
                mode="drop",
            )
            cached_value.value = cached_value.value.at[rows, flat_pos].set(
                v.astype(cached_value.value.dtype).reshape(-1, kv_h, d_h),
                mode="drop",
            )
            keys, values = cached_key.value, cached_value.value
        else:
            cached_key.value = jax.lax.dynamic_update_slice(
                cached_key.value, k.astype(cached_key.value.dtype), (0, index, 0, 0)
            )
            cached_value.value = jax.lax.dynamic_update_slice(
                cached_value.value, v.astype(cached_value.value.dtype), (0, index, 0, 0)
            )
            keys, values = cached_key.value, cached_value.value
        cache_index.value = index + t_step
        scale = q.shape[-1] ** -0.5
        # Position k is visible to step-q q when k <= index + q (and, with
        # a sliding window, within the last `window` positions). Per-row
        # indices make the mask [B, T_step, K] instead of [T_step, K].
        if per_row:
            q_abs = positions[:, :, None]  # [B, T_step, 1]
            k_abs = jnp.arange(max_len)[None, None, :]
        else:
            q_abs = (index + jnp.arange(t_step))[:, None]
            k_abs = jnp.arange(max_len)[None, :]
        visible = k_abs <= q_abs
        if self.window:
            visible = visible & (q_abs - k_abs < self.window)
        # ONE attention path for MHA and GQA: grouped einsums against the
        # (small) cache — the query is reshaped [B, t, Hkv, G, D] and
        # contracted directly with the [B, T, Hkv, D] cache, so the
        # n_heads-sized K/V tensors are never materialized (a jnp.repeat
        # here would make XLA write and re-read group x the cache bytes GQA
        # exists to avoid). MHA is simply group=1 (the reshape is a no-op
        # expand). Head order matches the forward path's jnp.repeat: query
        # head h shares kv head h // group, so kv leads group.
        kv_heads = keys.shape[2]
        b, t_q, h, d = q.shape
        group = h // kv_heads
        qg = q.reshape(b, t_q, kv_heads, group, d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, keys) * scale
        mask = (
            visible[:, None, None] if visible.ndim == 3  # [B,1,1,T,K]
            else visible[None, None, None]
        )
        logits = jnp.where(mask, logits, NEG_INF)
        weights = jax.nn.softmax(
            logits.astype(jnp.float32), axis=-1
        ).astype(q.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, values)
        return out.reshape(b, t_q, h, d)

    def _paged_decode_step(self, q_raw, k_raw, v, block_tables, seq_lens):
        """One decode/prefill step against the PAGED cache pool.

        ``q_raw`` [S, T_step, H, D]: T_step is 1 for the batched decode step,
        or a prefill chunk length (then S is the chunked rows, usually 1).
        ``block_tables`` [S, pages_per_seq] maps each row's logical page to a
        physical page in the [num_pages, page_size, Hkv, D] pool (0 = the
        reserved null page). ``seq_lens`` [S] is each row's token count
        BEFORE this step, i.e. the absolute position of its first new token.

        Same math as :meth:`_decode_step` — RoPE at absolute positions,
        write-then-attend, grouped GQA einsums — except positions are
        per-row, the write is a scatter into (physical page, offset), and the
        read gathers each row's pages into a [S, pages*page_size, Hkv, D]
        view. Rows whose table is all zeros (inactive slots) write into the
        null page and read garbage that the visibility mask turns into a
        discarded-but-finite output: the null page only ever holds finite
        values written by other inactive rows.
        """
        cached_key = self.variable("cache", "cached_key", lambda: None)
        cached_value = self.variable("cache", "cached_value", lambda: None)
        key_scale = value_scale = None
        if self.kv_quant:
            key_scale = self.variable("cache", "key_scale", lambda: None)
            value_scale = self.variable("cache", "value_scale", lambda: None)
        s, t_step, h, d = q_raw.shape
        kv_heads = k_raw.shape[2]
        page = self.page_size
        pages_per_seq = block_tables.shape[1]

        seq_lens = seq_lens.astype(jnp.int32)
        positions = seq_lens[:, None] + jnp.arange(t_step, dtype=jnp.int32)
        q = apply_rope(
            q_raw, positions=positions, theta=self.rope_theta,
            scale=self.rope_scale,
        )
        k = apply_rope(
            k_raw, positions=positions, theta=self.rope_theta,
            scale=self.rope_scale,
        )

        # Scatter this step's K/V into (physical page, in-page offset). A
        # position at or past the row's table capacity — a speculative
        # chunk's tail can overhang the final tokens of a sequence near
        # max_seq_len — is routed to the reserved null page (id 0) instead
        # of letting the clipped logical index alias into the row's LAST
        # page, where it would clobber valid K/V at the same in-page
        # offset. The null page absorbs the garbage exactly like inactive
        # rows' writes; the visibility mask keeps it dead on every read.
        flat_pos = positions.reshape(-1)  # [S*T_step]
        logical = jnp.clip(flat_pos // page, 0, pages_per_seq - 1)
        rows = jnp.repeat(jnp.arange(s, dtype=jnp.int32), t_step)
        phys = block_tables[rows, logical]  # [S*T_step]
        phys = jnp.where(flat_pos < pages_per_seq * page, phys, 0)
        offset = flat_pos % page
        if self.kv_quant:
            # Quantize at the write: symmetric absmax per-(token, head) over
            # D — the pool holds int8, the [num_pages, page_size, Hkv] scale
            # pool holds one float32 per written (page-slot, head).
            from distributed_pytorch_tpu.ops.quant import quantize_int8

            def write(cache, scale_var, x):
                qt = quantize_int8(
                    x.astype(jnp.float32).reshape(-1, kv_heads, d), (2,)
                )
                cache.value = cache.value.at[phys, offset].set(qt.q)
                scale_var.value = scale_var.value.at[phys, offset].set(
                    jnp.squeeze(qt.scale, -1)
                )

            write(cached_key, key_scale, k)
            write(cached_value, value_scale, v)
        else:
            cached_key.value = cached_key.value.at[phys, offset].set(
                k.astype(cached_key.value.dtype).reshape(-1, kv_heads, d)
            )
            cached_value.value = cached_value.value.at[phys, offset].set(
                v.astype(cached_value.value.dtype).reshape(-1, kv_heads, d)
            )

        if self.paged_kernel and t_step == 1:
            # Fused read path: the batched single-token decode step goes
            # through ops/paged_attention (Pallas on TPU, its XLA reference
            # elsewhere — which reproduces the inline math below bitwise).
            # Prefill chunks and speculative verify (t_step > 1) keep the
            # inline math: they are a tiny fraction of decode-step count and
            # the kernel's one-query-row grid doesn't fit them.
            from distributed_pytorch_tpu.ops.paged_attention import (
                paged_attention,
            )

            return paged_attention(
                q, cached_key.value, cached_value.value, block_tables,
                seq_lens,
                k_scale=None if key_scale is None else key_scale.value,
                v_scale=None if value_scale is None else value_scale.value,
                kernel=self.paged_kernel, mesh=self.mesh,
            )

        # Gather each row's pages into its contiguous logical view. K below
        # is pages_per_seq * page_size — the row's maximum context, not the
        # pool size.
        keys = cached_key.value[block_tables].reshape(
            s, pages_per_seq * page, kv_heads, d
        )
        values = cached_value.value[block_tables].reshape(
            s, pages_per_seq * page, kv_heads, d
        )
        if self.kv_quant:
            ks = key_scale.value[block_tables].reshape(
                s, pages_per_seq * page, kv_heads
            )
            vs = value_scale.value[block_tables].reshape(
                s, pages_per_seq * page, kv_heads
            )
            keys = keys.astype(q.dtype) * ks[..., None].astype(q.dtype)
            values = values.astype(q.dtype) * vs[..., None].astype(q.dtype)
        scale = d**-0.5
        k_abs = jnp.arange(pages_per_seq * page)[None, None, :]
        visible = k_abs <= positions[:, :, None]  # [S, T_step, K]
        group = h // kv_heads
        qg = q.reshape(s, t_step, kv_heads, group, d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, keys) * scale
        logits = jnp.where(visible[:, None, None], logits, NEG_INF)
        weights = jax.nn.softmax(
            logits.astype(jnp.float32), axis=-1
        ).astype(q.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, values)
        return out.reshape(s, t_step, h, d)

    def _update_quantized_cache(self, cached_key, cached_value, k, v, index):
        """Write this step's k/v as int8 + per-(token, head) float32 scales,
        and return the DEQUANTIZED full caches for the attention einsums —
        the dequant (int8 read, convert, scale) fuses into each einsum, so
        HBM sees int8 + one scale per head-token instead of bf16."""
        from distributed_pytorch_tpu.ops.quant import quantize_int8

        key_scale = self.variable("cache", "key_scale", lambda: None)
        value_scale = self.variable("cache", "value_scale", lambda: None)

        def write(cache, scale_var, x):
            qt = quantize_int8(x, (x.ndim - 1,))  # per-(token, head) over D
            q8, s = qt.q, jnp.squeeze(qt.scale, -1)  # [B, t, H]
            cache.value = jax.lax.dynamic_update_slice(
                cache.value, q8, (0, index, 0, 0)
            )
            scale_var.value = jax.lax.dynamic_update_slice(
                scale_var.value, s, (0, index, 0)
            )
            return (
                cache.value.astype(self.dtype)
                * scale_var.value[..., None].astype(self.dtype)
            )

        keys = write(cached_key, key_scale, k)
        values = write(cached_value, value_scale, v)
        return keys, values


class MLPBlock(nn.Module):
    d_ff: int
    d_model: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="up")(x)
        h = nn.gelu(h)
        return nn.Dense(self.d_model, dtype=self.dtype, name="down")(h)


class TransformerBlock(nn.Module):
    n_heads: int
    d_model: int
    d_ff: int
    dtype: Any = jnp.float32
    causal: bool = True
    mesh: Optional[Mesh] = None
    sequence_axis: Optional[str] = None
    sequence_mode: str = "ring"  # see Attention
    n_kv_heads: int = 0  # GQA (see Attention); 0 = MHA
    window: int = 0  # sliding-window attention (see Attention); 0 = full
    rope_scale: float = 1.0  # RoPE linear interpolation (see apply_rope)
    rope_theta: float = 10000.0
    dropout_rate: float = 0.0  # residual-branch dropout (see TransformerLM)
    n_experts: int = 0  # >0 swaps the dense MLP for an expert-parallel MoEMLP
    moe_top_k: int = 1  # router choices per token (see models/moe.py)
    decode: bool = False
    remat_mlp: bool = False  # rematerialize only the MLP branch (see TransformerLM)
    quantized_cache: bool = False  # int8 KV cache in decode (see Attention)
    page_size: int = 0  # paged KV cache in decode (see Attention); 0 = contiguous
    num_pages: int = 0
    paged_kernel: str = ""  # fused paged-decode read path (see Attention)
    kv_quant: str = ""  # int8 KV pages + scale pools (see Attention)

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        *,
        block_tables: Optional[jnp.ndarray] = None,
        seq_lens: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        def drop(y):
            # Active only when a "dropout" rng is supplied (the train step
            # with TrainState.rng armed); eval/decode never pass one, so
            # they are deterministic with no flags to thread.
            if self.dropout_rate == 0.0:
                return y
            return nn.Dropout(self.dropout_rate)(
                y, deterministic=not self.has_rng("dropout")
            )

        # Only pass the paged-decode arrays when the caller supplied them:
        # the train/remat paths must see the exact pre-paging call signature.
        paged_kw = (
            {} if block_tables is None
            else {"block_tables": block_tables, "seq_lens": seq_lens}
        )
        x = x + drop(Attention(
            self.n_heads, self.d_model, self.dtype, self.causal,
            n_kv_heads=self.n_kv_heads, window=self.window,
            rope_scale=self.rope_scale, rope_theta=self.rope_theta,
            mesh=self.mesh, sequence_axis=self.sequence_axis,
            sequence_mode=self.sequence_mode, decode=self.decode,
            quantized_cache=self.quantized_cache,
            page_size=self.page_size, num_pages=self.num_pages,
            paged_kernel=self.paged_kernel, kv_quant=self.kv_quant,
            name="attention",
        )(nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x), **paged_kw))
        if self.n_experts > 0:
            cls = nn.remat(MoEMLP) if self.remat_mlp else MoEMLP
            mlp = cls(
                self.n_experts, self.d_ff, self.d_model, self.dtype,
                router_top_k=self.moe_top_k, mesh=self.mesh, name="moe",
            )
        else:
            cls = nn.remat(MLPBlock) if self.remat_mlp else MLPBlock
            mlp = cls(self.d_ff, self.d_model, self.dtype, name="mlp")
        x = x + drop(mlp(nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)))
        return x


class LMHead(nn.Module):
    """The LM projection with an optional fused-loss path.

    Parameters are declared directly (``kernel``/``bias``) with the same
    names, shapes, and initializers ``nn.Dense(name="lm_head")`` would create,
    so the param tree — and pinned-seed initialization — is byte-identical
    whether or not the fused path is enabled, and checkpoints move freely
    between the two. EXCEPT under weight tying: with ``tied_kernel`` passed
    (``TransformerLM(tie_embeddings=True)``) no params are declared at all
    and the ``lm_head`` scope is absent from the tree — tied and untied
    checkpoints are different layouts by design.

    * ``targets is None`` (or ``fused_chunk == 0``): returns float32 logits
      ``[..., vocab]`` — the standard path, used by generation and eval.
    * fused path: returns the scalar mean cross-entropy via
      :func:`fused_linear_cross_entropy` — the ``[N, vocab]`` logits tensor
      (an LM's largest activation) is never materialized in forward or
      backward.
    """

    vocab_size: int
    fused_chunk: int = 0

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        targets: Optional[jnp.ndarray] = None,
        tied_kernel: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        if tied_kernel is not None:
            # Weight tying (GPT-2 style): the head IS the transposed token
            # embedding — no kernel or bias params are declared here, so
            # the lm_head scope vanishes from the param tree and gradients
            # flow to the embedding from both its uses. bias stays None:
            # the fused path then skips the bias add AND its dead gradient
            # accumulator in the backward scan.
            kernel = tied_kernel.astype(jnp.float32)
            bias = None
        else:
            kernel = self.param(
                "kernel",
                nn.initializers.lecun_normal(),
                (x.shape[-1], self.vocab_size),
                jnp.float32,
            )
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.vocab_size,),
                jnp.float32,
            )
        if self.fused_chunk and targets is not None:
            return fused_linear_cross_entropy(
                x.reshape(-1, x.shape[-1]),
                kernel,
                bias,
                targets.reshape(-1),
                self.fused_chunk,
            )
        # Logits in float32 for a numerically stable softmax-cross-entropy.
        logits = x.astype(jnp.float32) @ kernel
        return logits if bias is None else logits + bias


class TransformerLM(nn.Module):
    """GPT-style causal LM over token ids ``[batch, seq] -> [batch, seq, vocab]``.

    With ``fused_head_chunk > 0`` AND ``targets`` passed to ``__call__``, the
    model instead returns the scalar mean next-token cross-entropy computed by
    the fused LM head (the logits tensor is never materialized); the train
    step passes targets through when built with ``apply_takes_targets=True``.
    """

    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    d_ff: int = 2048
    dtype: Any = jnp.float32
    remat: bool = False
    # "full": jax.checkpoint around each whole block — maximal memory saving,
    # but the backward pass re-runs the flash-attention forward kernel
    # (measured 18% step-time tax at T=8192 on v5e). "mlp": rematerialize only
    # the MLP branch — the big d_ff activations are recomputed (cheap matmuls)
    # while attention kernels and their residuals stay saved. With the flash
    # kernel, activations are linear in T, so "mlp" (or remat=False) is the
    # right choice until HBM actually runs out.
    remat_policy: str = "full"  # "full" | "mlp"
    mesh: Optional[Mesh] = None
    sequence_axis: Optional[str] = None
    sequence_mode: str = "ring"  # "ring" | "ulysses" (see Attention)
    n_kv_heads: int = 0  # grouped-query attention (see Attention); 0 = MHA
    attention_window: int = 0  # sliding-window attention; 0 = full causal
    rope_scale: float = 1.0  # RoPE linear position interpolation factor
    rope_theta: float = 10000.0  # RoPE frequency base (NTK-aware extension)
    # Residual-branch + embedding dropout (GPT-2 placement). Active ONLY
    # when the apply carries a "dropout" rng — the train step does so when
    # TrainState.rng is armed (create_train_state(dropout_rng=...) /
    # Trainer(dropout_seed=...)); eval and generation never do, so they
    # stay deterministic with no train/eval flag plumbing.
    dropout_rate: float = 0.0
    n_experts: int = 0  # >0: MoE MLPs in every `moe_every`-th block
    moe_top_k: int = 1  # MoE router choices per token (1=Switch, 2=GShard)
    moe_every: int = 2
    # GPT-2-style weight tying: the LM head reuses the token embedding
    # (transposed, no bias) — vocab*d_model fewer params, gradients reach
    # the embedding from both ends. The lm_head scope then holds no params
    # (TP/quant rules for it simply don't match; the embedding stays a
    # gather + full-precision head reads under quantize=True). TP caveat:
    # TRANSFORMER_TP_RULES shard the embedding over d_model, so the tied
    # head contracts over the SHARDED axis — GSPMD inserts an all-reduce
    # and the [N, vocab] logits land replicated, where the untied
    # lm_head/kernel kept them vocab-sharded with no collective. For
    # vocab-sharded-head TP training at scale, prefer untied (or use the
    # fused CE head, which never materializes the logits at all).
    tie_embeddings: bool = False
    decode: bool = False  # KV-cache autoregressive mode (see generation.py)
    quantized_cache: bool = False  # int8 KV cache in decode (see Attention)
    fused_head_chunk: int = 0  # >0: vocab chunk size for the fused CE head
    # Paged KV cache for continuous-batching decode (see Attention and
    # serving/): the serving engine clones with decode=True, page_size=P,
    # num_pages=N and passes block_tables/seq_lens through __call__.
    page_size: int = 0
    num_pages: int = 0
    paged_kernel: str = ""  # fused paged-decode read path (see Attention)
    kv_quant: str = ""  # int8 KV pages + scale pools (see Attention)

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        targets: Optional[jnp.ndarray] = None,
        *,
        block_tables: Optional[jnp.ndarray] = None,
        seq_lens: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        embed = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.dtype, name="embed"
        )
        x = embed(tokens)
        if self.dropout_rate > 0.0:
            x = nn.Dropout(self.dropout_rate)(
                x, deterministic=not self.has_rng("dropout")
            )
        block = TransformerBlock
        remat_mlp = False
        if self.remat:
            if self.remat_policy == "full":
                block = nn.remat(TransformerBlock)
            elif self.remat_policy == "mlp":
                remat_mlp = True
            else:
                raise ValueError(f"unknown remat_policy {self.remat_policy!r}")
        # See TransformerBlock: the paged-decode arrays are forwarded only
        # when present so the train/remat call signature is unchanged.
        paged_kw = (
            {} if block_tables is None
            else {"block_tables": block_tables, "seq_lens": seq_lens}
        )
        for i in range(self.n_layers):
            # GShard-style interleaving: every `moe_every`-th block is MoE.
            moe = self.n_experts if (i + 1) % self.moe_every == 0 else 0
            x = block(
                self.n_heads, self.d_model, self.d_ff, self.dtype,
                True, self.mesh, self.sequence_axis,
                sequence_mode=self.sequence_mode,
                n_kv_heads=self.n_kv_heads, window=self.attention_window,
                rope_scale=self.rope_scale, rope_theta=self.rope_theta,
                dropout_rate=self.dropout_rate,
                n_experts=moe, moe_top_k=self.moe_top_k,
                decode=self.decode, remat_mlp=remat_mlp,
                quantized_cache=self.quantized_cache,
                page_size=self.page_size, num_pages=self.num_pages,
                paged_kernel=self.paged_kernel, kv_quant=self.kv_quant,
                name=f"block_{i}",
            )(x, **paged_kw)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        if self.fused_head_chunk and self.vocab_size % self.fused_head_chunk:
            # Fail loudly here: a silent dense fallback would surface later as
            # a baffling "gradient only defined for scalar-output functions"
            # from the train step (which expects the fused scalar loss).
            raise ValueError(
                f"vocab_size {self.vocab_size} not divisible by "
                f"fused_head_chunk {self.fused_head_chunk}"
            )
        return LMHead(
            self.vocab_size, self.fused_head_chunk, name="lm_head"
        )(
            x,
            targets,
            tied_kernel=embed.embedding.T if self.tie_embeddings else None,
        )
