"""Decoder-only transformer language model — the long-context flagship.

No reference analog (the reference tops out at ResNet-50 / a commented-out
torchvision ViT, ``multigpu_profile.py:23-24``); this is the model family that
exercises the framework's first-class long-context machinery:

* attention is pluggable: dense (XLA-fused) or :func:`ring_attention`
  (sequence-parallel over the mesh's ``sequence`` axis with ppermute rotation);
* RoPE positions are *global* sequence positions — correct under jit whether or
  not the sequence dim is sharded, because jitted arrays have global semantics;
* ``remat=True`` wraps each block in ``jax.checkpoint`` (rematerialize
  activations in backward — the HBM-for-FLOPs trade that long sequences need);
* all matmul-bearing modules take a compute ``dtype`` (bfloat16 for the MXU),
  while parameters and layernorm statistics stay float32.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import Mesh

from distributed_pytorch_tpu.models.moe import MoEMLP
from distributed_pytorch_tpu.ops.attention import ring_attention
from distributed_pytorch_tpu.ops.flash_attention import flash_attention


def apply_rope(x: jnp.ndarray, *, theta: float = 10000.0) -> jnp.ndarray:
    """Rotary position embedding over [B, T, H, D] (global positions 0..T-1)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    positions = jnp.arange(x.shape[1], dtype=jnp.float32)
    angles = positions[:, None] * freqs[None, :]  # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :d_half], x[..., d_half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


class Attention(nn.Module):
    """Multi-head attention with RoPE and a pluggable core.

    Core selection: ring attention when the mesh has a non-trivial sequence
    axis (cross-chip long context); otherwise the Pallas flash-attention
    kernel on TPU (which itself falls back to the dense XLA path on other
    backends or non-tiling shapes).
    """

    n_heads: int
    d_model: int
    dtype: Any = jnp.float32
    causal: bool = True
    mesh: Optional[Mesh] = None
    sequence_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        head_dim = self.d_model // self.n_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.n_heads, head_dim), dtype=self.dtype, name=name
        )
        q = apply_rope(dense("query")(x))
        k = apply_rope(dense("key")(x))
        v = dense("value")(x)

        use_ring = (
            self.mesh is not None
            and self.sequence_axis is not None
            and self.mesh.shape.get(self.sequence_axis, 1) > 1
        )
        if use_ring:
            out = ring_attention(
                q, k, v, mesh=self.mesh, axis_name=self.sequence_axis,
                causal=self.causal,
            )
        else:
            out = flash_attention(
                q, k, v, causal=self.causal, mesh=self.mesh
            )
        return nn.DenseGeneral(
            self.d_model, axis=(-2, -1), dtype=self.dtype, name="out"
        )(out)


class MLPBlock(nn.Module):
    d_ff: int
    d_model: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="up")(x)
        h = nn.gelu(h)
        return nn.Dense(self.d_model, dtype=self.dtype, name="down")(h)


class TransformerBlock(nn.Module):
    n_heads: int
    d_model: int
    d_ff: int
    dtype: Any = jnp.float32
    causal: bool = True
    mesh: Optional[Mesh] = None
    sequence_axis: Optional[str] = None
    n_experts: int = 0  # >0 swaps the dense MLP for an expert-parallel MoEMLP

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x + Attention(
            self.n_heads, self.d_model, self.dtype, self.causal,
            self.mesh, self.sequence_axis, name="attention",
        )(nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x))
        if self.n_experts > 0:
            mlp = MoEMLP(
                self.n_experts, self.d_ff, self.d_model, self.dtype,
                mesh=self.mesh, name="moe",
            )
        else:
            mlp = MLPBlock(self.d_ff, self.d_model, self.dtype, name="mlp")
        x = x + mlp(nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x))
        return x


class TransformerLM(nn.Module):
    """GPT-style causal LM over token ids ``[batch, seq] -> [batch, seq, vocab]``."""

    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    d_ff: int = 2048
    dtype: Any = jnp.float32
    remat: bool = False
    mesh: Optional[Mesh] = None
    sequence_axis: Optional[str] = None
    n_experts: int = 0  # >0: MoE MLPs in every `moe_every`-th block
    moe_every: int = 2

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        x = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.dtype, name="embed"
        )(tokens)
        block = TransformerBlock
        if self.remat:
            block = nn.remat(TransformerBlock)
        for i in range(self.n_layers):
            # GShard-style interleaving: every `moe_every`-th block is MoE.
            moe = self.n_experts if (i + 1) % self.moe_every == 0 else 0
            x = block(
                self.n_heads, self.d_model, self.d_ff, self.dtype,
                True, self.mesh, self.sequence_axis, moe, name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        # Logits in float32 for a numerically stable softmax-cross-entropy.
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(x)
