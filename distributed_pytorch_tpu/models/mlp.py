"""Configurable MLP — the "slightly real" model tier between the toy Linear
regressor and ResNet-50. No reference analog (the reference jumps straight from
``Linear(20,1)`` to torchvision ResNet-50 at ``multigpu_profile.py:13-27``);
this fills the gap for scaling/benchmark sweeps.
"""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Dense -> relu stack with a linear head."""

    hidden: Sequence[int] = (256, 256)
    features: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, width in enumerate(self.hidden):
            x = nn.Dense(width, name=f"hidden_{i}")(x)
            x = nn.relu(x)
        return nn.Dense(self.features, name="head")(x)
