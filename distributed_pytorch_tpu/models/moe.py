"""Mixture-of-Experts MLP with expert parallelism over the mesh's ``expert``
axis.

No reference analog (SURVEY.md §2b: EP absent from the reference) — this is a
beyond-parity capability, built the TPU way:

* **Routing** is deterministic top-1 (Switch-Transformer) or top-2
  (GShard-style, ``router_top_k=2``): the router picks the k best experts
  per token, gates renormalized over the kept choices; each expert
  processes at most ``capacity = ceil(k * tokens_per_group *
  capacity_factor / n_experts)`` tokens per group (group = one batch row),
  secondary assignments queue behind primaries for slots; overflow tokens
  fall through the residual connection (their MoE output is zero).
* **Dispatch/combine are einsums** against a one-hot ``[B, T, E, C]`` tensor —
  dense, static-shaped, MXU-friendly; no gather/scatter, no dynamic shapes,
  exactly what XLA tiles well.
* **Expert parallelism is a sharding annotation**: the stacked expert kernels
  ``[E, d_model, d_ff]`` carry ``P("expert", ...)`` specs
  (:data:`MOE_EP_RULES`), and the dispatched activations ``[E, B, C, M]`` are
  constrained to ``P("expert", "data")`` — XLA inserts the token all-to-all
  (data-sharded tokens -> expert-sharded slots) and back, riding ICI, the
  same role NCCL all-to-all plays in GPU MoE stacks.
* The **load-balance auxiliary loss** (mean expert load x mean router prob,
  scaled by ``aux_weight``) is sown into the ``"losses"`` collection; the
  train step adds every term in that collection to the task loss.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.parallel.partitioning import Rules

#: Expert-parallel specs for :class:`MoEMLP` params (stacked over dim 0 = E).
#: Compose with ``TRANSFORMER_TP_RULES`` for the dense layers: EP rules first,
#: first match wins.
MOE_EP_RULES: Rules = (
    (r".*/moe/up_kernel$", P("expert", None, None)),
    (r".*/moe/up_bias$", P("expert", None)),
    (r".*/moe/down_kernel$", P("expert", None, None)),
    (r".*/moe/down_bias$", P("expert", None)),
    (r".*/moe/router/kernel$", P()),
    (r".*/moe/router/bias$", P()),
)


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense transformer MLP block.

    ``[B, T, d_model] -> [B, T, d_model]`` with top-1 (Switch) or top-2
    (GShard-style, ``router_top_k=2``) routing over ``n_experts`` expert
    MLPs of width ``d_ff``.
    """

    n_experts: int
    d_ff: int
    d_model: int
    dtype: Any = jnp.float32
    # 1 = Switch top-1; 2 = GShard-style deterministic top-2 (gates
    # renormalized over the two chosen experts, primary assignments take
    # capacity slots before secondaries).
    router_top_k: int = 1
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2
    mesh: Optional[Mesh] = None
    expert_axis: Optional[str] = "expert"
    data_axis: Optional[str] = "data"

    def _constrain(self, x: jnp.ndarray) -> jnp.ndarray:
        """Pin dispatched activations [E, B, C, ...] to expert x data sharding
        so XLA materializes the all-to-all at this seam."""
        if self.mesh is None:
            return x
        e_ax = self.expert_axis if self.expert_axis in self.mesh.shape else None
        d_ax = self.data_axis if self.data_axis in self.mesh.shape else None
        if e_ax is None and d_ax is None:
            return x
        spec = P(e_ax, d_ax, *([None] * (x.ndim - 2)))
        return jax.lax.with_sharding_constraint(x, NamedSharding(self.mesh, spec))

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        n_batch, n_tokens, d_model = x.shape
        n_exp = self.n_experts
        k = self.router_top_k
        if k not in (1, 2):
            raise ValueError(f"router_top_k must be 1 or 2, got {k}")
        if k > n_exp:
            # With the primary masked out, a second choice doesn't exist:
            # argmax over all-zero probs would silently re-pick the primary
            # at half weight.
            raise ValueError(
                f"router_top_k={k} needs at least {k} experts, got {n_exp}"
            )
        capacity = max(
            1, math.ceil(k * n_tokens * self.capacity_factor / n_exp)
        )

        # --- route: deterministic top-k per token ------------------------
        router_logits = nn.Dense(n_exp, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32)
        )
        probs = jax.nn.softmax(router_logits, axis=-1)  # [B, T, E]
        idx1 = jnp.argmax(probs, axis=-1)  # [B, T]
        oh1 = jax.nn.one_hot(idx1, n_exp, dtype=jnp.float32)

        # Load-balance aux loss (Switch eq. 4 over the PRIMARY assignment):
        # E * mean_load . mean_prob.
        load = jnp.mean(oh1, axis=(0, 1))  # fraction routed per expert
        importance = jnp.mean(probs, axis=(0, 1))  # mean router prob
        aux = n_exp * jnp.sum(load * importance)
        self.sow("losses", "moe_aux", self.aux_weight * aux)

        def slots(position, keep):
            # [B, T, E, C] one-hot over capacity slots; position is 0 for
            # unrouted (token, expert) pairs -> index -1 -> all-zero row,
            # exactly the "no slot" encoding we want.
            return jax.nn.one_hot(
                position.astype(jnp.int32) - 1, capacity, dtype=jnp.float32
            ) * jnp.where(keep, 1.0, 0.0)[..., None]

        # Primary choice: position within each expert's capacity (1-based).
        pos1 = jnp.cumsum(oh1, axis=1) * oh1  # [B, T, E]
        keep1 = (pos1 > 0) & (pos1 <= capacity)
        disp1 = slots(pos1, keep1)
        gate1 = jnp.sum(probs * oh1, axis=-1)  # [B, T]

        if k == 2:
            # Secondary = best expert with the primary masked out; its
            # tokens queue BEHIND every primary assignment of that expert
            # (GShard priority), sharing one capacity budget.
            probs2 = probs * (1.0 - oh1)
            idx2 = jnp.argmax(probs2, axis=-1)
            oh2 = jax.nn.one_hot(idx2, n_exp, dtype=jnp.float32)
            count1 = jnp.sum(oh1, axis=1, keepdims=True)  # [B, 1, E]
            pos2 = (jnp.cumsum(oh2, axis=1) + count1) * oh2
            keep2 = (pos2 > 0) & (pos2 <= capacity)
            disp2 = slots(pos2, keep2)
            gate2 = jnp.sum(probs * oh2, axis=-1)
            # Renormalize over the two chosen experts, then zero dropped
            # assignments (kept one keeps its renormalized share).
            denom = gate1 + gate2 + 1e-9
            g1 = gate1 / denom
            g2 = gate2 / denom
            dispatch_t = disp1 + disp2  # disjoint slots by construction
            combine_t = disp1 * g1[..., None, None] + disp2 * g2[..., None, None]
        else:
            dispatch_t = disp1
            combine_t = disp1 * gate1[..., None, None]

        # --- dispatch -> experts -> combine ------------------------------
        w_up = self.param(
            "up_kernel",
            nn.initializers.lecun_normal(),
            (n_exp, d_model, self.d_ff),
        )
        b_up = self.param("up_bias", nn.initializers.zeros, (n_exp, self.d_ff))
        w_down = self.param(
            "down_kernel",
            nn.initializers.lecun_normal(),
            (n_exp, self.d_ff, d_model),
        )
        b_down = self.param("down_bias", nn.initializers.zeros, (n_exp, d_model))

        compute = self.dtype
        # Tokens -> expert slots: the EP all-to-all happens here.
        expert_in = jnp.einsum(
            "btec,btm->ebcm", dispatch_t.astype(compute), x.astype(compute)
        )
        expert_in = self._constrain(expert_in)
        h = jnp.einsum("ebcm,emf->ebcf", expert_in, w_up.astype(compute))
        h = nn.gelu(h + b_up[:, None, None, :].astype(compute))
        out = jnp.einsum("ebcf,efm->ebcm", h, w_down.astype(compute))
        out = out + b_down[:, None, None, :].astype(compute)
        out = self._constrain(out)
        # Expert slots -> tokens: the reverse all-to-all.
        y = jnp.einsum("btec,ebcm->btm", combine_t.astype(compute), out)
        return y.astype(x.dtype)
