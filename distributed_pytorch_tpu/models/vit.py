"""Vision Transformer — parity with the reference's alternative real model.

The reference offers torchvision ``vit_l_32`` (306M params) as a drop-in for
ResNet-50, left commented out at ``multigpu_profile.py:24``. This is that
model family TPU-first: NHWC patches, bfloat16 compute option, and the same
pluggable attention stack as :class:`TransformerLM` (non-causal here).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_pytorch_tpu.models.transformer import TransformerBlock


class ViT(nn.Module):
    """Vision Transformer over NHWC images ``[batch, H, W, 3]``."""

    patch_size: int = 32
    d_model: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    d_ff: int = 4096
    num_classes: int = 1000
    image_size: int = 224
    dtype: Any = jnp.float32
    remat: bool = False

    @nn.compact
    def __call__(self, images: jnp.ndarray) -> jnp.ndarray:
        p = self.patch_size
        # Patchify = non-overlapping conv; one big MXU matmul per image.
        x = nn.Conv(
            self.d_model, (p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, name="patch_embed",
        )(images.astype(self.dtype))
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, self.d_model), jnp.float32
        )
        x = jnp.concatenate([jnp.tile(cls.astype(x.dtype), (b, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, x.shape[1], self.d_model),
            jnp.float32,
        )
        x = x + pos.astype(x.dtype)

        block = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        for i in range(self.n_layers):
            x = block(
                self.n_heads, self.d_model, self.d_ff, self.dtype,
                causal=False, name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x[:, 0]
        )


# torchvision vit_l_32 twin (306M params, the configuration named at
# multigpu_profile.py:24).
ViT_L32 = partial(
    ViT, patch_size=32, d_model=1024, n_layers=24, n_heads=16, d_ff=4096
)
