"""Backend/platform helpers.

``use_fake_cpu_devices(n)`` presents ``n`` virtual CPU devices in this process
— the framework's stand-in for a multi-chip test rig (SURVEY.md §4): it lets
every DP/mesh code path run on a laptop or CI box with no TPU attached. Must be
called before the first JAX backend touch (any ``jax.devices()`` /
computation). Works even when a platform plugin overrides ``JAX_PLATFORMS``.
"""

from __future__ import annotations

import os


def use_fake_cpu_devices(n: int = 8) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")


def on_tpu() -> bool:
    """True when the default backend compiles for TPU (the predicate the
    Pallas kernels key on — same check as ops/flash_attention.py)."""
    import jax

    return jax.default_backend() == "tpu"
