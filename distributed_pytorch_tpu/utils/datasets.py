"""Real datasets: CIFAR-10 loading, normalization, and a learnable stand-in.

The reference's "real model" rung swaps torchvision models onto its synthetic
loader (`/root/reference/multigpu_profile.py:13-27`); BASELINE.json's
configs[4] names **ResNet-18 / CIFAR-10** as the real-data workload. This
module supplies the data half:

* :func:`load_cifar10` — loads the standard ``cifar-10-batches-py`` pickle
  layout (or a previously converted ``cifar10.npz`` cache, which it writes on
  first load) from ``data_dir``. It never downloads: on a connected machine,
  fetch https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz and extract
  into ``data_dir`` once; air-gapped rigs ship the ``.npz``.
* :func:`synthetic_cifar10` — a deterministic, *learnable* 10-class stand-in
  with CIFAR-10's exact shapes/dtypes for machines with no dataset and no
  network (this CI rig): low-contrast class templates under heavy Gaussian
  noise, tuned to a KNOWN ~6.5% Bayes error (the nearest-template rule is
  Bayes-optimal here; :func:`synthetic_oracle_accuracy` computes the
  ceiling). A model must learn over multiple epochs to approach the oracle,
  so the rung exercises the full recipe (normalize -> augment -> SGD
  schedule -> exact eval accuracy), not just shape plumbing; it is clearly
  labeled and never silently substituted (``cifar10_or_synthetic`` prints
  which one ran).
* :func:`normalize_images` — uint8 HWC -> float32 NHWC with per-channel
  standardization (the torchvision ``transforms.Normalize`` twin).
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Tuple

import numpy as np

from distributed_pytorch_tpu.utils.data import ArrayDataset

# Canonical CIFAR-10 per-channel statistics (training split, [0,1] scale).
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]


def normalize_images(
    images: np.ndarray,
    mean: np.ndarray = CIFAR10_MEAN,
    std: np.ndarray = CIFAR10_STD,
) -> np.ndarray:
    """uint8 ``[N, H, W, C]`` -> standardized float32 (NHWC, TPU-native)."""
    x = images.astype(np.float32) / 255.0
    return (x - mean) / std


def _load_pickle_batches(batches_dir: str) -> Arrays:
    def read(name):
        with open(os.path.join(batches_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NHWC
        y = np.asarray(d[b"labels"], np.int32)
        return x, y

    train = [read(f"data_batch_{i}") for i in range(1, 6)]
    x_train = np.concatenate([x for x, _ in train])
    y_train = np.concatenate([y for _, y in train])
    x_test, y_test = read("test_batch")
    return x_train, y_train, x_test, y_test


def load_cifar10(data_dir: str = "data") -> Arrays:
    """``(x_train u8 [50000,32,32,3], y_train i32, x_test u8 [10000,...], y_test)``.

    Resolution order: ``cifar10.npz`` cache -> ``cifar-10-batches-py/`` pickles
    -> ``cifar-10-python.tar.gz`` (extracted in place) -> FileNotFoundError
    with fetch instructions. The ``.npz`` cache is written after a pickle load
    so subsequent startups are one mmap'd read.
    """
    npz = os.path.join(data_dir, "cifar10.npz")
    if os.path.exists(npz):
        with np.load(npz) as d:
            return d["x_train"], d["y_train"], d["x_test"], d["y_test"]

    tar = os.path.join(data_dir, "cifar-10-python.tar.gz")
    batches = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(batches) and os.path.exists(tar):
        with tarfile.open(tar) as tf:
            tf.extractall(data_dir)
    if os.path.isdir(batches):
        arrays = _load_pickle_batches(batches)
        np.savez_compressed(
            npz,
            x_train=arrays[0], y_train=arrays[1],
            x_test=arrays[2], y_test=arrays[3],
        )
        return arrays

    raise FileNotFoundError(
        f"CIFAR-10 not found under {data_dir!r}. Fetch "
        "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz into that "
        "directory (no auto-download: many TPU rigs are air-gapped), or use "
        "synthetic_cifar10() / cifar10_or_synthetic()."
    )


def _lowpass(patterns: np.ndarray, scale: float) -> np.ndarray:
    """Gaussian low-pass over the two spatial axes (periodic, via FFT),
    renormalized to unit per-pixel std so ``contrast`` keeps its meaning."""
    h, w = patterns.shape[1], patterns.shape[2]
    fy = np.fft.fftfreq(h)[:, None]
    fx = np.fft.fftfreq(w)[None, :]
    # Transfer function of a Gaussian blur with sigma=scale pixels.
    g = np.exp(-2.0 * (np.pi * scale) ** 2 * (fy**2 + fx**2))
    smooth = np.fft.ifft2(
        np.fft.fft2(patterns, axes=(1, 2)) * g[None, :, :, None], axes=(1, 2)
    ).real.astype(np.float32)
    return smooth / smooth.std(axis=(1, 2, 3), keepdims=True)


def _synthetic_templates(
    seed: int, contrast: float, smooth_frac: float = 0.0,
    smooth_scale: float = 6.0,
) -> np.ndarray:
    """The 10 class templates: mid-gray plus a +-``contrast`` gray-level
    pattern. Deterministic per seed; shared by the generator and the
    Bayes-oracle classifier.

    ``smooth_frac`` puts that fraction of each pattern's variance into a
    LOW-FREQUENCY (Gaussian-blurred at ``smooth_scale`` px) component and
    the rest into the original spatially-white one. Why this knob exists
    (round-5 finding): with fully white templates the Bayes rule is a
    POSITION-SPECIFIC matched filter, which a weight-shared conv stack
    crowned by global average pooling cannot express — local patch
    statistics are class-independent, so ResNet-18 sits at chance while a
    per-pixel linear probe reaches the oracle band. Real images are
    low-frequency dominated, so a partly-smooth template is the more
    faithful CIFAR stand-in AND gives convolutional features something
    expressible to pool, while the white remainder keeps the task
    multi-epoch for linear learners (tests/test_datasets.py)."""
    if not 0.0 <= smooth_frac <= 1.0:
        raise ValueError(
            f"smooth_frac must be in [0, 1], got {smooth_frac}"
        )
    rng = np.random.default_rng(seed)
    patterns = rng.standard_normal((10, 32, 32, 3)).astype(np.float32)
    if smooth_frac:
        # Independent white field for the smooth part so the two
        # components are uncorrelated; unit-variance mix.
        smooth = _lowpass(
            rng.standard_normal((10, 32, 32, 3)).astype(np.float32),
            smooth_scale,
        )
        patterns = (
            np.sqrt(1.0 - smooth_frac) * patterns
            + np.sqrt(smooth_frac) * smooth
        )
    return 128.0 + contrast * patterns


def _synthetic_template_components(
    seed: int, contrast: float, smooth_frac: float, smooth_scale: float = 6.0
) -> Tuple[np.ndarray, np.ndarray]:
    """(full, smooth_only) template pairs on the 128-gray base — the smooth
    member is the conv-expressible part of the signal (see
    :func:`_synthetic_templates`); exposed so analyses/tests never have to
    replicate the generator's private RNG draw order."""
    full = _synthetic_templates(seed, contrast, smooth_frac, smooth_scale)
    white_only = _synthetic_templates(seed, contrast, 0.0, smooth_scale)
    # full = 128 + c*(sqrt(1-f)*white + sqrt(f)*smooth); white_only = 128 + c*white
    smooth_only = (
        128.0
        + (full - 128.0)
        - np.sqrt(1.0 - smooth_frac) * (white_only - 128.0)
    )
    return full, smooth_only


def synthetic_cifar10(
    n_train: int = 50000,
    n_test: int = 10000,
    seed: int = 0,
    noise: float = 0.35,
    contrast: float = 2.6,
    smooth_frac: float = 0.0,
    smooth_scale: float = 6.0,
) -> Arrays:
    """Deterministic learnable 10-class dataset with CIFAR-10 shapes/dtypes
    and a KNOWN, non-trivial Bayes error.

    Class ``c``'s images are ``template_c + noise``: templates sit at
    mid-gray +- a ``contrast``-gray-level pattern (~2.6 levels by default)
    under per-pixel Gaussian noise of sigma ``noise*128`` (~45 levels), so
    the per-pixel SNR is ~0.06 — single pixels carry almost nothing and a
    classifier must pool evidence across all 3072. Because the generative
    model is an isotropic Gaussian mixture, the nearest-template rule is
    Bayes-optimal; at the defaults its measured accuracy is ~93.5%
    (:func:`synthetic_oracle_accuracy`), i.e. ~6.5% Bayes error. That makes
    this rung test *learning*, not shape-compatibility: a model cannot hit
    the ceiling by memorizing gross pixel values in epoch 1 (the round-3
    stand-in's failure mode — full-contrast templates reached accuracy
    1.0000 immediately), and its eval accuracy converging into the oracle
    band over epochs is a meaningful end-to-end recipe signal. No real-data
    claim is implied. Clipping DOES touch the noise tails (+-3 sigma is
    ~134 levels around mid-gray, so roughly 0.1-0.3% of pixels rail per
    tail) and uint8 rounding quantizes the rest; both effects are
    empirically negligible — nearest-template is exactly Bayes-optimal only
    for the unclipped mixture, and the quoted ~93.5% oracle accuracy is the
    MEASURED value on the clipped data, not a Gaussian-theory number.
    """
    templates = _synthetic_templates(seed, contrast, smooth_frac, smooth_scale)

    def split(n, seed_offset):
        r = np.random.default_rng([seed, seed_offset])
        y = r.integers(0, 10, size=n).astype(np.int32)
        x = templates[y] + r.normal(0.0, noise * 128.0, size=(n, 32, 32, 3))
        return np.clip(x, 0, 255).astype(np.uint8), y

    x_train, y_train = split(n_train, 1)
    x_test, y_test = split(n_test, 2)
    return x_train, y_train, x_test, y_test


def synthetic_oracle_accuracy(
    x: np.ndarray,
    y: np.ndarray,
    seed: int = 0,
    contrast: float = 2.6,
    batch: int = 2048,
    smooth_frac: float = 0.0,
    smooth_scale: float = 6.0,
) -> float:
    """Accuracy of the Bayes-optimal (nearest-template) classifier on
    synthetic data produced by :func:`synthetic_cifar10` with the same
    template parameters — the ceiling any trained model is converging
    toward. Computed in batches so 50k images stay cheap."""
    templates = _synthetic_templates(
        seed, contrast, smooth_frac, smooth_scale
    ).reshape(10, -1)
    correct = 0
    for i in range(0, len(x), batch):
        xb = x[i : i + batch].astype(np.float32).reshape(-1, templates.shape[1])
        d = (
            (xb**2).sum(1, keepdims=True)
            - 2.0 * xb @ templates.T
            + (templates**2).sum(1)[None, :]
        )
        correct += int((d.argmin(1) == y[i : i + batch]).sum())
    return correct / len(x)


def cifar10_or_synthetic(data_dir: str = "data", **synth_kw):
    """``(arrays, is_real)`` — real CIFAR-10 when present, else the synthetic
    stand-in, with a printed notice so runs are never silently synthetic."""
    try:
        arrays = load_cifar10(data_dir)
        print(f"[datasets] real CIFAR-10 loaded from {data_dir}", flush=True)
        return arrays, True
    except FileNotFoundError:
        print(
            "[datasets] CIFAR-10 not on disk and this rig has no egress -> "
            "using the synthetic learnable stand-in (shapes/dtypes identical; "
            "accuracy numbers are NOT real-CIFAR numbers)",
            flush=True,
        )
        return synthetic_cifar10(**synth_kw), False


def as_datasets(arrays: Arrays) -> Tuple[ArrayDataset, ArrayDataset]:
    """Normalized train/test :class:`ArrayDataset` pair from raw arrays."""
    x_train, y_train, x_test, y_test = arrays
    return (
        ArrayDataset(normalize_images(x_train), y_train),
        ArrayDataset(normalize_images(x_test), y_test),
    )


class AugmentedDataset:
    """Standard small-image train augmentation: pad-and-random-crop + random
    horizontal flip (the canonical CIFAR recipe), deterministic per
    ``(seed, epoch, index)`` so loss curves are reproducible and multi-process
    shards agree — the reference's ``DataLoader`` transforms are stochastic;
    here determinism is what makes serial == DP parity testable.

    Wraps any dataset of NHWC float32 rows. ``ShardedLoader.set_epoch``
    forwards the epoch so every epoch sees fresh crops/flips.
    """

    def __init__(self, base, pad: int = 4, seed: int = 0):
        self.base = base
        self.pad = pad
        self.seed = seed
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        return len(self.base)

    def __getitem__(self, index: int):
        x, y = self.base[index]
        rng = np.random.default_rng([self.seed, self._epoch, index])
        h, w = x.shape[0], x.shape[1]
        # Zero padding, matching the canonical CIFAR recipe (He et al.);
        # on normalized inputs zero is the per-channel dataset mean.
        padded = np.pad(
            x, ((self.pad, self.pad), (self.pad, self.pad), (0, 0))
        )
        top = rng.integers(0, 2 * self.pad + 1)
        left = rng.integers(0, 2 * self.pad + 1)
        x = padded[top : top + h, left : left + w]
        if rng.integers(0, 2):
            x = x[:, ::-1]
        return np.ascontiguousarray(x), y
