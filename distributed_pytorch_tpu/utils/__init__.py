from distributed_pytorch_tpu.utils.data import (
    ArrayDataset,
    MaterializedDataset,
    NativeShardedLoader,
    RandomDataset,
    ShardedLoader,
)
from distributed_pytorch_tpu.utils.datasets import (
    AugmentedDataset,
    cifar10_or_synthetic,
    load_cifar10,
    normalize_images,
    synthetic_cifar10,
    synthetic_oracle_accuracy,
)
from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices

__all__ = [
    "ArrayDataset",
    "AugmentedDataset",
    "MaterializedDataset",
    "NativeShardedLoader",
    "RandomDataset",
    "ShardedLoader",
    "cifar10_or_synthetic",
    "load_cifar10",
    "normalize_images",
    "synthetic_cifar10",
    "synthetic_oracle_accuracy",
    "use_fake_cpu_devices",
]
