from distributed_pytorch_tpu.utils.data import (
    ArrayDataset,
    MaterializedDataset,
    NativeShardedLoader,
    RandomDataset,
    ShardedLoader,
)
from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices

__all__ = [
    "ArrayDataset",
    "MaterializedDataset",
    "NativeShardedLoader",
    "RandomDataset",
    "ShardedLoader",
    "use_fake_cpu_devices",
]
