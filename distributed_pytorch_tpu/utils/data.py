"""Host-side synthetic datasets and the sharded batch loader.

TPU-native replacement for the reference's data sublayer:

* :class:`MaterializedDataset` — eagerly materialized ``(input, target)`` pairs,
  capability twin of ``MyTrainDataset`` (reference ``utils.py:4-13``).
* :class:`RandomDataset` — lazy per-index random samples, capability twin of
  ``MyRandomDataset`` (reference ``utils.py:16-26``).
* :class:`ShardedLoader` — batching + shuffling + per-process disjoint sharding,
  replacing ``DataLoader(..., sampler=DistributedSampler(...))`` (reference
  ``multigpu.py:72-79``). Shard semantics mirror ``DistributedSampler``: the
  index list is padded *by wrapping around* so every shard sees the same number
  of samples, and shards are strided (``indices[shard_index::num_shards]``) so
  they are pairwise disjoint before padding.

Data stays in numpy on the host; device placement (with sharding) happens in the
Trainer so that the loader is backend-agnostic and cheap to test.
"""

from __future__ import annotations

import math
from typing import Iterator, Sequence, Tuple

import numpy as np

Batch = Tuple[np.ndarray, np.ndarray]


class MaterializedDataset:
    """Eagerly materialized random regression dataset.

    Twin of ``MyTrainDataset`` (reference ``utils.py:4-13``): ``size`` pairs of
    ``(input_dim,)`` inputs and ``(target_dim,)`` targets, generated once at
    construction. Deterministic given ``seed``.
    """

    def __init__(self, size: int, input_dim: int = 20, target_dim: int = 1, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.inputs = rng.standard_normal((size, input_dim)).astype(np.float32)
        self.targets = rng.standard_normal((size, target_dim)).astype(np.float32)

    def __len__(self) -> int:
        return self.inputs.shape[0]

    def __getitem__(self, index: int) -> Batch:
        return self.inputs[index], self.targets[index]


class ArrayDataset:
    """Materialized dataset over caller-provided arrays.

    The general form of :class:`MaterializedDataset` (any shapes/dtypes):
    exposes C-contiguous ``inputs``/``targets``, so it feeds both the Python
    :class:`ShardedLoader` and the C++-backed :class:`NativeShardedLoader`.
    Used for real data (e.g. CIFAR-10) and materialized benchmark workloads.
    """

    def __init__(self, inputs: np.ndarray, targets: np.ndarray):
        if len(inputs) != len(targets):
            raise ValueError(
                f"inputs ({len(inputs)}) and targets ({len(targets)}) disagree"
            )
        self.inputs = np.ascontiguousarray(inputs)
        self.targets = np.ascontiguousarray(targets)

    def __len__(self) -> int:
        return self.inputs.shape[0]

    def __getitem__(self, index: int) -> Batch:
        return self.inputs[index], self.targets[index]


class RandomDataset:
    """Lazy random dataset: every ``__getitem__`` generates its sample on demand.

    Twin of ``MyRandomDataset`` (reference ``utils.py:16-26``). Unlike the
    reference (fresh ``torch.rand`` every access), samples here are
    *deterministic per index* so that loss curves are reproducible and the
    serial-vs-data-parallel parity tests are meaningful.

    ``num_classes`` > 0 yields integer class targets (for classification models
    like ResNet-50); otherwise targets are dense random vectors of
    ``target_shape`` (the reference default ``(1000,)``).
    """

    def __init__(
        self,
        size: int,
        input_shape: Sequence[int],
        target_shape: Sequence[int] = (1000,),
        seed: int = 0,
        num_classes: int = 0,
    ):
        self.size = size
        self.input_shape = tuple(input_shape)
        self.target_shape = tuple(target_shape)
        self.seed = seed
        self.num_classes = num_classes

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, index: int) -> Batch:
        if not 0 <= index < self.size:
            raise IndexError(index)
        rng = np.random.default_rng([self.seed, index])
        x = rng.standard_normal(self.input_shape).astype(np.float32)
        if self.num_classes:
            y = rng.integers(0, self.num_classes, size=(), dtype=np.int32)
            return x, np.asarray(y)
        y = rng.standard_normal(self.target_shape).astype(np.float32)
        return x, y


class ShardedLoader:
    """Batched, optionally shuffled, per-process-sharded iterator over a dataset.

    Replaces the reference's ``DataLoader`` + ``DistributedSampler`` pair
    (``multigpu.py:72-79``) with ``DistributedSampler``-compatible semantics:

    * total indices are padded by wrapping (repeat from the start) up to a
      multiple of ``num_shards`` so all shards are equal length;
    * shard ``i`` takes ``indices[i::num_shards]`` — disjoint before padding;
    * ``set_epoch(e)`` reseeds the shuffle so every epoch (and every shard)
      agrees on one global permutation, mirroring ``sampler.set_epoch``.

    Ragged final batches and XLA: a batch whose leading dim changes forces a
    recompile, and one that is not divisible by the mesh's data axis cannot be
    placed with ``P("data")`` at all. Two remedies:

    * ``drop_last=True`` drops the ragged final batch;
    * ``pad_final_batch=True`` wraps the final batch around to full
      ``batch_size`` (the same pad-by-repeat semantic DistributedSampler
      applies across ranks). The Trainer auto-enables this when running on a
      mesh.

    With the reference's divisible defaults (2048 samples / batch 32) neither
    changes anything.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        shuffle: bool = False,
        num_shards: int = 1,
        shard_index: int = 0,
        seed: int = 0,
        drop_last: bool = False,
        pad_final_batch: bool = False,
    ):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.seed = seed
        self.drop_last = drop_last
        self.pad_final_batch = pad_final_batch
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        """Reseed shuffling for ``epoch`` (twin of ``DistributedSampler.set_epoch``);
        forwarded to the dataset when it is epoch-aware (e.g.
        ``AugmentedDataset``: fresh deterministic crops/flips per epoch)."""
        self._epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def shard_indices(self) -> np.ndarray:
        """The (padded, strided) global indices owned by this shard, this epoch."""
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng([self.seed, self._epoch]).permutation(n)
        else:
            order = np.arange(n)
        padded_total = math.ceil(n / self.num_shards) * self.num_shards
        if padded_total > n:
            order = np.concatenate([order, order[: padded_total - n]])
        return order[self.shard_index :: self.num_shards]

    def __len__(self) -> int:
        per_shard = math.ceil(len(self.dataset) / self.num_shards)
        if self.drop_last:
            return per_shard // self.batch_size
        return math.ceil(per_shard / self.batch_size)

    def batch_index_table(self) -> "list[np.ndarray]":
        """This epoch's batches as a list of per-batch index rows (the final
        batch is wrap-padded to full size when ``pad_final_batch`` is set,
        otherwise it may be short)."""
        indices = self.shard_indices()
        rows = []
        for b in range(len(self)):
            chunk = indices[b * self.batch_size : (b + 1) * self.batch_size]
            if self.pad_final_batch and len(chunk) < self.batch_size:
                # np.resize repeats cyclically, so this wraps even when the
                # whole shard is smaller than one batch.
                chunk = np.concatenate(
                    [chunk, np.resize(indices, self.batch_size - len(chunk))]
                )
            rows.append(chunk)
        return rows

    def batch_weight_table(self) -> "list[np.ndarray]":
        """Per-batch sample weights (1.0 real / 0.0 wrap-pad duplicate),
        aligned row-for-row with :meth:`batch_index_table`.

        Two padding layers can duplicate samples: shard-level (the global
        index list is wrapped so every shard has equal length — this shard's
        row ``j`` came from padded-order position ``shard_index +
        j * num_shards``, a duplicate iff that position >= dataset size) and
        batch-level (``pad_final_batch`` wraps the final batch to full size).
        Weighting both kinds to zero makes weighted eval sums EXACT
        distinct-sample statistics on any dataset/mesh shape (the training
        path deliberately keeps DistributedSampler's pad-by-repeat mean)."""
        n = len(self.dataset)
        per_shard = math.ceil(n / self.num_shards)
        positions = self.shard_index + np.arange(per_shard) * self.num_shards
        real = (positions < n).astype(np.float32)
        rows = []
        for b in range(len(self)):
            chunk = real[b * self.batch_size : (b + 1) * self.batch_size]
            if self.pad_final_batch and len(chunk) < self.batch_size:
                chunk = np.concatenate(
                    [chunk, np.zeros(self.batch_size - len(chunk), np.float32)]
                )
            rows.append(chunk)
        return rows

    def order_state(self) -> dict:
        """The parameters that determine this epoch's batch order — the
        resumable-iteration contract behind mid-epoch (drain) snapshots. A
        resumed process whose loader :meth:`matches_order_state` will, after
        ``set_epoch(epoch)``, yield the IDENTICAL batch sequence, so
        ``iter_batches(start_batch=k)`` continues exactly where a drained
        run stopped."""
        return {
            "seed": int(self.seed),
            "shuffle": bool(self.shuffle),
            "num_shards": int(self.num_shards),
            "batch_size": int(self.batch_size),
            "dataset_size": int(len(self.dataset)),
        }

    def matches_order_state(self, state) -> bool:
        """True iff a saved :meth:`order_state` describes this loader's batch
        order (same sharding geometry, seed, and dataset) — i.e. a mid-epoch
        ``start_batch`` recorded under that state is still meaningful here.
        False after e.g. an elastic scale-down changed ``num_shards``: the
        caller must replay the epoch from batch 0 instead."""
        return isinstance(state, dict) and state == self.order_state()

    def iter_batches(self, start_batch: int = 0) -> Iterator[Batch]:
        """Iterate this epoch's batches, optionally skipping the first
        ``start_batch`` of them (mid-epoch resume: batches already applied to
        the restored state before a drain snapshot must not be replayed)."""
        for chunk in self.batch_index_table()[start_batch:]:
            samples = [self.dataset[int(i)] for i in chunk]
            xs = np.stack([s[0] for s in samples])
            ys = np.stack([s[1] for s in samples])
            yield xs, ys

    def __iter__(self) -> Iterator[Batch]:
        return self.iter_batches()


class NativeShardedLoader(ShardedLoader):
    """ShardedLoader whose batch assembly runs in the C++ prefetch worker pool
    (``native/prefetch.cpp``) — the torch ``DataLoader(num_workers=...,
    pin_memory=True)`` twin (reference ``multigpu.py:72-79``): batches are
    gathered by GIL-free background threads into a bounded ring while the
    training loop consumes, so host batch assembly overlaps device compute.

    Requires a dataset exposing C-contiguous ``inputs``/``targets`` arrays
    (:class:`MaterializedDataset`). Batch order and contents are IDENTICAL to
    the Python loader (same index table); only who does the copying changes.

    When it wins, measured (tools/loader_overlap_bench.py, BASELINE.md round
    3): at SMALL rows the Python loader's per-item overhead dominates and the
    pool assembles ~1.4x faster; at large rows (e.g. 224x224x3 images, where
    one ``np.stack`` is a single fused memcpy) the pool's safe-ownership
    design costs a second copy (worker gather -> ring slot, slot -> caller
    array) and pure assembly is SLOWER than the Python loader — its value
    there is only overlap with *device* compute, which a core-shared
    CPU-backend rig cannot show (measured ~1.0x end to end). Zero-copy slot
    views were considered and rejected: jax's CPU ``device_put`` may alias
    numpy buffers, so recycling slot memory under a live view corrupts data.
    """

    def __init__(self, *args, num_workers: int = 2, prefetch_depth: int = 4, **kw):
        super().__init__(*args, **kw)
        if not (
            hasattr(self.dataset, "inputs") and hasattr(self.dataset, "targets")
        ):
            raise TypeError(
                "NativeShardedLoader needs a materialized dataset with "
                ".inputs/.targets arrays"
            )
        self.num_workers = num_workers
        self.prefetch_depth = prefetch_depth
        self._x = np.ascontiguousarray(self.dataset.inputs)
        self._y = np.ascontiguousarray(self.dataset.targets)
        # The pool gathers rows straight from .inputs/.targets, bypassing
        # __getitem__. A dataset whose __getitem__ applies a transform would
        # pass the attribute check above yet yield different batches than
        # ShardedLoader — probe one sample to keep the "identical contents"
        # contract honest.
        if len(self.dataset):
            x0, y0 = self.dataset[0]

            def same(a, b):
                try:
                    # equal_nan: a stored NaN (masked feature) must not read
                    # as "__getitem__ transformed the data".
                    return np.array_equal(np.asarray(a), b, equal_nan=True)
                except TypeError:  # non-float dtype rejects equal_nan
                    return np.array_equal(np.asarray(a), b)

            if not (same(x0, self._x[0]) and same(y0, self._y[0])):
                raise TypeError(
                    "NativeShardedLoader requires dataset[i] == "
                    "(dataset.inputs[i], dataset.targets[i]); this dataset's "
                    "__getitem__ transforms the stored arrays"
                )

    def iter_batches(self, start_batch: int = 0) -> Iterator[Batch]:
        import ctypes

        from distributed_pytorch_tpu.native import prefetch_library

        rows = self.batch_index_table()[start_batch:]
        full = [r for r in rows if len(r) == self.batch_size]
        ragged = rows[len(full):]  # at most one short final batch

        if full:
            lib = prefetch_library()
            table = np.ascontiguousarray(np.stack(full).ravel(), dtype=np.int64)
            row_x = self._x.dtype.itemsize * int(np.prod(self._x.shape[1:]))
            row_y = self._y.dtype.itemsize * int(np.prod(self._y.shape[1:]))
            handle = lib.prefetch_create(
                self._x.ctypes.data,
                self._y.ctypes.data,
                row_x,
                row_y,
                table.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
                table.size,
                self.batch_size,
                self.prefetch_depth,
                self.num_workers,
            )
            if not handle:
                raise RuntimeError("prefetch_create failed")
            try:
                shape_x = (self.batch_size,) + self._x.shape[1:]
                shape_y = (self.batch_size,) + self._y.shape[1:]
                while True:
                    xs = np.empty(shape_x, self._x.dtype)
                    ys = np.empty(shape_y, self._y.dtype)
                    if not lib.prefetch_next(handle, xs.ctypes.data, ys.ctypes.data):
                        break
                    yield xs, ys
            finally:
                lib.prefetch_destroy(handle)

        for chunk in ragged:  # rare: no drop_last/pad on an uneven tail
            yield self._x[chunk], self._y[chunk]
