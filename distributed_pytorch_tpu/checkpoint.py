"""Pytree checkpointing: plain checkpoints and elastic snapshots.

Capability twin of the reference's two-tier persistence:

* plain checkpoint — bare ``state_dict`` -> ``checkpoint.pt``, rank-0 only
  (reference ``multigpu.py:53-56,61``)  ->  :func:`save_checkpoint`;
* snapshot — ``{MODEL_STATE, EPOCHS_RUN}`` -> ``snapshot.pt`` with auto-load on
  init (reference ``multigpu_torchrun.py:36-40,57-62``)  ->
  :func:`save_snapshot` / :func:`load_snapshot`, extended to carry optimizer
  state and step count (the reference never saves optimizer state — a resume
  gap we close).

Format: a single ``.npz`` holding every leaf keyed by its flattened pytree path
plus a JSON metadata entry. Restoring requires a template pytree with the same
structure (exactly like ``load_state_dict`` requiring a constructed model).
Writes are atomic (tmp file + ``os.replace``) and, under multi-process runs,
performed by process 0 only with a cross-host barrier after the write — fixing
the reference's multi-writer shared-FS race (``multinode_torchrun.py:68``
gates on *local* rank 0, so every node wrote the same file).

Integrity & self-healing (TorchTitan-style "checkpoints you can trust"):

* every save embeds per-array checksums plus a whole-manifest checksum in the
  metadata entry; loads verify both and raise :class:`SnapshotIntegrityError`
  on any mismatch — a torn write or bit-rot is *detected*, never silently
  trained on;
* snapshot saves rotate the previous file to ``<path>.prev`` before writing,
  so there is always a one-interval-older fallback;
* :func:`load_snapshot_with_fallback` (used by the Trainer) quarantines a
  corrupt candidate as ``<path>.corrupt``, warns loudly, and falls back to
  ``.prev`` — corruption costs one save interval, never the run.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from distributed_pytorch_tpu.chaos import on_snapshot_write as _chaos_on_snapshot_write
from distributed_pytorch_tpu.parallel.bootstrap import barrier, is_main_process

_META_KEY = "__checkpoint_meta__"
_INTEGRITY_KEY = "__integrity__"

try:  # CRC32C (Castagnoli) when a native impl exists; stdlib CRC32 otherwise.
    # The manifest records which algorithm wrote it, so verification always
    # recomputes with the matching one — and no new dependency either way.
    import crc32c as _crc32c_mod

    _CRC_ALGO = "crc32c"

    def _crc(data: bytes) -> int:
        return _crc32c_mod.crc32c(data)

except ImportError:
    import zlib

    _CRC_ALGO = "crc32"

    def _crc(data: bytes) -> int:
        return zlib.crc32(data) & 0xFFFFFFFF


class SnapshotIntegrityError(RuntimeError):
    """A checkpoint failed checksum verification (torn write, truncation, or
    disk bit-rot). Callers with a fallback chain quarantine and move on."""


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _to_host(leaf) -> np.ndarray:
    """Bring a (possibly sharded) jax.Array fully to host memory.

    Replicated arrays (the DP case: params/opt_state carry ``P()``) read from
    the first addressable shard; anything else is gathered via ``jax.device_get``.
    """
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        first = leaf.addressable_shards[0]
        if first.data.shape == leaf.shape:  # fully replicated
            return np.asarray(first.data)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _gather_arrays(tree: Any, metadata: Optional[Dict]) -> Dict[str, np.ndarray]:
    """Device -> host snapshot of every leaf plus the metadata entry. Runs on
    the caller thread (may involve cross-host collectives for sharded leaves)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = {_path_str(p): _to_host(v) for p, v in flat}
    if _META_KEY in arrays:
        raise ValueError(f"reserved key {_META_KEY} present in tree")
    arrays[_META_KEY] = np.frombuffer(
        json.dumps(metadata or {}).encode("utf-8"), dtype=np.uint8
    )
    return arrays


def _atomic_write(path: str, write_fn, *, mode: str = "wb") -> None:
    """Atomic durable write: tmp file in the target dir + ``os.replace``.
    ``write_fn(file_object)`` produces the content."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _with_integrity(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Embed per-array checksums + a manifest checksum into the metadata
    entry. The manifest checksum covers the (key -> crc) map itself, so a
    corruption that hits the metadata entry is as detectable as one that
    hits array bytes."""
    meta_arr = arrays.get(_META_KEY)
    meta = (
        json.loads(bytes(meta_arr.tobytes()).decode("utf-8"))
        if meta_arr is not None
        else {}
    )
    manifest = {
        key: _crc(np.ascontiguousarray(value).tobytes())
        for key, value in arrays.items()
        if key != _META_KEY
    }
    meta[_INTEGRITY_KEY] = {
        "algo": _CRC_ALGO,
        "arrays": manifest,
        "manifest": _crc(json.dumps(manifest, sort_keys=True).encode("utf-8")),
    }
    out = dict(arrays)
    out[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8
    )
    return out


def _verify_integrity(npz, meta: Dict, *, source: str) -> None:
    """Raise :class:`SnapshotIntegrityError` if any checksum recorded at save
    time no longer matches. Files written before integrity existed (or by a
    different checksum impl) pass through unverified — npz's own zip CRCs
    still apply to them."""
    integ = meta.get(_INTEGRITY_KEY)
    if not integ or integ.get("algo") != _CRC_ALGO:
        return
    manifest = integ.get("arrays", {})
    if _crc(json.dumps(manifest, sort_keys=True).encode("utf-8")) != integ.get(
        "manifest"
    ):
        raise SnapshotIntegrityError(f"{source}: checksum manifest is corrupt")
    for key, expect in manifest.items():
        if key not in npz:
            raise SnapshotIntegrityError(
                f"{source}: array {key!r} listed in manifest is missing"
            )
        got = _crc(np.ascontiguousarray(npz[key]).tobytes())
        if got != expect:
            raise SnapshotIntegrityError(
                f"{source}: array {key!r} checksum mismatch "
                f"(expected {expect:#010x}, got {got:#010x}) — torn write "
                "or disk corruption"
            )


def _write_npz(path: str, arrays: Dict[str, np.ndarray]) -> None:
    _atomic_write(path, lambda f: np.savez(f, **_with_integrity(arrays)))
    # Chaos hook: a "corrupt the next snapshot write" fault fires here, right
    # after the file became durable — exactly where real bit-rot would land.
    _chaos_on_snapshot_write(path)


def _rotate_previous(path: str) -> None:
    """Keep the previous file as ``<path>.prev`` so a corrupt new write still
    leaves a one-interval-older snapshot to fall back to."""
    if os.path.exists(path):
        os.replace(path, path + ".prev")


def quarantine(path: str) -> Optional[str]:
    """Move a corrupt checkpoint aside as ``<path>.corrupt`` (suffixing
    ``.1``, ``.2``, ... on collision) so it can be inspected post-mortem but
    can never be loaded again. Returns the new path, or None if the file was
    already gone (another process of the same job quarantined it first)."""
    if not os.path.exists(path):
        return None
    dest = path + ".corrupt"
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}.corrupt.{n}"
    try:
        os.replace(path, dest)
    except OSError:
        return None
    return dest


def save_checkpoint(
    path: str,
    tree: Any,
    metadata: Optional[Dict] = None,
    *,
    keep_previous: bool = False,
) -> None:
    """Atomically write ``tree`` (+ JSON-able ``metadata``) to ``path`` (.npz).

    Process-0-only under multi-process runs; all processes return only after the
    write is durable (barrier). ``keep_previous`` rotates an existing file to
    ``<path>.prev`` first (snapshot saves do this; see module docstring).
    """
    arrays = _gather_arrays(tree, metadata)
    if is_main_process():
        if keep_previous:
            _rotate_previous(path)
        _write_npz(path, arrays)
    barrier("checkpoint_write")


class AsyncCheckpointer:
    """Checkpoint writes that overlap training (orbax-style async save).

    ``save()`` gathers device state to host ON THE CALLER THREAD (so any
    cross-host collectives stay on the main thread), then hands the durable
    disk write to a background thread and returns. The cross-host barrier that
    :func:`save_checkpoint` performs inline is deferred to the next ``wait()``
    — which ``save()`` itself calls first, so writes never overlap and every
    save is known durable before the next one starts. Call ``wait()`` before
    reading the file or exiting.
    """

    def __init__(self):
        self._thread = None
        self._barrier_due = False
        self._error: Optional[BaseException] = None

    def save(
        self,
        path: str,
        tree: Any,
        metadata: Optional[Dict] = None,
        *,
        keep_previous: bool = False,
    ) -> None:
        self.wait()
        arrays = _gather_arrays(tree, metadata)
        self._barrier_due = True
        if not is_main_process():
            return

        def write():
            try:
                if keep_previous:
                    _rotate_previous(path)
                _write_npz(path, arrays)
            except BaseException as e:  # surfaced on the next wait()
                self._error = e

        import threading

        self._thread = threading.Thread(target=write, daemon=True)
        self._thread.start()

    def save_snapshot(
        self,
        path: str,
        state: Any,
        epochs_run: int,
        *,
        step_in_epoch: int = 0,
        extra_meta: Optional[Dict] = None,
    ) -> None:
        """Async variant of :func:`save_snapshot` (same metadata schema and
        the same ``.prev`` rotation)."""
        self.save(
            path,
            state,
            metadata=_snapshot_meta(epochs_run, step_in_epoch, extra_meta),
            keep_previous=True,
        )

    def wait(self) -> None:
        """Block until the in-flight write is durable on every process."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        # Barrier BEFORE surfacing a write error: the other processes are
        # already blocked in this barrier, and skipping it on failure would
        # strand them (and desynchronize the next barrier).
        if self._barrier_due:
            self._barrier_due = False
            barrier("checkpoint_write_async")
        if self._error is not None:
            error, self._error = self._error, None
            raise error


def load_checkpoint(path: str, template: Any) -> Tuple[Any, Dict]:
    """Restore a pytree with ``template``'s structure from ``path``.

    Returns ``(tree, metadata)``. Leaves come back as numpy arrays cast to the
    template leaf's dtype; callers place them on device (the Trainer re-puts
    them with the replicated sharding).
    """
    with np.load(path) as data:
        meta = json.loads(bytes(data[_META_KEY].tobytes()).decode("utf-8"))
        _verify_integrity(data, meta, source=f"checkpoint {path}")
        tree = _align_to_template(data, template, source=f"checkpoint {path}")
    meta.pop(_INTEGRITY_KEY, None)  # plumbing, not caller-facing metadata
    return tree, meta


def _align_to_template(
    mapping, template: Any, *, source: str, materialize: bool = True
) -> Any:
    """Rebuild ``template``'s structure from ``mapping`` (any object with
    ``key in mapping`` / ``mapping[key]``, keyed by "/"-joined leaf paths):
    missing keys raise, shapes are validated, values cast to the template
    leaf's dtype. The single leaf-restoration contract, shared by
    :func:`load_checkpoint` (npz) and :func:`import_orbax`.

    ``materialize=False`` keeps each value AS-IS apart from the dtype cast
    (``.astype`` on a sharded ``jax.Array`` preserves its placement) — the
    sharded-restore path, where ``np.asarray`` would gather the very
    shards sharded restore exists to avoid."""
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for p, tmpl in paths_and_leaves:
        key = _path_str(p)
        if key not in mapping:
            raise KeyError(f"{source} missing leaf {key!r}")
        value = mapping[key] if not materialize else np.asarray(mapping[key])
        # Shape/dtype only — NEVER materialize the template leaf: a sharded
        # TrainState template (Trainer(partition_specs=)) spans
        # non-addressable devices and cannot be fetched.
        if hasattr(tmpl, "shape") and hasattr(tmpl, "dtype"):
            tmpl_shape, tmpl_dtype = tuple(tmpl.shape), tmpl.dtype
        else:
            tmpl_arr = np.asarray(tmpl)
            tmpl_shape, tmpl_dtype = tmpl_arr.shape, tmpl_arr.dtype
        if tuple(value.shape) != tmpl_shape:
            raise ValueError(
                f"{source} leaf {key!r} shape {tuple(value.shape)} != "
                f"template {tmpl_shape}"
            )
        leaves.append(
            value if value.dtype == tmpl_dtype else value.astype(tmpl_dtype)
        )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _snapshot_meta(
    epochs_run: int,
    step_in_epoch: int = 0,
    extra: Optional[Dict] = None,
) -> Dict:
    """The snapshot metadata schema — single definition shared by the sync
    and async save paths (load_snapshot reads the same keys).

    ``step_in_epoch`` > 0 marks a MID-epoch (just-in-time drain) snapshot:
    ``epochs_run`` epochs are complete and ``step_in_epoch`` batches of epoch
    ``epochs_run`` have already been applied to the state. ``extra`` merges
    arbitrary JSON-able resume context (the Trainer stores the loader's order
    state and the partial-epoch loss sums there)."""
    meta = {"epochs_run": int(epochs_run), "step_in_epoch": int(step_in_epoch)}
    if extra:
        meta.update(extra)
    return meta


def save_snapshot(
    path: str,
    state: Any,
    epochs_run: int,
    *,
    step_in_epoch: int = 0,
    extra_meta: Optional[Dict] = None,
) -> None:
    """Elastic-training snapshot: full TrainState + progress marker.

    Twin of ``Trainer._save_snapshot`` (reference ``multigpu_torchrun.py:57-62``,
    which stores ``{MODEL_STATE, EPOCHS_RUN}``). Rotates the previous snapshot
    to ``<path>.prev`` so resume always has a fallback candidate.
    ``step_in_epoch``/``extra_meta`` make the snapshot step-granular (the
    preemption drain path — see :func:`_snapshot_meta`).
    """
    save_checkpoint(
        path,
        state,
        metadata=_snapshot_meta(epochs_run, step_in_epoch, extra_meta),
        keep_previous=True,
    )


def load_snapshot(path: str, template: Any) -> Tuple[Any, Dict]:
    """Restore a snapshot; returns ``(state, meta)`` where ``meta`` carries at
    least ``epochs_run`` and ``step_in_epoch`` (see :func:`_snapshot_meta`;
    both default to 0 for snapshots written before the schema carried them).

    Twin of ``Trainer._load_snapshot`` (reference ``multigpu_torchrun.py:36-40``).
    """
    state, meta = load_checkpoint(path, template)
    meta.setdefault("epochs_run", 0)
    meta.setdefault("step_in_epoch", 0)
    return state, meta


def load_snapshot_with_fallback(
    path: str, template: Any
) -> Optional[Tuple[Any, Dict, str]]:
    """Self-healing snapshot resume: try ``path``, then ``<path>.prev``.

    A candidate that exists but fails to load — checksum mismatch, torn zip,
    missing leaves — is quarantined (renamed ``.corrupt``) with a loud
    warning, and the chain moves on. Returns ``(state, meta, used_path)``
    from the first loadable candidate, or ``None`` when no candidate exists
    at all (silent: a first run) or every candidate was corrupt (loud: the
    caller starts fresh knowing data was lost).

    On shared-filesystem multi-process runs every process walks the same
    chain; the quarantine rename is first-writer-wins and the losers simply
    see the file gone and continue down the chain.
    """
    candidates = [c for c in (path, path + ".prev") if os.path.exists(c)]
    if not candidates:
        return None
    for cand in candidates:
        try:
            state, meta = load_snapshot(cand, template)
            return state, meta, cand
        except Exception as e:
            dest = quarantine(cand)
            print(
                f"[checkpoint] snapshot {cand} failed to load "
                f"({type(e).__name__}: {e}); quarantined to {dest}",
                file=sys.stderr,
                flush=True,
            )
    print(
        f"[checkpoint] WARNING: no loadable snapshot for {path} — every "
        "candidate was corrupt and quarantined; training will start FRESH",
        file=sys.stderr,
        flush=True,
    )
    return None


# -------------------------------------------------------- orbax interop

def export_orbax(path: str, state: Any, *, epochs_run: int = 0) -> None:
    """Write ``state`` as an Orbax (tensorstore) checkpoint directory — the
    JAX-ecosystem interchange format — so checkpoints trained here load in
    any Orbax-consuming stack (and vice versa through
    :func:`import_orbax`). ``epochs_run`` rides in a sibling JSON file
    (Orbax trees hold arrays, not metadata).

    Multi-host: EVERY process must call this — orbax's ``save`` runs
    internal ``sync_global_processes`` barriers on all hosts. Only the
    metadata sidecar is process-0-gated here.

    Sharded leaves are handed to orbax AS live ``jax.Array``\\ s: its
    tensorstore backend writes each host's addressable shards directly, so
    no process materializes the full global state in host memory — the
    models big enough to need sharded checkpointing are exactly the ones a
    per-leaf host allgather would OOM.

    The npz format (:func:`save_checkpoint`) stays the framework's native
    snapshot: single-file, atomic-replace, template-validated. This bridge
    exists for interop, not as a replacement.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    checkpointer = ocp.PyTreeCheckpointer()
    checkpointer.save(path, state, force=True)
    if is_main_process():
        # Atomic sidecar write: a truncated meta.json would fail
        # import_orbax where a missing one correctly defaults to epoch 0.
        _atomic_write(
            path + ".meta.json",
            lambda f: json.dump(_snapshot_meta(epochs_run), f),
            mode="w",
        )
    barrier("orbax_export")


def import_orbax(
    path: str, template: Any, *, shardings: Any = None
) -> Tuple[Any, int]:
    """Load an Orbax checkpoint directory into ``template``'s structure;
    returns ``(tree, epochs_run)`` (0 when no sidecar metadata exists —
    e.g. a checkpoint produced by another framework).

    ``shardings`` (a template-shaped pytree of ``NamedSharding`` leaves,
    e.g. from ``parallel.partitioning.make_state_shardings``) switches to
    the SHARDED-NATIVE restore: orbax's tensorstore backend reads each
    host's addressable shards directly into correctly-placed ``jax.Array``
    leaves — the restore-side mirror of :func:`export_orbax`'s sharded
    write, and the difference between "loads" and "OOMs" for models big
    enough to need sharded checkpointing. Without it, leaves restore to
    host numpy (fine for small states, replicated placement downstream).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    checkpointer = ocp.PyTreeCheckpointer()
    source = f"orbax checkpoint {path}"
    if shardings is None:
        restored = checkpointer.restore(path)
        # Orbax restores a nested dict whose leaf ORDER (alphabetical keys)
        # need not match the template's dataclass field order — align by
        # path string, not position.
        by_path = {
            _path_str(p): leaf
            for p, leaf in jax.tree_util.tree_flatten_with_path(restored)[0]
        }
        tree = _align_to_template(by_path, template, source=source)
    else:
        shard_by_path = {
            _path_str(p): s
            for p, s in jax.tree_util.tree_flatten_with_path(
                shardings,
                is_leaf=lambda x: hasattr(x, "spec"),  # NamedSharding leaves
            )[0]
        }
        # restore_args must mirror the SAVED tree's structure, which the
        # checkpoint's own metadata provides; shardings are matched to it
        # by path string (same alignment rule as the host path above).
        meta_tree = checkpointer.metadata(path)
        # Newer orbax wraps the item tree in StepMetadata.item_metadata.
        item_meta = getattr(meta_tree, "item_metadata", None)
        if item_meta is not None:
            meta_tree = getattr(item_meta, "tree", item_meta)

        # Template dtypes by path: restoring at the template dtype makes
        # tensorstore cast DURING the read, so _align_to_template's astype
        # is a guaranteed no-op on the sharded path — an eager .astype on a
        # restored global jax.Array would be computation on possibly
        # non-addressable shards, exactly what sharded restore exists to
        # avoid (ADVICE r04). Attribute access only; never materialize.
        tmpl_dtypes = {
            _path_str(p): (
                leaf.dtype
                if hasattr(leaf, "dtype")
                else np.asarray(leaf).dtype
            )
            for p, leaf in jax.tree_util.tree_flatten_with_path(template)[0]
        }
        consumed = set()

        def make_arg(p, _meta_leaf):
            key = _path_str(p)
            sharding = shard_by_path.get(key)
            if sharding is None:
                return ocp.RestoreArgs()  # host numpy for unlisted leaves
            consumed.add(key)
            return ocp.ArrayRestoreArgs(
                sharding=sharding, dtype=tmpl_dtypes.get(key)
            )

        restore_args = jax.tree_util.tree_map_with_path(make_arg, meta_tree)
        unmatched = set(shard_by_path) - consumed
        if unmatched:
            # Loud, not silent: a shardings tree that misses the saved
            # paths would quietly restore everything to host numpy — the
            # OOM this path exists to prevent.
            raise ValueError(
                f"{source}: shardings provided for leaves the checkpoint "
                f"does not contain: {sorted(unmatched)[:5]}"
                f"{'...' if len(unmatched) > 5 else ''} — check the "
                "shardings tree matches the saved state's structure"
            )
        restored = checkpointer.restore(path, restore_args=restore_args)
        by_path = {
            _path_str(p): leaf
            for p, leaf in jax.tree_util.tree_flatten_with_path(restored)[0]
        }
        tree = _align_to_template(
            by_path, template, source=source, materialize=False
        )
    epochs = 0
    meta_path = path + ".meta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            epochs = int(json.load(f).get("epochs_run", 0))
    return tree, epochs


# -------------------------------------------------------- rotation manager

class CheckpointManager:
    """Rotating checkpoint directory: keep the last ``keep`` snapshots plus
    (optionally) the best-by-metric one, pruning the rest.

    The reference overwrites one ``checkpoint.pt`` forever (``multigpu.py:
    53-56``); real training wants bounded history and a protected best —
    the torch-ecosystem habit (lightning/accelerate save_top_k) expressed
    over this module's atomic npz snapshots:

    * ``save(state, step=..., metric=...)`` writes ``ckpt_<step>.npz``
      through :func:`save_checkpoint` (atomic, process-0 writer, cross-host
      barrier) and then prunes: newest ``keep`` stay, the best-metric
      checkpoint (lowest with ``mode="min"``, highest with ``"max"``) is
      never pruned while it holds the record.
    * ``latest_path()`` / ``best_path()`` / ``restore(template)`` / 
      ``restore_best(template)`` read back; the metric ledger rides in each
      file's own metadata, so the directory is self-describing (a fresh
      process can resume the rotation).
    """

    PREFIX = "ckpt_"

    def __init__(
        self,
        directory: str,
        *,
        keep: int = 3,
        mode: str = "min",
    ):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self.mode = mode
        # step -> consecutive failed reads (this process): one glitch must
        # not delete a file, but a PERMANENTLY corrupt one must not be
        # protected forever (it would accumulate and slow every save).
        self._read_failures: Dict[int, int] = {}
        self._max_read_failures = 3

    # ------------------------------------------------------------- paths
    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"{self.PREFIX}{step:010d}.npz")

    def _steps(self):
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(self.PREFIX) and name.endswith(".npz"):
                try:
                    out.append(int(name[len(self.PREFIX):-4]))
                except ValueError:
                    continue
        return sorted(out)

    def _recent(self):
        """The newest ``keep`` steps by WRITE TIME (mtime, step-number tie
        break), not by step number: after ``restore_best`` rolls training
        back, new saves carry lower step numbers than the abandoned run's
        files — recency-by-step would delete the fresh checkpoint on the
        very save that created it and keep serving the stale run."""
        entries = []
        for step in self._steps():
            try:
                entries.append((os.path.getmtime(self._path(step)), step))
            except OSError:
                continue  # pruned concurrently
        entries.sort()
        return [step for _, step in entries[-self.keep:]]

    def _metric_of(self, step: int):
        """``(readable, metric_or_None)`` — a file that EXISTS but cannot
        be read (transient FS error, concurrent truncated read) is
        distinguished from one saved without a metric: pruning must treat
        the former as protected, or a glitch while re-reading the best
        checkpoint would delete it. Consecutive failures are counted so a
        permanently corrupt file stops being protected after
        ``_max_read_failures`` reads."""
        try:
            with np.load(self._path(step)) as data:
                meta = json.loads(
                    bytes(data[_META_KEY].tobytes()).decode("utf-8")
                )
            self._read_failures.pop(step, None)
            return True, meta.get("metric")
        except Exception:
            self._read_failures[step] = self._read_failures.get(step, 0) + 1
            return False, None

    def latest_path(self) -> Optional[str]:
        recent = self._recent()
        return self._path(recent[-1]) if recent else None

    def best_path(self) -> Optional[str]:
        best_step, _ = self._best()
        return self._path(best_step) if best_step is not None else None

    def _best(self, info=None):
        """``info``: optional pre-read ``{step: (ok, metric)}`` so one save
        does not open every file twice (once here, once in _prune)."""
        import math

        best_step, best_val = None, None
        sign = 1.0 if self.mode == "min" else -1.0
        for step in self._steps():
            ok, val = (
                info[step] if info is not None and step in info
                else self._metric_of(step)
            )
            # Non-finite metrics (a diverged eval) never become "best" — a
            # NaN record would win every strict comparison forever.
            if not ok or val is None or not math.isfinite(val):
                continue
            if best_val is None or sign * val < sign * best_val:
                best_step, best_val = step, val
        return best_step, best_val

    # -------------------------------------------------------------- save
    def save(
        self,
        state: Any,
        *,
        step: int,
        metric: Optional[float] = None,
        epochs_run: int = 0,
        extra_metadata: Optional[Dict] = None,
    ) -> str:
        """Write ``ckpt_<step>.npz`` and prune. ``metric`` (e.g. eval loss)
        enters the file's metadata and drives best-retention; without it
        only recency is kept. ``extra_metadata`` merges into the file's
        metadata (schema compatibility with non-rotated consumers). Call
        from EVERY process (the write itself is process-0-gated with a
        barrier inside save_checkpoint)."""
        path = self._path(step)
        meta: Dict = dict(extra_metadata or {})
        meta.update(_snapshot_meta(epochs_run))
        if metric is not None:
            meta["metric"] = float(metric)
        save_checkpoint(path, state, metadata=meta)
        if is_main_process():
            self._prune()
        barrier("checkpoint_manager_prune")
        return path

    def _prune(self) -> None:
        steps = self._steps()
        # One metadata read per file per prune, shared with best selection.
        info = {step: self._metric_of(step) for step in steps}
        keepers = set(self._recent())
        best_step, _ = self._best(info)
        if best_step is not None:
            keepers.add(best_step)
        for step in steps:
            if step in keepers:
                continue
            ok, _ = info[step]
            if not ok and (
                self._read_failures.get(step, 0) < self._max_read_failures
            ):
                continue  # maybe-transient read failure: protect for now
            try:
                os.unlink(self._path(step))
            except OSError:
                pass  # already gone (a concurrent manager pruned it)

    # ----------------------------------------------------------- restore
    def restore(self, template: Any) -> Tuple[Any, Dict]:
        """Latest *loadable* checkpoint -> ``(tree, metadata)``; raises if
        none exists. A corrupt latest is quarantined (``.corrupt``) with a
        loud warning and the next-newest is tried — the rotation itself is
        the fallback chain, same recovery contract as
        :func:`load_snapshot_with_fallback`."""
        ordered = self._recent()  # oldest -> newest among the kept set
        if not ordered:
            raise FileNotFoundError(
                f"no {self.PREFIX}*.npz under {self.directory}"
            )
        last_err: Optional[Exception] = None
        for step in reversed(ordered):
            path = self._path(step)
            try:
                return load_checkpoint(path, template)
            except Exception as e:
                last_err = e
                dest = quarantine(path)
                print(
                    f"[checkpoint] {path} failed to load "
                    f"({type(e).__name__}: {e}); quarantined to {dest}",
                    file=sys.stderr,
                    flush=True,
                )
        raise FileNotFoundError(
            f"no loadable {self.PREFIX}*.npz under {self.directory} "
            f"(all candidates corrupt; last error: {last_err})"
        )

    def restore_best(self, template: Any) -> Tuple[Any, Dict]:
        """Best-metric checkpoint -> ``(tree, metadata)``; raises if no
        checkpoint carries a metric."""
        path = self.best_path()
        if path is None:
            raise FileNotFoundError(
                f"no metric-carrying {self.PREFIX}*.npz under "
                f"{self.directory}"
            )
        return load_checkpoint(path, template)
