"""distributed_pytorch_tpu — a TPU-native (JAX/XLA/pjit) distributed training framework.

Re-implements, TPU-first, the full capability surface of the reference
``subramen/distributed-pytorch`` tutorial ladder (see SURVEY.md):

1. A reusable :class:`Trainer` (epoch loop -> batch loop -> fused jitted train step).
2. Data-parallel gradient synchronization — XLA-inserted all-reduce over a named
   device mesh replaces DDP/NCCL (reference: ``multigpu.py:36,42``).
3. Per-replica disjoint input sharding — :class:`ShardedLoader` replaces
   ``DistributedSampler`` (reference: ``multigpu.py:72-79``).
4. Process bootstrap + rendezvous, explicit and env-driven —
   :func:`setup_distributed` replaces ``init_process_group`` / torchrun env vars
   (reference: ``multigpu.py:12-20``, ``multigpu_torchrun.py:12-13``).
5. Checkpointing and snapshot-based elastic resume (reference:
   ``multigpu_torchrun.py:30-40,57-62``), extended to include optimizer state.
6. Multi-host pod launch (reference: ``slurm/sbatch_run.sh``) via
   ``launch/tpu_pod_run.sh``.
7. Step-level profiling with TensorBoard trace export (reference:
   ``multigpu_profile.py:80-91``) via :class:`StepProfiler`.
8. Toy synthetic datasets and a real-model (ResNet-50 / ViT) swap-in path.

The design stance is SPMD-first: one pure jitted ``train_step`` over a
``jax.sharding.Mesh``; the compiler owns communication (ICI/DCN collectives),
there is no user-space NCCL analog.
"""

from distributed_pytorch_tpu.checkpoint import (
    AsyncCheckpointer,
    export_orbax,
    import_orbax,
    load_checkpoint,
    load_snapshot,
    save_checkpoint,
    save_snapshot,
)
from distributed_pytorch_tpu.generation import (
    beam_search,
    generate,
    top_p_filter,
)
from distributed_pytorch_tpu.speculative import speculative_generate
from distributed_pytorch_tpu.parallel.bootstrap import (
    is_main_process,
    setup_distributed,
    shutdown_distributed,
)
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.profiling import StepProfiler
from distributed_pytorch_tpu.training.losses import (
    mse_loss,
    smoothed_cross_entropy_loss,
    softmax_cross_entropy_loss,
)
from distributed_pytorch_tpu.training.lora import LoraModel, merge_lora
from distributed_pytorch_tpu.training.train_step import TrainState, make_train_step
from distributed_pytorch_tpu.training.trainer import Trainer
from distributed_pytorch_tpu.utils.data import (
    ArrayDataset,
    MaterializedDataset,
    NativeShardedLoader,
    RandomDataset,
    ShardedLoader,
)

__version__ = "0.1.0"

__all__ = [
    "AsyncCheckpointer",
    "ArrayDataset",
    "MaterializedDataset",
    "NativeShardedLoader",
    "beam_search",
    "generate",
    "speculative_generate",
    "top_p_filter",
    "RandomDataset",
    "ShardedLoader",
    "StepProfiler",
    "TrainState",
    "Trainer",
    "LoraModel",
    "merge_lora",
    "export_orbax",
    "import_orbax",
    "is_main_process",
    "load_checkpoint",
    "load_snapshot",
    "make_mesh",
    "make_train_step",
    "mse_loss",
    "save_checkpoint",
    "save_snapshot",
    "setup_distributed",
    "shutdown_distributed",
    "smoothed_cross_entropy_loss",
    "softmax_cross_entropy_loss",
]
