"""Goodput / MFU accounting: where the wall-clock actually goes.

Two halves, one module:

* **The FLOPs model** — the single analytic source of truth for model
  FLOPs, shared by ``bench.py`` (which re-exports these names for
  backward compatibility) and ``tools/mfu_probe.py`` so the formula can
  never drift between them. Training cost is the PaLM-style
  ``3 * (2 * non-embedding-params * tokens + attention)`` with exact
  causal (and sliding-window) attention terms; decode cost is the
  forward-only per-token marginal at a given KV context length.

* **:class:`GoodputTracker`** — decomposes engine wall-clock, step by
  step, into *productive* time and named waste buckets
  (:data:`WASTE_KINDS`): speculative tokens the verifier rejected,
  re-prefill of KV lost to preemption, re-prefill after a
  snapshot/restore, token-budget under-utilization while requests queue,
  and in-process drain downtime. Attribution is proportional: a step's
  non-idle time splits over its work units (prefill tokens + decode
  positions), so a step that proposed 4 speculative tokens and kept 1
  charges 3 units of its span to ``spec_rejected``. From the same feed it
  derives tokens/sec/device and MFU (emitted tokens x decode
  FLOPs-per-token over elapsed x peak FLOPs), surfaced in
  ``registry.snapshot()``, ``bench.py --serving`` rows, and per-step
  tracer gauges.

Everything here is host-side float arithmetic on numbers the engine
already has — no device work, no extra syncs.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

# ----------------------------------------------------------------- peak FLOPs

# Peak bf16 FLOP/s per chip by generation (public spec sheets). Used as the
# MFU denominator; unknown kinds fall back to v5e-class DEFAULT_PEAK.
PEAK_BF16_FLOPS = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 46e12,
}
DEFAULT_PEAK = 197e12


def peak_flops_per_chip(device) -> float:
    """Best-effort peak bf16 FLOP/s for a jax device, by kind substring."""
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_BF16_FLOPS.items():
        if key in kind:
            return peak
    return DEFAULT_PEAK


# ---------------------------------------------------------------- FLOPs model

# ResNet-50 forward cost at 224x224 (the standard ~4.09 GFLOPs figure);
# training steps cost ~3x forward (fwd + 2x bwd).
RESNET50_FWD_FLOPS_PER_IMAGE = 4.09e9


def resnet50_train_flops(batch: int) -> float:
    """Analytic FLOPs for one ResNet-50 training step at 224x224."""
    return 3.0 * RESNET50_FWD_FLOPS_PER_IMAGE * batch


def causal_attention_flops(
    *,
    n_layers: int,
    n_heads: int,
    head_dim: int,
    seq_len: int,
    batch: int,
    window: Optional[int] = None,
) -> float:
    """Forward FLOPs of the attention score+value matmuls, exact for the
    causal mask: query position i attends to ``min(i+1, window)`` keys.
    The factor 4 is 2 matmuls (QK^T and PV) x 2 FLOPs per MAC."""
    if window:
        w = int(window)
        if seq_len <= w:
            per_q = seq_len * (seq_len + 1) / 2
        else:
            per_q = w * (w + 1) / 2 + (seq_len - w) * w
    else:
        per_q = seq_len**2 / 2
    return n_layers * 4.0 * batch * n_heads * per_q * head_dim


def transformer_train_flops(
    *,
    n_params: int,
    embed_params: int,
    n_layers: int,
    n_heads: int,
    head_dim: int,
    seq_len: int,
    batch: int,
    window: Optional[int] = None,
) -> float:
    """Analytic FLOPs for one transformer LM training step: PaLM-style
    ``6 * non-embedding-params * tokens`` (2 per MAC, x3 for fwd+bwd) plus
    the exact causal attention term, also x3."""
    tokens = batch * seq_len
    attn_fwd = causal_attention_flops(
        n_layers=n_layers,
        n_heads=n_heads,
        head_dim=head_dim,
        seq_len=seq_len,
        batch=batch,
        window=window,
    )
    return 3.0 * (2.0 * (n_params - embed_params) * tokens + attn_fwd)


def transformer_decode_flops_per_token(
    *,
    n_params: int,
    embed_params: int,
    n_layers: int,
    n_heads: int,
    head_dim: int,
    context_len: int,
) -> float:
    """Forward-only marginal cost of decoding one token against a KV cache
    of ``context_len`` positions: ``2 * non-embedding-params`` for the
    matmuls plus the attention read over the cache."""
    attn = 4.0 * n_layers * n_heads * head_dim * context_len
    return 2.0 * float(n_params - embed_params) + attn


def count_params(params) -> int:
    """Total scalar count of a jax pytree of arrays (host-side)."""
    import jax

    return sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
    )


# ------------------------------------------------------------------- tracker

WASTE_KINDS = (
    "spec_rejected",
    "preempt_rework",
    "restore_reprefill",
    "budget_idle",
    "drain_downtime",
)


class GoodputTracker:
    """Per-step wall-clock decomposition into productive vs wasted time.

    Feed it one :meth:`note_step` per engine step. The step's span splits:

    * ``budget_idle`` — the fraction of the token budget left unused while
      requests were queued (a full budget or an empty queue charges zero);
    * the remainder splits proportionally over the step's work units
      (prefill tokens + decode positions): units re-computing KV the
      engine already had go to ``preempt_rework`` / ``restore_reprefill``,
      speculative positions the verifier rejected go to ``spec_rejected``,
      and the rest is productive.

    ``note_drain`` / ``note_restore`` bracket in-process drain downtime
    (a restore in a fresh process has no visible gap to measure).
    """

    def __init__(
        self,
        *,
        flops_per_token: float = 0.0,
        peak_flops_per_device: float = 0.0,
        n_devices: int = 1,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.flops_per_token = float(flops_per_token)
        self.peak_flops_per_device = float(peak_flops_per_device)
        self.n_devices = max(1, int(n_devices))
        self._clock = clock
        self.reset()

    def reset(self) -> None:
        """Zero all accumulators (bench warm-up boundary)."""
        self.productive_s = 0.0
        self.wasted: Dict[str, float] = {k: 0.0 for k in WASTE_KINDS}
        self.steps = 0
        self.tokens = 0
        self._drain_t0: Optional[float] = None

    # ------------------------------------------------------------ feeding

    def note_step(
        self,
        dt_s: float,
        *,
        prefill_tokens: int = 0,
        decode_positions: int = 0,
        emitted_tokens: int = 0,
        spec_proposed: int = 0,
        rework: Optional[Dict[str, int]] = None,
        budget_used: int = 0,
        token_budget: int = 0,
        queue_depth: int = 0,
    ) -> None:
        """Attribute one engine step's wall-clock span.

        ``rework`` maps waste kind -> prefill tokens re-computing KV the
        engine had before a preemption or snapshot (a subset of
        ``prefill_tokens``). ``spec_proposed`` is the total speculative
        positions verified this step; ``emitted_tokens`` the tokens kept.
        """
        dt_s = max(0.0, float(dt_s))
        self.steps += 1
        self.tokens += int(emitted_tokens)

        idle_s = 0.0
        if token_budget > 0 and queue_depth > 0:
            fill = min(1.0, budget_used / token_budget)
            idle_s = dt_s * (1.0 - fill)
            self.wasted["budget_idle"] += idle_s

        span = dt_s - idle_s
        units = int(prefill_tokens) + int(decode_positions)
        if units <= 0:
            self.productive_s += span
            return
        per_unit = span / units

        wasted_units = 0
        if rework:
            for kind, n_tokens in rework.items():
                n = min(int(n_tokens), units - wasted_units)
                if n <= 0:
                    continue
                self.wasted[kind] += n * per_unit
                wasted_units += n
        rejected = max(0, int(spec_proposed) - int(emitted_tokens))
        rejected = min(rejected, units - wasted_units)
        if rejected > 0:
            self.wasted["spec_rejected"] += rejected * per_unit
            wasted_units += rejected

        self.productive_s += (units - wasted_units) * per_unit

    def note_drain(self) -> None:
        """Mark the start of an in-process drain (downtime clock starts)."""
        self._drain_t0 = self._clock()

    def note_restore(self) -> None:
        """Close the drain-downtime window opened by :meth:`note_drain`;
        a restore into a fresh process (no matching drain) is a no-op."""
        if self._drain_t0 is not None:
            self.wasted["drain_downtime"] += max(
                0.0, self._clock() - self._drain_t0
            )
            self._drain_t0 = None

    # ----------------------------------------------------------- reporting

    def wasted_total_s(self) -> float:
        return sum(self.wasted.values())

    def fraction(self) -> float:
        """Productive share of attributed time; 1.0 before any feed."""
        total = self.productive_s + self.wasted_total_s()
        if total <= 0.0:
            return 1.0
        return self.productive_s / total

    def mfu(self) -> float:
        """Achieved model FLOPs over peak, from emitted tokens x the
        decode FLOPs-per-token model; 0.0 when the model is unconfigured."""
        total = self.productive_s + self.wasted_total_s()
        peak = self.peak_flops_per_device * self.n_devices
        if total <= 0.0 or peak <= 0.0 or self.flops_per_token <= 0.0:
            return 0.0
        return (self.tokens * self.flops_per_token) / (total * peak)

    def tokens_per_sec_per_device(self) -> float:
        total = self.productive_s + self.wasted_total_s()
        if total <= 0.0:
            return 0.0
        return self.tokens / total / self.n_devices

    def report(self) -> dict:
        """Flat dict for bench rows / ``stats()``."""
        return {
            "steps": self.steps,
            "tokens": self.tokens,
            "productive_s": self.productive_s,
            "wasted_s": dict(self.wasted),
            "wasted_total_s": self.wasted_total_s(),
            "goodput_fraction": self.fraction(),
            "tokens_per_sec_per_device": self.tokens_per_sec_per_device(),
            "mfu": self.mfu(),
        }

    def register_into(self, registry) -> None:
        """Expose the accounting through a MetricsRegistry (pull-based, so
        snapshots always see current values)."""
        registry.counter_fn(
            "goodput_productive_seconds_total",
            lambda: self.productive_s,
            help="Wall-clock attributed to productive work",
        )
        for kind in WASTE_KINDS:
            registry.counter_fn(
                f"goodput_wasted_{kind}_seconds_total",
                lambda k=kind: self.wasted[k],
                help=f"Wall-clock wasted on {kind}",
            )
        registry.counter_fn(
            "goodput_wasted_seconds_total",
            self.wasted_total_s,
            help="Total wall-clock attributed to waste",
        )
        registry.gauge_fn(
            "goodput_fraction",
            self.fraction,
            help="Productive share of attributed wall-clock",
        )
        registry.gauge_fn(
            "goodput_tokens_per_sec_per_device",
            self.tokens_per_sec_per_device,
            help="Emitted tokens per second per device",
        )
        registry.gauge_fn(
            "goodput_mfu",
            self.mfu,
            help="Model FLOPs utilization vs peak",
        )
