"""Strict Prometheus text-exposition grammar checker.

``MetricsRegistry.prometheus_text`` claims "real scrapers accept the body
as-is" — this module makes that claim testable instead of aspirational. It
parses a scrape body under the text-format rules a conformant Prometheus
server enforces (plus the stricter conventions this repo commits to) and
raises :class:`ExpositionError` naming the offending line:

* metric names match ``[a-zA-Z_:][a-zA-Z0-9_:]*``, label names match
  ``[a-zA-Z_][a-zA-Z0-9_]*`` and never start ``__``;
* label values escape exactly ``\\``, ``\"`` and ``\\n`` — a raw newline or
  an unknown escape inside a quoted value is a corruption, not a sample;
* ``# HELP`` / ``# TYPE`` come in pairs, HELP first, at most once per
  family, BEFORE any of the family's samples (a TYPE after its samples is
  legal-but-meaningless and rejected here);
* every sample belongs to the most recent TYPE'd family: bare name for
  counters/gauges, ``name`` + ``name_sum`` + ``name_count`` (quantile
  label on the bare name) for summaries — and families are contiguous;
* sample values parse as Go floats (``+Inf``/``-Inf``/``NaN`` included),
  optional timestamps as integers;
* the body ends with a newline (the format requires the final line feed).

:func:`validate_exposition` returns the parsed families, so tests assert
content ("the merged body still carries every engine's counter") with the
same call that proves the grammar.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_VALUE_RE = re.compile(
    r"^[+-]?(?:Inf|inf|NaN|nan|\d+\.?\d*(?:[eE][+-]?\d+)?"
    r"|\.\d+(?:[eE][+-]?\d+)?)$"
)
_TYPES = ("counter", "gauge", "summary", "histogram", "untyped")


class ExpositionError(ValueError):
    """The body violates the text exposition format; the message carries
    the 1-based line number and the offending content."""


class Family:
    """One metric family: its TYPE, HELP, and samples in body order."""

    __slots__ = ("name", "type", "help", "samples")

    def __init__(self, name: str, type_: str, help_: Optional[str]):
        self.name = name
        self.type = type_
        self.help = help_
        # (sample_name, labels dict, value text)
        self.samples: List[Tuple[str, Dict[str, str], str]] = []


def _fail(lineno: int, line: str, why: str) -> None:
    raise ExpositionError(f"line {lineno}: {why}: {line!r}")


def _parse_labels(body: str, lineno: int, line: str) -> Dict[str, str]:
    """Parse the ``a="b",c="d"`` interior of a label set, enforcing the
    three-escape rule inside quoted values."""
    labels: Dict[str, str] = {}
    i = 0
    n = len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            _fail(lineno, line, "label without '='")
        name = body[i:eq]
        if not _LABEL_NAME_RE.match(name):
            _fail(lineno, line, f"bad label name {name!r}")
        if name.startswith("__"):
            _fail(lineno, line, f"reserved label name {name!r}")
        if name in labels:
            _fail(lineno, line, f"duplicate label {name!r}")
        if eq + 1 >= n or body[eq + 1] != '"':
            _fail(lineno, line, f"label {name!r} value not quoted")
        i = eq + 2
        value_chars: List[str] = []
        while True:
            if i >= n:
                _fail(lineno, line, f"unterminated value for label {name!r}")
            ch = body[i]
            if ch == "\\":
                if i + 1 >= n or body[i + 1] not in ('\\', '"', "n"):
                    _fail(
                        lineno, line,
                        f"bad escape in label {name!r} (only \\\\ \\\" \\n)",
                    )
                value_chars.append(
                    "\n" if body[i + 1] == "n" else body[i + 1]
                )
                i += 2
            elif ch == '"':
                i += 1
                break
            else:
                value_chars.append(ch)
                i += 1
        labels[name] = "".join(value_chars)
        if i < n:
            if body[i] != ",":
                _fail(lineno, line, "expected ',' between labels")
            i += 1
    return labels


def _family_of(sample_name: str, families: Dict[str, Family]) -> Optional[str]:
    """Map a sample name to its family: exact match, or the ``_sum`` /
    ``_count`` / ``_bucket`` suffix of a summary/histogram family."""
    if sample_name in families:
        return sample_name
    for suffix in ("_sum", "_count", "_bucket"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in families:
                return base
    return None


def validate_exposition(text: str) -> Dict[str, Family]:
    """Validate one scrape body; returns ``{family_name: Family}`` or
    raises :class:`ExpositionError` (see module doc for the rules)."""
    if not isinstance(text, str) or not text:
        raise ExpositionError("empty exposition body")
    if not text.endswith("\n"):
        raise ExpositionError("body must end with a newline")
    families: Dict[str, Family] = {}
    pending_help: Dict[str, str] = {}
    closed: set = set()  # families that may not receive more samples
    current: Optional[str] = None
    for lineno, line in enumerate(text.split("\n")[:-1], start=1):
        if line == "":
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 3 or parts[0] != "#" or parts[1] not in (
                "HELP", "TYPE"
            ):
                _fail(lineno, line, "comment is neither # HELP nor # TYPE")
            kind, name = parts[1], parts[2]
            rest = parts[3] if len(parts) > 3 else ""
            if not _METRIC_NAME_RE.match(name):
                _fail(lineno, line, f"bad metric name {name!r}")
            if kind == "HELP":
                if name in pending_help or name in families:
                    _fail(lineno, line, f"second HELP for {name!r}")
                bad = re.search(r"\\(?![\\n])", rest)
                if bad is not None:
                    _fail(lineno, line, "bad escape in HELP text")
                pending_help[name] = rest
            else:
                if rest not in _TYPES:
                    _fail(lineno, line, f"unknown TYPE {rest!r}")
                if name in families:
                    _fail(lineno, line, f"second TYPE for {name!r}")
                if name not in pending_help:
                    _fail(lineno, line, f"TYPE for {name!r} without HELP")
                if current is not None:
                    closed.add(current)
                families[name] = Family(name, rest, pending_help.pop(name))
                current = name
            continue
        # ---- sample line ------------------------------------------------
        rest = line
        labels: Dict[str, str] = {}
        brace = rest.find("{")
        if brace >= 0:
            close_idx = rest.rfind("}")
            if close_idx < brace:
                _fail(lineno, line, "unbalanced '{'")
            sample_name = rest[:brace]
            labels = _parse_labels(
                rest[brace + 1 : close_idx], lineno, line
            )
            tail = rest[close_idx + 1 :]
        else:
            fields = rest.split(" ", 1)
            if len(fields) != 2:
                _fail(lineno, line, "sample without value")
            sample_name, tail = fields[0], " " + fields[1]
        if not _METRIC_NAME_RE.match(sample_name):
            _fail(lineno, line, f"bad metric name {sample_name!r}")
        tail = tail.strip()
        if not tail:
            _fail(lineno, line, "sample without value")
        value_fields = tail.split(" ")
        if len(value_fields) > 2:
            _fail(lineno, line, "too many fields after label set")
        if not _VALUE_RE.match(value_fields[0]):
            _fail(lineno, line, f"bad sample value {value_fields[0]!r}")
        if len(value_fields) == 2 and not re.match(
            r"^-?\d+$", value_fields[1]
        ):
            _fail(lineno, line, f"bad timestamp {value_fields[1]!r}")
        fam_name = _family_of(sample_name, families)
        if fam_name is None:
            _fail(
                lineno, line,
                f"sample {sample_name!r} has no preceding # TYPE",
            )
        if fam_name in closed:
            _fail(
                lineno, line,
                f"family {fam_name!r} is not contiguous (samples after "
                "another family started)",
            )
        fam = families[fam_name]
        if fam.type in ("counter", "gauge") and sample_name != fam.name:
            _fail(
                lineno, line,
                f"{fam.type} family {fam.name!r} with suffixed sample",
            )
        if fam.type == "summary":
            if sample_name == fam.name:
                if "quantile" not in labels:
                    _fail(
                        lineno, line,
                        f"summary {fam.name!r} sample missing quantile label",
                    )
            elif sample_name not in (
                f"{fam.name}_sum", f"{fam.name}_count"
            ):
                _fail(
                    lineno, line,
                    f"summary {fam.name!r} only allows _sum/_count suffixes",
                )
        fam.samples.append((sample_name, labels, value_fields[0]))
    if pending_help:
        raise ExpositionError(
            f"HELP without TYPE for: {sorted(pending_help)}"
        )
    return families


__all__ = ["ExpositionError", "Family", "validate_exposition"]
