"""Fixed-memory in-process time-series telemetry — the fleet's memory.

The registry answers "what is the value NOW"; nothing before this module
answered "how did it get here". :class:`TimeSeriesDB` gives every engine a
bounded, multi-resolution history of its own metrics: each engine step,
every registry counter and gauge plus the derived per-step serving series
(step wall time, decode rows, tokens/sec, per-phase time, goodput
fraction) is appended to a raw ring and folded into 10s and 60s
downsampled rings. All three rings are fixed capacity, so memory is flat
for the life of the process no matter how long the run — the property the
long-run test pins.

Memory bound (defaults): per series, ``raw_capacity`` (512) raw
``(t, v)`` pairs + 360 ten-second buckets (1 hour) + 1440 sixty-second
buckets (1 day), each bucket eight floats — ~30 KB per series, ~3 MB for
a fully-instrumented engine's ~100 series. Nothing ever allocates on the
sample path after the rings warm up.

Downsampling keeps *sufficient statistics*, not lossy averages-of-
averages: every bucket stores ``(first_t, first_v, last_t, last_v, sum,
count, min, max)`` over the raw samples that landed in it. That makes the
bucket-level queries EXACT reconstructions of the raw-level ones:

* ``rate()`` over counters uses first/last cumulative values of the
  covered span — identical to the raw delta, because counters are
  cumulative and the bucket endpoints are real samples;
* ``avg_over_time()`` over gauges uses ``sum(sums)/sum(counts)`` —
  identical to the mean of the raw samples in those buckets;
* ``quantile_over_time()`` is exact while the window fits the raw ring
  and bucket-mean-approximate beyond it (documented, not hidden).

The exactness contract is what the property test in
``tests/test_timeseries.py`` checks against brute-force recomputation
across ring-wrap boundaries.

Counter-rate semantics: a "counter" series stores the CUMULATIVE value at
each sample (registry convention); ``rate`` is ``(v_end - v_start) /
(t_end - t_start)`` over the retained samples inside the window. There is
no extrapolation and no reset detection — engines never reset counters
mid-life (bench warm-up swaps the whole metrics object, which restarts
the series from a new baseline sample).

Cross-engine merge follows :meth:`MetricsRegistry.merge`:
:meth:`TimeSeriesDB.merge` aligns ``dump()`` documents on wall-epoch
bucket boundaries and combines per-bucket statistics (sums add, counts
add, min/min, max/max, counters sum their cumulative endpoints), so a
fleet-level tokens/sec series is one call away from per-replica scrapes.
"""

from __future__ import annotations

import bisect
import math
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Downsample resolutions (bucket width seconds -> ring capacity). 10s for
# the last hour, 60s for the last day; both fixed, both tiny.
DEFAULT_RESOLUTIONS: Tuple[Tuple[float, int], ...] = (
    (10.0, 360),
    (60.0, 1440),
)


class _Bucket:
    """Sufficient statistics of the raw samples in one time bucket."""

    __slots__ = (
        "start", "first_t", "first_v", "last_t", "last_v",
        "sum", "count", "min", "max",
    )

    def __init__(self, start: float, t: float, v: float):
        self.start = start
        self.first_t = self.last_t = t
        self.first_v = self.last_v = v
        self.sum = v
        self.count = 1
        self.min = v
        self.max = v

    def add(self, t: float, v: float) -> None:
        self.last_t = t
        self.last_v = v
        self.sum += v
        self.count += 1
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def to_list(self) -> list:
        return [
            self.start, self.first_t, self.first_v, self.last_t,
            self.last_v, self.sum, self.count, self.min, self.max,
        ]

    @classmethod
    def from_list(cls, row: Sequence[float]) -> "_Bucket":
        b = cls.__new__(cls)
        (
            b.start, b.first_t, b.first_v, b.last_t, b.last_v,
            b.sum, b.count, b.min, b.max,
        ) = row
        b.count = int(b.count)
        return b


class _Ring:
    """Fixed-capacity append-only ring of :class:`_Bucket` at one
    resolution. Buckets are time-ordered; appending a sample either folds
    into the open (newest) bucket or seals it and opens the next,
    evicting the oldest when full."""

    __slots__ = ("step_s", "capacity", "buckets")

    def __init__(self, step_s: float, capacity: int):
        self.step_s = float(step_s)
        self.capacity = int(capacity)
        self.buckets: List[_Bucket] = []

    def add(self, t: float, v: float) -> None:
        start = math.floor(t / self.step_s) * self.step_s
        if self.buckets and self.buckets[-1].start == start:
            self.buckets[-1].add(t, v)
            return
        self.buckets.append(_Bucket(start, t, v))
        if len(self.buckets) > self.capacity:
            del self.buckets[0]

    def covered(self, since: float) -> List[_Bucket]:
        """Buckets whose span intersects ``[since, now]``."""
        starts = [b.start for b in self.buckets]
        i = bisect.bisect_left(starts, since - self.step_s)
        return self.buckets[i:]

    def covers(self, since: float) -> bool:
        """True when the ring's retention reaches back to ``since`` —
        i.e. no retained data was evicted after that instant (an empty
        or never-wrapped ring covers everything it ever saw)."""
        if len(self.buckets) < self.capacity:
            return True
        return self.buckets[0].start <= since


class _Series:
    """One named series: a raw ring plus the downsampled rings."""

    __slots__ = ("name", "kind", "raw", "raw_capacity", "rings")

    def __init__(
        self,
        name: str,
        kind: str,
        raw_capacity: int,
        resolutions: Sequence[Tuple[float, int]],
    ):
        if kind not in ("counter", "gauge"):
            raise ValueError(f"kind must be counter|gauge, got {kind!r}")
        self.name = name
        self.kind = kind
        self.raw: List[Tuple[float, float]] = []
        self.raw_capacity = int(raw_capacity)
        self.rings = [_Ring(step, cap) for step, cap in resolutions]

    def add(self, t: float, v: float) -> None:
        self.raw.append((t, v))
        if len(self.raw) > self.raw_capacity:
            del self.raw[0]
        for ring in self.rings:
            ring.add(t, v)


class TimeSeriesDB:
    """Bounded multi-resolution telemetry store for one engine.

    ``clock`` is injectable (tests drive synthetic timelines); it must be
    the same monotonic clock the engine times steps with
    (``time.perf_counter``). ``wall_epoch`` anchors that clock to wall
    time once, at construction, so dumps from different engines can be
    aligned for :meth:`merge`.
    """

    def __init__(
        self,
        *,
        raw_capacity: int = 512,
        resolutions: Sequence[Tuple[float, int]] = DEFAULT_RESOLUTIONS,
        clock=time.perf_counter,
    ):
        if raw_capacity < 2:
            raise ValueError("raw_capacity must be >= 2 (rate needs a delta)")
        res = sorted((float(s), int(c)) for s, c in resolutions)
        if any(s <= 0 or c < 1 for s, c in res):
            raise ValueError(f"bad resolutions {resolutions!r}")
        self.raw_capacity = int(raw_capacity)
        self.resolutions = tuple(res)
        self.clock = clock
        # clock-time 0 in wall-epoch seconds: epoch_t = wall_epoch + t.
        self.wall_epoch = time.time() - clock()
        self.samples_taken = 0
        self._series: Dict[str, _Series] = {}
        self._registries: List = []

    # ----------------------------------------------------------- ingestion

    def track_registry(self, registry) -> None:
        """Sample every counter and gauge of ``registry`` (by its
        namespace-qualified snapshot names) on each :meth:`sample` call.
        Reservoirs are deliberately not tracked — their percentiles are
        already windowed by the reservoir itself, and the derived series
        the engine records cover the latency story."""
        self._registries.append(registry)

    def series(self, name: str, kind: str) -> _Series:
        s = self._series.get(name)
        if s is None:
            s = self._series[name] = _Series(
                name, kind, self.raw_capacity, self.resolutions
            )
        elif s.kind != kind:
            raise ValueError(
                f"series {name!r} already registered as {s.kind}"
            )
        return s

    def record(
        self, name: str, value: float, *, kind: str = "gauge",
        now: Optional[float] = None,
    ) -> None:
        """Append one sample. Counters must be CUMULATIVE values."""
        t = self.clock() if now is None else now
        self.series(name, kind).add(t, float(value))

    def sample(self, now: Optional[float] = None, **derived: float) -> None:
        """One sampling tick: snapshot every tracked registry's counters
        and gauges, then record the ``derived`` keyword gauges. The engine
        calls this once per accounted step, under the registry lock."""
        t = self.clock() if now is None else now
        self.samples_taken += 1
        for registry in self._registries:
            # scalars() skips reservoir-percentile sorts — this runs once
            # per engine step, on the step path.
            read = getattr(registry, "scalars", registry.snapshot)
            snap = read()
            for name, value in snap["counters"].items():
                self.series(name, "counter").add(t, float(value))
            for name, value in snap["gauges"].items():
                if value is None or value != value:  # skip NaN gauges
                    continue
                self.series(name, "gauge").add(t, float(value))
        for name, value in derived.items():
            if value is None:
                continue
            self.series(name, "gauge").add(t, float(value))

    # ------------------------------------------------------------- queries

    def series_names(self) -> List[str]:
        return sorted(self._series)

    def kind_of(self, name: str) -> Optional[str]:
        s = self._series.get(name)
        return s.kind if s else None

    def latest(self, name: str) -> Optional[Tuple[float, float]]:
        s = self._series.get(name)
        if s is None or not s.raw:
            return None
        return s.raw[-1]

    def _resolve_window(
        self, s: _Series, window_s: float, *,
        resolution: Optional[float], now: Optional[float],
    ):
        """Pick the finest store covering ``[now-window_s, now]``: the raw
        ring when its retention reaches back far enough, else the first
        downsampled ring that does, else the coarsest. An explicit
        ``resolution`` (0 = raw, else a bucket width) overrides."""
        t_now = self.clock() if now is None else now
        since = t_now - float(window_s)
        if resolution is not None:
            if resolution == 0:
                return ("raw", [p for p in s.raw if p[0] >= since])
            for ring in s.rings:
                if ring.step_s == resolution:
                    return ("buckets", ring.covered(since))
            raise ValueError(
                f"no ring at resolution {resolution}; have raw + "
                f"{[r.step_s for r in s.rings]}"
            )
        if len(s.raw) < s.raw_capacity or (s.raw and s.raw[0][0] <= since):
            return ("raw", [p for p in s.raw if p[0] >= since])
        for ring in s.rings:
            if ring.covers(since):
                return ("buckets", ring.covered(since))
        return ("buckets", s.rings[-1].covered(since))

    def rate(
        self, name: str, window_s: float, *,
        resolution: Optional[float] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        """Per-second increase of a counter over the trailing window:
        ``(v_last - v_first) / (t_last - t_first)`` over retained samples
        in the window. None with fewer than two samples."""
        s = self._series.get(name)
        if s is None:
            return None
        store, data = self._resolve_window(
            s, window_s, resolution=resolution, now=now
        )
        if store == "raw":
            if len(data) < 2:
                return None
            t0, v0 = data[0]
            t1, v1 = data[-1]
        else:
            if not data:
                return None
            t0, v0 = data[0].first_t, data[0].first_v
            t1, v1 = data[-1].last_t, data[-1].last_v
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def avg_over_time(
        self, name: str, window_s: float, *,
        resolution: Optional[float] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        """Mean of a gauge's samples over the trailing window (sample
        mean, not time-weighted — steps tick at a near-constant cadence)."""
        s = self._series.get(name)
        if s is None:
            return None
        store, data = self._resolve_window(
            s, window_s, resolution=resolution, now=now
        )
        if store == "raw":
            if not data:
                return None
            return sum(v for _t, v in data) / len(data)
        total = sum(b.sum for b in data)
        count = sum(b.count for b in data)
        return total / count if count else None

    def quantile_over_time(
        self, name: str, q: float, window_s: float, *,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Trailing-window quantile. Exact over the raw ring; once the
        window outgrows raw retention it falls back to the quantile of
        the covering ring's bucket MEANS — an approximation, adequate for
        dashboards, never used by the regression detector (which runs on
        O(1) streaming statistics instead)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        s = self._series.get(name)
        if s is None:
            return None
        store, data = self._resolve_window(
            s, window_s, resolution=None, now=now
        )
        if store == "raw":
            values = sorted(v for _t, v in data)
        else:
            values = sorted(b.sum / b.count for b in data if b.count)
        if not values:
            return None
        idx = min(len(values) - 1, int(q * len(values)))
        return values[idx]

    def points(
        self, name: str, *, step: float = 0.0, window_s: float = 0.0,
        now: Optional[float] = None,
    ) -> List[Tuple[float, float]]:
        """Plottable ``(t, value)`` pairs for one series — the payload of
        ``/timeseries`` and the sparkline feeds. ``step=0`` returns raw
        samples; ``step=W`` returns one point per W-second bucket. Gauges
        plot their value (bucket mean when stepped); counters plot their
        per-second RATE (delta to the previous sample / within-bucket
        delta), which is the only graphable form of a cumulative series.
        ``window_s=0`` means everything retained at that resolution."""
        s = self._series.get(name)
        if s is None:
            return []
        t_now = self.clock() if now is None else now
        since = (t_now - window_s) if window_s else -math.inf
        if step == 0:
            raw = [p for p in s.raw if p[0] >= since]
            if s.kind == "gauge":
                return raw
            out = []
            for (t0, v0), (t1, v1) in zip(raw, raw[1:]):
                if t1 > t0:
                    out.append((t1, (v1 - v0) / (t1 - t0)))
            return out
        ring = None
        for r in s.rings:
            if r.step_s >= step:
                ring = r
                break
        if ring is None:
            ring = s.rings[-1]
        buckets = ring.covered(since) if since > -math.inf else ring.buckets
        if s.kind == "gauge":
            return [(b.start, b.sum / b.count) for b in buckets if b.count]
        out = []
        prev: Optional[_Bucket] = None
        for b in buckets:
            if prev is not None and b.last_t > prev.last_t:
                out.append(
                    (b.start,
                     (b.last_v - prev.last_v) / (b.last_t - prev.last_t))
                )
            prev = b
        return out

    # --------------------------------------------------------- merge / dump

    def memory_bytes(self) -> int:
        """Upper-bound estimate of retained-sample memory: 2 floats per
        raw sample + 9 per bucket, 8 bytes each plus interpreter overhead
        (~4x). Flat once the rings fill — the long-run test's gauge."""
        floats = 0
        for s in self._series.values():
            floats += 2 * len(s.raw)
            floats += sum(9 * len(r.buckets) for r in s.rings)
        return floats * 32

    def status(self) -> dict:
        """The ``/statusz`` observatory block."""
        return {
            "series": len(self._series),
            "samples_taken": self.samples_taken,
            "raw_capacity": self.raw_capacity,
            "resolutions": [
                {"step_s": s, "buckets": c} for s, c in self.resolutions
            ],
            "memory_bytes": self.memory_bytes(),
        }

    def dump(
        self, names: Optional[Iterable[str]] = None, *,
        step: float = 0.0, window_s: float = 0.0,
    ) -> dict:
        """JSON-able export: per-series kind + plottable points at the
        requested resolution, timestamps shifted to wall-epoch seconds so
        documents from different engines share one timeline."""
        wanted = sorted(names) if names is not None else self.series_names()
        series = {}
        for name in wanted:
            s = self._series.get(name)
            if s is None:
                continue
            pts = self.points(name, step=step, window_s=window_s)
            series[name] = {
                "kind": s.kind,
                "points": [
                    [self.wall_epoch + t, v] for t, v in pts
                ],
            }
        return {
            "wall_epoch": self.wall_epoch,
            "step": step,
            "window_s": window_s,
            "series": series,
        }

    def export_state(self) -> dict:
        """Full sufficient-statistics export (for :meth:`merge`): every
        series' buckets at every resolution, timestamps in wall-epoch
        seconds."""
        out = {"resolutions": list(self.resolutions), "series": {}}
        for name, s in self._series.items():
            rings = {}
            for ring in s.rings:
                rows = []
                for b in ring.buckets:
                    row = b.to_list()
                    # start, first_t, last_t are clock times; shift all
                    # three to the shared wall-epoch timeline.
                    row[0] += self.wall_epoch
                    row[1] += self.wall_epoch
                    row[3] += self.wall_epoch
                    rows.append(row)
                rings[repr(ring.step_s)] = rows
            out["series"][name] = {"kind": s.kind, "rings": rings}
        return out

    @classmethod
    def merge(cls, states: Sequence[dict]) -> dict:
        """Combine :meth:`export_state` documents from several engines into
        one document of the same shape — the registry-``merge`` analogue.
        Buckets align on their wall-epoch start; gauge statistics combine
        exactly (sums add, counts add, min/min, max/max), counter
        cumulative endpoints SUM across engines (fleet total), with
        first/last picked per-engine then summed, so a fleet ``rate()``
        over the merged buckets equals the sum of per-engine rates over
        aligned, fully-covered spans."""
        merged: Dict[str, dict] = {}
        resolutions = None
        for state in states:
            if resolutions is None:
                resolutions = state["resolutions"]
            for name, sdoc in state["series"].items():
                slot = merged.setdefault(
                    name, {"kind": sdoc["kind"], "rings": {}}
                )
                for step, rows in sdoc["rings"].items():
                    ring = slot["rings"].setdefault(step, {})
                    for row in rows:
                        b = _Bucket.from_list(row)
                        have = ring.get(b.start)
                        if have is None:
                            ring[b.start] = b
                            continue
                        # Same-bucket combine across engines.
                        have.sum += b.sum
                        have.count += b.count
                        have.min = min(have.min, b.min)
                        have.max = max(have.max, b.max)
                        if sdoc["kind"] == "counter":
                            # Fleet cumulative: endpoints add.
                            have.first_v += b.first_v
                            have.last_v += b.last_v
                            have.first_t = max(have.first_t, b.first_t)
                            have.last_t = min(have.last_t, b.last_t)
                        else:
                            if b.first_t < have.first_t:
                                have.first_t, have.first_v = (
                                    b.first_t, b.first_v
                                )
                            if b.last_t > have.last_t:
                                have.last_t, have.last_v = (
                                    b.last_t, b.last_v
                                )
        return {
            "resolutions": resolutions or [],
            "series": {
                name: {
                    "kind": doc["kind"],
                    "rings": {
                        step: [
                            ring[start].to_list()
                            for start in sorted(ring)
                        ]
                        for step, ring in doc["rings"].items()
                    },
                }
                for name, doc in merged.items()
            },
        }


def sparkline(values: Sequence[float], width: int = 32) -> str:
    """Render ``values`` as a unicode sparkline (``▁``..``█``), resampled
    to ``width`` columns. Shared by ``/graphz`` and ``tools/obs_top.py``.
    Empty input renders as spaces; a flat series renders mid-height."""
    blocks = "▁▂▃▄▅▆▇█"
    if not values:
        return " " * width
    vals = list(values)
    if len(vals) > width:
        # Tail-biased resample: the newest samples are the interesting
        # ones; one column per stride, mean within it.
        stride = len(vals) / width
        vals = [
            sum(chunk) / len(chunk)
            for chunk in (
                vals[int(i * stride):max(int(i * stride) + 1,
                                         int((i + 1) * stride))]
                for i in range(width)
            )
        ]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return ("▄" * len(vals)).rjust(width)
    span = hi - lo
    out = "".join(
        blocks[min(len(blocks) - 1, int((v - lo) / span * len(blocks)))]
        for v in vals
    )
    return out.rjust(width)


__all__ = ["TimeSeriesDB", "DEFAULT_RESOLUTIONS", "sparkline"]
