"""Per-engine HTTP introspection server — the observability wire.

Everything the obs stack knows stays trapped in-process until something
exports it; this module is that something, built on stdlib only
(``http.server`` + ``urllib``) so it runs anywhere the engine does. One
:class:`IntrospectionServer` rides one engine on a daemon thread:

==============  ============================================================
endpoint        body
==============  ============================================================
``/metrics``    Prometheus text exposition from the engine's registry
``/healthz``    ``{"status": "live"|"draining"|"closed"}`` — 200 only when
                live, 503 while draining or closed (load-balancer semantics:
                a draining replica must fall out of rotation)
``/statusz``    JSON live-state: queue depth, per-request phase/age/tokens,
                page-state counts, SLO firing set, goodput split, XLA
                program ledger, recompile-sentinel state
``/snapshot``   ``registry.snapshot(include_state=True)`` as JSON — the
                exact-merge payload :meth:`MetricsRegistry.merge_remote`
                aggregates across a fleet
``/trace``      the Perfetto trace dump, rendered on demand (404 untraced)
``/postmortem`` a fresh flight-recorder dump (404 without a recorder)
``/requestz``   distributed-trace index; ``?id=<trace_id>`` returns that
                request's latency waterfall computed over the merged
                door/router/replica trace (404 untraced or unknown id)
``/timeseries`` the performance observatory's TSDB as JSON:
                ``?series=a,b`` selects series (default all),
                ``?step=<seconds>`` picks the downsample resolution (0 =
                raw samples), ``?window=<seconds>`` the trailing span
                (404 when the engine has no TSDB)
``/graphz``     human view of the same data: one unicode sparkline per
                series, rendered server-side as monospace HTML
==============  ============================================================

Thread safety: every handler goes through the engine's registry lock —
either implicitly (``prometheus_text``/``snapshot`` lock internally) or
via :meth:`InferenceEngine.status`, which snapshots scheduler state under
the same lock the engine holds across each ``step()`` while a server is
attached. The server thread therefore always observes step boundaries,
never a half-updated slot table. Scrapes never touch device state, so
serving traffic stays bitwise-identical with the server on (pinned by
tests and the bench obs-parity gate).

:func:`scrape` is the matching client: one GET, JSON-decoded when the
endpoint serves JSON, raw text for ``/metrics``.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Union

from distributed_pytorch_tpu.obs.disttrace import (
    merge_traces,
    request_waterfall,
    trace_ids,
)

_JSON = "application/json"
_PROM = "text/plain; version=0.0.4; charset=utf-8"


class IntrospectionServer:
    """HTTP introspection for one engine. ``port=0`` (default) binds an
    ephemeral port — read it back from :attr:`port` / :attr:`url`. That is
    the contract replica worker processes rely on: N workers on one host
    each bind port 0 and REPORT the kernel-assigned port up to their
    parent (the spawn handshake in ``serving/replica_worker.py``), so
    fleet spawn never races on a port and never needs a port registry.
    Constructed-and-started by :meth:`InferenceEngine.serve`; usable
    standalone around anything exposing the same surface (``registry``,
    ``status()``, ``tracer``, ``flight``, ``admission``, ``_closed``)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0):
        self.engine = engine
        server = self

        class Handler(BaseHTTPRequestHandler):
            # One quiet access log line per scrape would swamp test output.
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                try:
                    server._route(self)
                except BrokenPipeError:
                    pass
                except Exception as exc:  # surface handler bugs as 500s
                    try:
                        self.send_error(500, repr(exc))
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "IntrospectionServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"obs-server-{self.port}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()

    # ------------------------------------------------------------ handlers

    def _route(self, handler: BaseHTTPRequestHandler) -> None:
        raw_path, _, query = handler.path.partition("?")
        path = raw_path.rstrip("/") or "/"
        eng = self.engine
        if path == "/metrics":
            self._send(handler, 200, eng.registry.prometheus_text(), _PROM)
        elif path == "/healthz":
            status = self._health()
            code = 200 if status == "live" else 503
            self._send_json(handler, code, {"status": status})
        elif path == "/statusz":
            self._send_json(handler, 200, eng.status())
        elif path == "/snapshot":
            self._send_json(
                handler, 200, eng.registry.snapshot(include_state=True)
            )
        elif path == "/trace":
            tracer = getattr(eng, "tracer", None)
            if tracer is None or not getattr(tracer, "enabled", False):
                self._send_json(
                    handler, 404, {"error": "engine has no tracer"}
                )
            else:
                with eng.registry.lock:
                    doc = tracer.to_perfetto()
                self._send_json(handler, 200, doc)
        elif path == "/requestz":
            self._requestz(handler, query)
        elif path == "/timeseries":
            self._timeseries(handler, query)
        elif path == "/graphz":
            self._graphz(handler, query)
        elif path == "/postmortem":
            flight = getattr(eng, "flight", None)
            if flight is None or not getattr(flight, "enabled", False):
                self._send_json(
                    handler, 404, {"error": "engine has no flight recorder"}
                )
            else:
                doc = eng._dump_postmortem("postmortem_endpoint")
                self._send_json(handler, 200, doc)
        elif path == "/":
            self._send_json(
                handler,
                200,
                {
                    "endpoints": [
                        "/metrics", "/healthz", "/statusz", "/snapshot",
                        "/trace", "/postmortem", "/requestz",
                        "/timeseries", "/graphz",
                    ]
                },
            )
        else:
            self._send_json(handler, 404, {"error": f"unknown path {path}"})

    def _requestz(self, handler: BaseHTTPRequestHandler, query: str) -> None:
        """Distributed-trace view. Without ``?id=``, lists every trace_id
        visible across the attached component's trace documents; with one,
        merges door / router / replica lanes onto one timeline and returns
        the request's exact-partition latency waterfall."""
        eng = self.engine
        docs_fn = getattr(eng, "trace_documents", None)
        if callable(docs_fn):
            docs = docs_fn()
        else:
            tracer = getattr(eng, "tracer", None)
            if tracer is None or not getattr(tracer, "enabled", False):
                docs = []
            else:
                with eng.registry.lock:
                    docs = [tracer.to_perfetto()]
        if not docs:
            self._send_json(
                handler, 404, {"error": "component has no tracer"}
            )
            return
        merged = merge_traces(*docs)
        wanted = urllib.parse.parse_qs(query).get("id", [None])[0]
        if wanted is None:
            self._send_json(
                handler, 200, {"trace_ids": trace_ids(merged)}
            )
            return
        try:
            waterfall = request_waterfall(merged, wanted)
        except KeyError:
            self._send_json(
                handler, 404, {"error": f"unknown trace_id {wanted!r}"}
            )
            return
        self._send_json(handler, 200, waterfall)

    def _timeseries_db(self):
        return getattr(self.engine, "timeseries", None)

    def _timeseries(self, handler: BaseHTTPRequestHandler, query: str) -> None:
        """TSDB JSON export. ``?series=a,b`` filters (default: all),
        ``?step=<s>`` picks resolution (0 = raw), ``?window=<s>`` the
        trailing span (0 = full retention at that resolution)."""
        db = self._timeseries_db()
        if db is None:
            self._send_json(
                handler, 404, {"error": "engine has no timeseries db"}
            )
            return
        params = urllib.parse.parse_qs(query)
        wanted = params.get("series", [None])[0]
        names = (
            [n for n in wanted.split(",") if n] if wanted else None
        )
        try:
            step = float(params.get("step", ["0"])[0])
            window = float(params.get("window", ["0"])[0])
        except ValueError as exc:
            self._send_json(handler, 400, {"error": repr(exc)})
            return
        with self.engine.registry.lock:
            doc = db.dump(names, step=step, window_s=window)
        self._send_json(handler, 200, doc)

    def _graphz(self, handler: BaseHTTPRequestHandler, query: str) -> None:
        """Sparkline dashboard: one row per series (filtered the same way
        as ``/timeseries``), newest values on the right, rendered as
        monospace HTML with no javascript — readable over curl too."""
        from distributed_pytorch_tpu.obs.timeseries import sparkline

        db = self._timeseries_db()
        if db is None:
            self._send_json(
                handler, 404, {"error": "engine has no timeseries db"}
            )
            return
        params = urllib.parse.parse_qs(query)
        wanted = params.get("series", [None])[0]
        names = (
            [n for n in wanted.split(",") if n]
            if wanted
            else db.series_names()
        )
        rows = []
        with self.engine.registry.lock:
            for name in names:
                pts = db.points(name)
                values = [v for _t, v in pts]
                last = values[-1] if values else float("nan")
                rows.append(
                    f"<tr><td>{name}</td>"
                    f"<td class='s'>{sparkline(values, 48)}</td>"
                    f"<td class='v'>{last:.6g}</td></tr>"
                )
        html = (
            "<!doctype html><html><head><title>graphz</title><style>"
            "body{font-family:monospace;background:#111;color:#ddd}"
            "td{padding:2px 8px}td.s{letter-spacing:0}"
            "td.v{text-align:right;color:#8c8}"
            "</style></head><body><h3>performance observatory</h3>"
            "<table>" + "".join(rows) + "</table>"
            "<p>raw JSON: <a href='/timeseries' style='color:#88c'>"
            "/timeseries</a>?series=&amp;step=&amp;window=</p>"
            "</body></html>"
        )
        self._send(handler, 200, html, "text/html; charset=utf-8")

    def _health(self) -> str:
        eng = self.engine
        health = getattr(eng, "health", None)
        if callable(health):
            return health()
        if getattr(eng, "_closed", False):
            return "closed"
        if getattr(getattr(eng, "admission", None), "draining", False):
            return "draining"
        return "live"

    @staticmethod
    def _send(handler, code: int, body: str, content_type: str) -> None:
        payload = body.encode("utf-8")
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(payload)))
        handler.end_headers()
        handler.wfile.write(payload)

    @classmethod
    def _send_json(cls, handler, code: int, doc) -> None:
        cls._send(handler, code, json.dumps(doc, default=str), _JSON)


def scrape(
    base_url: str,
    endpoint: str = "/snapshot",
    timeout: float = 5.0,
    *,
    retries: int = 1,
    backoff_s: float = 0.1,
) -> Union[dict, list, str]:
    """GET one introspection endpoint. Returns the decoded JSON document,
    or the raw text body for ``/metrics``. ``/healthz`` answers through
    its status code too — a 503 here still returns the JSON body rather
    than raising, because "draining" is an answer, not an error.

    ``timeout`` bounds BOTH the connect and every socket read (a peer that
    accepts the connection and then never answers raises within
    ``timeout``, it cannot wedge the caller), and transport failures —
    refused connect, reset, timeout — are retried ``retries`` times with
    exponential backoff before the last error propagates. The bound
    matters more than the retry: a fleet-wide scrape or a router health
    loop polls every replica in sequence, so one dead or partitioned
    replica must cost at most ``(retries+1) * timeout + backoff``, never a
    hang. An HTTP *error response* is an answer from a live server, not a
    transport blip, and is never retried."""
    url = base_url.rstrip("/") + endpoint
    attempts = max(1, int(retries) + 1)
    for attempt in range(attempts):
        try:
            try:
                with urllib.request.urlopen(url, timeout=timeout) as resp:
                    body = resp.read().decode("utf-8")
                    ctype = resp.headers.get("Content-Type", "")
            except urllib.error.HTTPError as err:
                if endpoint.rstrip("/") == "/healthz":
                    return json.loads(err.read().decode("utf-8"))
                raise
        except urllib.error.HTTPError:
            raise  # served error page: the server is alive and answered
        except OSError:
            # URLError (refused/reset, DNS) and the bare socket timeout a
            # mid-response stall raises are both OSError subclasses.
            if attempt + 1 >= attempts:
                raise
            # Full-jittered exponential backoff: a fleet of scrapers that
            # all saw the same replica blip must not retry in lockstep and
            # thundering-herd it the instant it comes back.
            time.sleep(
                backoff_s * (2 ** attempt) * (0.5 + random.random() * 0.5)
            )
            continue
        if _JSON in ctype:
            return json.loads(body)
        return body


__all__ = ["IntrospectionServer", "scrape"]
