"""SLO burn-rate monitoring over the metrics registry.

Declare objectives (:class:`SLObjective`) against metrics the registry
already exports — latency objectives read a reservoir quantile (``TTFT
p95 < 400ms``), rate objectives read a bad/total counter pair
(``expired-rate < 1%``) — and feed :meth:`SLOMonitor.tick` once per
engine step (the engine does this automatically when constructed with
``slo=[...]``).

Alerting is the SRE multi-window burn-rate recipe: each objective has an
error *budget* (the tolerated bad fraction); the monitor computes the
observed bad fraction over a short and a long sliding window and divides
each by the budget to get a burn rate ("how many times faster than
sustainable are we spending the budget"). The alert condition requires
BOTH windows hot — ``burn_fast >= fast_burn AND burn_slow >= slow_burn``
— so a single slow request can't page anyone (the long window hasn't
accumulated) and a long-resolved incident stops alerting quickly (the
short window has drained). Alerts fire on the rising edge and land in
three places at once: a registry counter (``slo_<name>_alerts_total``),
a tracer instant, and a flight-recorder event, so a postmortem dump
shows exactly when the SLO went red relative to the engine timeline.

Latency evaluation note: registry reservoirs are cumulative over the
run, so each tick samples "is the quantile over threshold *now*" and the
window aggregates those violation samples — a windowed violation ratio
over a converging estimator, not per-window percentiles. That is the
standard scrape-based approximation and exactly what a Prometheus
recording rule over this exposition would see.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Sequence

from distributed_pytorch_tpu.obs.flight import NULL_FLIGHT_RECORDER
from distributed_pytorch_tpu.obs.tracer import NULL_TRACER


@dataclass(frozen=True)
class SLObjective:
    """One declarative objective.

    Latency form: set ``metric`` (a registry reservoir name), ``quantile``
    and ``threshold_s`` — bad means "the quantile exceeds the threshold".
    Rate form: set ``bad_counter`` and ``total_counter`` (registry counter
    names) — bad fraction is the windowed delta ratio.

    ``budget`` is the tolerated bad fraction (0.1 = 10% of ticks/requests
    may be bad before the budget burns at rate 1.0). ``fast_burn`` /
    ``slow_burn`` are the alerting thresholds over the ``fast_window_s`` /
    ``slow_window_s`` sliding windows.
    """

    name: str
    # latency objective
    metric: Optional[str] = None
    quantile: float = 0.95
    threshold_s: Optional[float] = None
    label: Optional[str] = None
    # rate objective
    bad_counter: Optional[str] = None
    total_counter: Optional[str] = None
    # budget + windows
    budget: float = 0.1
    fast_window_s: float = 5.0
    slow_window_s: float = 60.0
    fast_burn: float = 2.0
    slow_burn: float = 1.0

    def __post_init__(self):
        latency = self.metric is not None
        rate = self.bad_counter is not None
        if latency == rate:
            raise ValueError(
                f"objective {self.name!r}: set exactly one of metric= "
                "(latency) or bad_counter= (rate)"
            )
        if latency and self.threshold_s is None:
            raise ValueError(
                f"objective {self.name!r}: latency objective needs "
                "threshold_s"
            )
        if rate and self.total_counter is None:
            raise ValueError(
                f"objective {self.name!r}: rate objective needs "
                "total_counter"
            )
        if not (0.0 < self.budget <= 1.0):
            raise ValueError(
                f"objective {self.name!r}: budget must be in (0, 1]"
            )
        if self.fast_window_s > self.slow_window_s:
            raise ValueError(
                f"objective {self.name!r}: fast window must not exceed "
                "the slow window"
            )

    @property
    def kind(self) -> str:
        return "latency" if self.metric is not None else "rate"


class _Window:
    """One sliding window advanced incrementally: O(1) amortized per
    tick regardless of tick rate or window length. (A rescan-the-history
    implementation makes every tick cost O(window_s / tick_interval) —
    the monitor gets SLOWER the longer the engine runs, exactly the
    observability tax the parity gate forbids.) Ticks must arrive in
    nondecreasing time order, which the engine's step loop guarantees."""

    __slots__ = ("window_s", "samples", "bad_sum", "base_bad", "base_total")

    def __init__(self, window_s: float):
        self.window_s = window_s
        # latency: (t, bad 0/1). rate: (t, bad_cum, total_cum).
        self.samples: Deque[tuple] = deque()
        self.bad_sum = 0.0   # latency: running sum of retained 0/1 samples
        self.base_bad = 0.0  # rate: cumulative counters at the sample just
        self.base_total = 0.0  # BEFORE the oldest retained one (see below)

    def push_latency(self, now: float, bad: float) -> float:
        """Append one violation sample; return the window's bad fraction."""
        self.samples.append((now, bad))
        self.bad_sum += bad
        cutoff = now - self.window_s
        while self.samples[0][0] < cutoff:
            self.bad_sum -= self.samples.popleft()[1]
        return self.bad_sum / len(self.samples)

    def push_rate(self, now: float, bad_cum: float, total_cum: float) -> float:
        """Append one cumulative-counter sample; return the windowed delta
        ratio. The baseline is the newest sample OLDER than the window
        (counters start at zero, so the initial baseline is (0, 0)) — the
        same convention a Prometheus ``increase()`` over this exposition
        would use."""
        self.samples.append((now, bad_cum, total_cum))
        cutoff = now - self.window_s
        while self.samples[0][0] < cutoff:
            _, self.base_bad, self.base_total = self.samples.popleft()
        d_total = total_cum - self.base_total
        if d_total <= 0.0:
            return 0.0
        return max(0.0, bad_cum - self.base_bad) / d_total


class _ObjectiveState:
    """Per-objective sliding windows + alert edge state."""

    __slots__ = (
        "obj", "fast", "slow", "alerts", "g_fast", "g_slow", "g_firing",
        "firing", "burn_fast", "burn_slow",
    )

    def __init__(self, obj, alerts, g_fast, g_slow, g_firing):
        self.obj = obj
        self.fast = _Window(obj.fast_window_s)
        self.slow = _Window(obj.slow_window_s)
        self.alerts = alerts
        self.g_fast = g_fast
        self.g_slow = g_slow
        self.g_firing = g_firing
        self.firing = False
        self.burn_fast = 0.0
        self.burn_slow = 0.0


class SLOMonitor:
    """Evaluates a set of :class:`SLObjective` against a registry.

    Registers, per objective, ``slo_<name>_alerts_total`` plus
    ``slo_<name>_burn_fast`` / ``_burn_slow`` / ``_firing`` gauges into
    the same registry it reads, so one ``snapshot()`` carries both the
    raw metrics and the verdicts. ``min_interval_s`` rate-limits
    evaluation for callers that tick every step.
    """

    def __init__(
        self,
        registry,
        objectives: Sequence[SLObjective],
        *,
        tracer=NULL_TRACER,
        flight=NULL_FLIGHT_RECORDER,
        clock: Callable[[], float] = time.perf_counter,
        min_interval_s: float = 0.0,
    ):
        self.registry = registry
        self.tracer = tracer
        self.flight = flight
        self._clock = clock
        self.min_interval_s = float(min_interval_s)
        self._last_tick: Optional[float] = None
        self.ticks = 0
        self._states: Dict[str, _ObjectiveState] = {}
        for obj in objectives:
            if obj.name in self._states:
                raise ValueError(f"duplicate objective {obj.name!r}")
            self._states[obj.name] = _ObjectiveState(
                obj,
                registry.counter(
                    f"slo_{obj.name}_alerts_total",
                    help=f"Burn-rate alerts fired for SLO {obj.name}",
                ),
                registry.gauge(
                    f"slo_{obj.name}_burn_fast",
                    help=f"Fast-window burn rate for SLO {obj.name}",
                ),
                registry.gauge(
                    f"slo_{obj.name}_burn_slow",
                    help=f"Slow-window burn rate for SLO {obj.name}",
                ),
                registry.gauge(
                    f"slo_{obj.name}_firing",
                    help=f"1 while SLO {obj.name} alert condition holds",
                ),
            )

    @property
    def objectives(self) -> List[SLObjective]:
        return [s.obj for s in self._states.values()]

    # ----------------------------------------------------------- evaluation

    def tick(self, now: Optional[float] = None) -> List[str]:
        """Sample every objective and evaluate the burn-rate condition.
        Returns the names of objectives that FIRED this tick (rising
        edges only)."""
        if now is None:
            now = self._clock()
        if (
            self._last_tick is not None
            and self.min_interval_s > 0.0
            and now - self._last_tick < self.min_interval_s
        ):
            return []
        self._last_tick = now
        self.ticks += 1
        fired: List[str] = []
        for state in self._states.values():
            obj = state.obj
            if obj.kind == "latency":
                value = self.registry.read_quantile(
                    obj.metric, obj.quantile, label_value=obj.label
                )
                bad = (
                    1.0
                    if (value == value and value > obj.threshold_s)
                    else 0.0
                )
                frac_fast = state.fast.push_latency(now, bad)
                frac_slow = state.slow.push_latency(now, bad)
            else:
                bad_cum = float(self.registry.read_counter(obj.bad_counter))
                total_cum = float(
                    self.registry.read_counter(obj.total_counter)
                )
                frac_fast = state.fast.push_rate(now, bad_cum, total_cum)
                frac_slow = state.slow.push_rate(now, bad_cum, total_cum)
            state.burn_fast = frac_fast / obj.budget
            state.burn_slow = frac_slow / obj.budget
            state.g_fast.set(state.burn_fast)
            state.g_slow.set(state.burn_slow)
            hot = (
                state.burn_fast >= obj.fast_burn
                and state.burn_slow >= obj.slow_burn
            )
            state.g_firing.set(1.0 if hot else 0.0)
            if hot and not state.firing:
                state.alerts.inc()
                fired.append(obj.name)
                # "objective_kind", not "kind": the flight recorder's
                # event-kind slot is taken by "slo_alert" itself.
                detail = {
                    "objective": obj.name,
                    "objective_kind": obj.kind,
                    "burn_fast": round(state.burn_fast, 4),
                    "burn_slow": round(state.burn_slow, 4),
                }
                self.tracer.instant("slo_alert", **detail)
                self.flight.record("slo_alert", **detail)
            state.firing = hot
        return fired

    def state(self) -> Dict[str, dict]:
        """Current verdict per objective, for bench rows and stats()."""
        out: Dict[str, dict] = {}
        for name, st in self._states.items():
            out[name] = {
                "kind": st.obj.kind,
                "burn_fast": st.burn_fast,
                "burn_slow": st.burn_slow,
                "firing": st.firing,
                "alerts": st.alerts.value,
            }
        return out


def default_serving_objectives(
    *,
    ttft_p95_s: float = 0.5,
    tpot_p50_s: float = 0.05,
    expired_budget: float = 0.05,
    fast_window_s: float = 5.0,
    slow_window_s: float = 60.0,
) -> List[SLObjective]:
    """A reasonable starter set wired to the serving engine's registry
    names: TTFT p95, TPOT p50, and the expired-request rate."""
    return [
        SLObjective(
            name="ttft_p95",
            metric="ttft_seconds",
            quantile=0.95,
            threshold_s=ttft_p95_s,
            budget=0.1,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
        SLObjective(
            name="tpot_p50",
            metric="tpot_seconds",
            quantile=0.5,
            threshold_s=tpot_p50_s,
            budget=0.1,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
        SLObjective(
            name="expired_rate",
            bad_counter="requests_expired_total",
            total_counter="admission_accepted_total",
            budget=expired_budget,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
    ]


__all__ = [
    "SLObjective",
    "SLOMonitor",
    "default_serving_objectives",
]
