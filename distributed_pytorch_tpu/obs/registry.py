"""Unified metrics registry: counters / gauges / reservoirs with labels.

One export surface for every subsystem's numbers. The serving engine,
allocator, admission controller, Trainer, and elastic agent each REGISTER
their metrics here instead of growing another ad-hoc ``stats()`` dialect;
the registry then renders them three ways:

* :meth:`MetricsRegistry.snapshot` — structured JSON (``counters`` /
  ``gauges`` / ``reservoirs``), the payload embedded in BENCH_SERVING.json
  and asserted against engine ground truth in tests;
* :meth:`MetricsRegistry.prometheus_text` — Prometheus text exposition
  (counters/gauges as-is, reservoirs as ``summary`` with quantile labels);
* :meth:`MetricsRegistry.merge` — cross-host aggregation: counters and
  gauges sum, reservoirs merge sample-exactly via
  :meth:`~distributed_pytorch_tpu.metrics.ReservoirHistogram.merge_state`,
  so a fleet-wide p99 is computed over the union stream, not averaged
  per-host percentiles (which would be meaningless).

Registration is PULL-based: most metrics are registered as zero-arg
callables resolved at snapshot time (``counter_fn`` / ``gauge_fn`` /
``reservoir``), so the owning object keeps its counters as plain attributes
— one source of truth, no double bookkeeping, and an object that is
replaced wholesale (bench.py swaps ``engine.metrics`` after warm-up) stays
correct as long as the callable re-resolves it. :class:`Counter` /
:class:`Gauge` cover the push-style cases (the elastic agent's restart
loop) where no long-lived owner exists.

Naming convention: ``<namespace>_<subsystem>_<name>_<unit>[_total]`` —
``_total`` marks monotonic counters (Prometheus idiom), units are spelled
out (``_seconds``, never ``_s``), and label splits ride on the reservoir's
``label`` key (``serving_ttft_seconds{source="hit"}``) rather than name
suffixes.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Union

from distributed_pytorch_tpu.metrics import ReservoirGroup, ReservoirHistogram

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


class Counter:
    """Push-style monotonic counter for owners without a metrics object."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Push-style settable gauge."""

    __slots__ = ("value",)

    def __init__(self, value: float = 0.0):
        self.value = value

    def set(self, value: float) -> None:
        self.value = value


class MetricsRegistry:
    """Named collection of counters, gauges, and reservoir histograms.

    ``namespace`` prefixes every metric name at export time
    (``serving_...``, ``elastic_...``), so registries from different
    subsystems can be merged or scraped side by side without collisions.
    """

    def __init__(self, namespace: str = ""):
        self.namespace = _sanitize(namespace) if namespace else ""
        # THE observability lock. Scrapes arrive on the introspection
        # server's thread while the engine steps on its own; every render
        # path below takes this lock, and the engine takes it around
        # step()/submit() whenever a server is attached — reservoir reads
        # lazily re-sort their sample buffer, so an unlocked scrape would
        # race the step loop's record() calls. Reentrant: the SLO monitor
        # ticks (reads quantiles) from inside a locked step.
        self.lock = threading.RLock()
        # name -> zero-arg callable returning the current value.
        self._counters: Dict[str, Callable[[], float]] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        # name -> (resolver, label_key or None). The resolver returns a
        # ReservoirHistogram (label_key None) or a ReservoirGroup.
        self._reservoirs: Dict[str, tuple] = {}
        # sanitized name -> HELP text for the Prometheus exposition.
        self._help: Dict[str, str] = {}

    # --------------------------------------------------------- registration

    def _check_new(self, name: str) -> str:
        name = _sanitize(name)
        if (
            name in self._counters
            or name in self._gauges
            or name in self._reservoirs
        ):
            raise ValueError(f"metric {name!r} already registered")
        return name

    def counter(self, name: str, help: str = "") -> Counter:
        """Create and register a push-style :class:`Counter`."""
        c = Counter()
        self.counter_fn(name, lambda: c.value, help=help)
        return c

    def counter_fn(
        self, name: str, fn: Callable[[], float], help: str = ""
    ) -> None:
        """Register a pull-style counter: ``fn`` is read at snapshot time
        and must be monotonic over the owner's lifetime."""
        name = self._check_new(name)
        self._counters[name] = fn
        if help:
            self._help[name] = help

    def gauge(self, name: str, value: float = 0.0, help: str = "") -> Gauge:
        """Create and register a push-style :class:`Gauge`."""
        g = Gauge(value)
        self.gauge_fn(name, lambda: g.value, help=help)
        return g

    def gauge_fn(
        self, name: str, fn: Callable[[], float], help: str = ""
    ) -> None:
        name = self._check_new(name)
        self._gauges[name] = fn
        if help:
            self._help[name] = help

    def reservoir(
        self,
        name: str,
        hist: Union[
            ReservoirHistogram,
            ReservoirGroup,
            Callable[[], Union[ReservoirHistogram, ReservoirGroup]],
        ],
        label: Optional[str] = None,
        help: str = "",
    ) -> None:
        """Register a :class:`ReservoirHistogram` (``label=None``) or a
        :class:`ReservoirGroup` (``label`` names the label dimension, e.g.
        ``"source"``). Pass a zero-arg callable to re-resolve the object at
        snapshot time (survives owners that replace their metrics object)."""
        resolver = hist if callable(hist) else (lambda: hist)
        name = self._check_new(name)
        self._reservoirs[name] = (resolver, label)
        if help:
            self._help[name] = help

    # -------------------------------------------------------------- export

    def _qualified(self, name: str) -> str:
        return f"{self.namespace}_{name}" if self.namespace else name

    def _resolve(self, name: str) -> str:
        """Accept either the registered name or the namespace-qualified
        one (as it appears in snapshots) — accessors take both."""
        name = _sanitize(name)
        prefix = f"{self.namespace}_" if self.namespace else ""
        if (
            prefix
            and name.startswith(prefix)
            and not (
                name in self._counters
                or name in self._gauges
                or name in self._reservoirs
            )
        ):
            return name[len(prefix):]
        return name

    def read_counter(self, name: str) -> float:
        """Current value of a registered counter (by either name form)."""
        with self.lock:
            return self._counters[self._resolve(name)]()

    def read_gauge(self, name: str) -> float:
        """Current value of a registered gauge (by either name form)."""
        with self.lock:
            return self._gauges[self._resolve(name)]()

    def read_quantile(
        self, name: str, q: float, label_value: Optional[str] = None
    ) -> float:
        """Current quantile of a registered reservoir; ``label_value``
        selects the series of a labeled group. NaN on empty reservoirs,
        consistent with :meth:`ReservoirHistogram.quantile`."""
        with self.lock:
            resolver, label = self._reservoirs[self._resolve(name)]
            obj = resolver()
            if label is not None:
                if label_value is None:
                    raise ValueError(
                        f"reservoir {name!r} is labeled by {label!r}; "
                        "pass label_value"
                    )
                if label_value not in obj.labels:
                    return float("nan")
                obj = obj[label_value]
            return obj.quantile(q)

    @staticmethod
    def _summary(hist: ReservoirHistogram) -> Dict[str, float]:
        return hist.summary()

    def snapshot(self, include_state: bool = False) -> Dict[str, dict]:
        """Structured JSON view. ``include_state=True`` additionally embeds
        each reservoir's sample state so :meth:`merge` can aggregate
        percentiles sample-exactly across hosts."""
        with self.lock:
            return self._snapshot_locked(include_state)

    def scalars(self) -> Dict[str, Dict[str, float]]:
        """Counters and gauges only, qualified like :meth:`snapshot` but
        WITHOUT reservoir summaries — those sort their samples to build
        percentiles, far too expensive for the TSDB's once-per-engine-step
        sampling tick (reservoir latencies are already windowed by the
        reservoir itself; the derived per-step series cover that story)."""
        with self.lock:
            return {
                "counters": {
                    self._qualified(n): fn()
                    for n, fn in self._counters.items()
                },
                "gauges": {
                    self._qualified(n): fn()
                    for n, fn in self._gauges.items()
                },
            }

    def _snapshot_locked(self, include_state: bool) -> Dict[str, dict]:
        counters = {
            self._qualified(n): fn() for n, fn in self._counters.items()
        }
        gauges = {self._qualified(n): fn() for n, fn in self._gauges.items()}
        reservoirs: Dict[str, dict] = {}
        states: Dict[str, dict] = {}
        for name, (resolver, label) in self._reservoirs.items():
            obj = resolver()
            qname = self._qualified(name)
            if label is None:
                reservoirs[qname] = self._summary(obj)
                if include_state:
                    states[qname] = obj.state()
            else:
                reservoirs[qname] = {
                    "label": label,
                    "series": {
                        value: self._summary(obj[value])
                        for value in obj.labels
                    },
                }
                if include_state:
                    states[qname] = {"label": label, "series": obj.state()}
        out = {
            "counters": counters,
            "gauges": gauges,
            "reservoirs": reservoirs,
        }
        if include_state:
            out["reservoir_states"] = states
        return out

    @classmethod
    def merge(cls, snapshots: List[dict]) -> dict:
        """Aggregate ``snapshot(include_state=True)`` payloads from several
        processes into one snapshot of the same shape: counters and gauges
        sum; reservoirs merge their sample states (exact count/sum/min/max,
        reservoir-union percentiles) and re-render summaries. The multi-host
        story: each host JSON-dumps its snapshot, host 0 gathers and merges."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        merged_hists: Dict[str, object] = {}
        labels: Dict[str, Optional[str]] = {}
        for snap in snapshots:
            for name, value in snap.get("counters", {}).items():
                counters[name] = counters.get(name, 0) + value
            for name, value in snap.get("gauges", {}).items():
                gauges[name] = gauges.get(name, 0) + value
            for name, state in snap.get("reservoir_states", {}).items():
                if isinstance(state, dict) and "series" in state:
                    labels.setdefault(name, state["label"])
                    series = merged_hists.setdefault(name, {})
                    for lab, sub in state["series"].items():
                        hist = series.get(lab)
                        if hist is None:
                            hist = series[lab] = ReservoirHistogram(
                                int(sub["capacity"])
                            )
                        hist.merge_state(sub)
                else:
                    labels.setdefault(name, None)
                    hist = merged_hists.get(name)
                    if hist is None:
                        hist = merged_hists[name] = ReservoirHistogram(
                            int(state["capacity"])
                        )
                    hist.merge_state(state)
        reservoirs: Dict[str, dict] = {}
        states: Dict[str, dict] = {}
        for name, obj in merged_hists.items():
            if labels[name] is None:
                reservoirs[name] = cls._summary(obj)
                states[name] = obj.state()
            else:
                reservoirs[name] = {
                    "label": labels[name],
                    "series": {
                        lab: cls._summary(h) for lab, h in obj.items()
                    },
                }
                states[name] = {
                    "label": labels[name],
                    "series": {lab: h.state() for lab, h in obj.items()},
                }
        return {
            "counters": counters,
            "gauges": gauges,
            "reservoirs": reservoirs,
            "reservoir_states": states,
        }

    @classmethod
    def merge_remote(
        cls,
        urls: Sequence[str],
        timeout: float = 5.0,
        *,
        retries: int = 1,
        backoff_s: float = 0.1,
    ) -> dict:
        """Scrape each engine's ``/snapshot`` endpoint (see
        ``obs.server.IntrospectionServer``) and :meth:`merge` the payloads
        — N engines' metrics aggregated over HTTP, the routed-fleet signal.
        ``urls`` are server base URLs (``http://host:port``). ``timeout``
        bounds connect + every read per attempt and ``retries`` transport
        retries ride over blips (both forwarded to ``scrape``), so one
        dead or partitioned replica delays a fleet-wide merge by a bounded
        ``(retries+1) * timeout`` instead of hanging it. A peer still dead
        after the retries raises; fleet callers that want partial
        aggregation catch per-URL and merge what answered."""
        from distributed_pytorch_tpu.obs.server import scrape

        return cls.merge(
            [
                scrape(
                    url,
                    "/snapshot",
                    timeout=timeout,
                    retries=retries,
                    backoff_s=backoff_s,
                )
                for url in urls
            ]
        )

    @classmethod
    def render_snapshot(cls, snapshot: dict) -> str:
        """Render a snapshot dict — typically :meth:`merge` /
        :meth:`merge_remote` output — as a Prometheus text body, same
        grammar as :meth:`prometheus_text`. Reservoirs re-render from
        their sample states when present (exact merged quantiles), else
        from the precomputed summaries."""
        lines: List[str] = []

        def head(qname, mtype):
            lines.append(f"# HELP {qname} {cls._escape_help(qname)}")
            lines.append(f"# TYPE {qname} {mtype}")

        def emit_hist(qname, hist, extra=""):
            for q in (0.5, 0.95, 0.99):
                value = hist.quantile(q)
                if value == value:
                    lines.append(f'{qname}{{{extra}quantile="{q}"}} {value}')
            suffix = "{" + extra.rstrip(",") + "}" if extra else ""
            lines.append(f"{qname}_sum{suffix} {hist.sum}")
            lines.append(f"{qname}_count{suffix} {hist.count}")

        def rebuild(state):
            hist = ReservoirHistogram(int(state["capacity"]))
            hist.merge_state(state)
            return hist

        for name, value in snapshot.get("counters", {}).items():
            head(name, "counter")
            lines.append(f"{name} {value}")
        for name, value in snapshot.get("gauges", {}).items():
            head(name, "gauge")
            lines.append(f"{name} {value}")
        states = snapshot.get("reservoir_states")
        if states is not None:
            for name, state in states.items():
                head(name, "summary")
                if isinstance(state, dict) and "series" in state:
                    label = _sanitize(str(state["label"]))
                    for lab, sub in state["series"].items():
                        emit_hist(
                            name,
                            rebuild(sub),
                            extra=f'{label}="{cls._escape_label(lab)}",',
                        )
                else:
                    emit_hist(name, rebuild(state))
        else:
            for name, summ in snapshot.get("reservoirs", {}).items():
                head(name, "summary")
                series = (
                    summ["series"].items()
                    if isinstance(summ, dict) and "series" in summ
                    else [(None, summ)]
                )
                label = (
                    _sanitize(str(summ["label"]))
                    if isinstance(summ, dict) and "series" in summ
                    else None
                )
                for lab, sub in series:
                    extra = (
                        f'{label}="{cls._escape_label(lab)}",'
                        if lab is not None
                        else ""
                    )
                    count = sub.get("count", 0)
                    for q_key, q in (("p50", 0.5), ("p95", 0.95),
                                     ("p99", 0.99)):
                        if q_key in sub:
                            lines.append(
                                f'{name}{{{extra}quantile="{q}"}} '
                                f"{sub[q_key]}"
                            )
                    suffix = "{" + extra.rstrip(",") + "}" if extra else ""
                    total = sub.get("mean", 0.0) * count
                    lines.append(f"{name}_sum{suffix} {total}")
                    lines.append(f"{name}_count{suffix} {count}")
        return "\n".join(lines) + "\n"

    @staticmethod
    def _escape_label(value: object) -> str:
        """Escape a label VALUE per the exposition format: backslash,
        double-quote, and newline must be backslash-escaped."""
        return (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    @staticmethod
    def _escape_help(text: str) -> str:
        """Escape HELP text: backslash and newline (quotes are legal)."""
        return str(text).replace("\\", "\\\\").replace("\n", "\\n")

    def prometheus_text(self) -> str:
        """Prometheus text exposition (one scrape body). Reservoirs render
        as ``summary`` metrics: quantile-labeled samples plus ``_sum`` and
        ``_count``; group labels become ordinary Prometheus labels. Every
        metric gets ``# HELP`` / ``# TYPE`` headers and label values are
        escaped, so real scrapers accept the body as-is (and
        ``obs.promtext.validate_exposition`` enforces it in tests)."""
        with self.lock:
            return self._prometheus_text_locked()

    def _prometheus_text_locked(self) -> str:
        lines: List[str] = []

        def emit_head(name, qname, mtype):
            text = self._help.get(name, qname)
            lines.append(f"# HELP {qname} {self._escape_help(text)}")
            lines.append(f"# TYPE {qname} {mtype}")

        def emit_summary(qname, hist, extra=""):
            for q in (0.5, 0.95, 0.99):
                value = hist.quantile(q)
                if value == value:  # skip NaN on empty reservoirs
                    lines.append(
                        f'{qname}{{{extra}quantile="{q}"}} {value}'
                    )
            suffix = "{" + extra.rstrip(",") + "}" if extra else ""
            lines.append(f"{qname}_sum{suffix} {hist.sum}")
            lines.append(f"{qname}_count{suffix} {hist.count}")

        for name, fn in self._counters.items():
            qname = self._qualified(name)
            emit_head(name, qname, "counter")
            lines.append(f"{qname} {fn()}")
        for name, fn in self._gauges.items():
            qname = self._qualified(name)
            emit_head(name, qname, "gauge")
            lines.append(f"{qname} {fn()}")
        for name, (resolver, label) in self._reservoirs.items():
            obj = resolver()
            qname = self._qualified(name)
            emit_head(name, qname, "summary")
            if label is None:
                emit_summary(qname, obj)
            else:
                for value in obj.labels:
                    extra = (
                        f'{_sanitize(label)}="{self._escape_label(value)}",'
                    )
                    emit_summary(qname, obj[value], extra=extra)
        return "\n".join(lines) + "\n"
