"""Online perf-regression detection: notice the slowdown, name the phase.

The TSDB remembers how step time evolved; this module watches it evolve
and fires when the level SHIFTS. A stray slow step is noise (GC pause,
OS jitter); a sustained shift — a recompile settling on a worse layout,
a stuck DMA path, a chaos ``slow_program`` stall — is an incident, and
the operator's first question is always "which phase got slow?".

The hard part of watching a SERVING engine is that step wall time moves
with load: a step decoding 8 rows is legitimately slower than one
decoding 2, and an open-loop arrival ramp shifts the level for entirely
healthy reasons. So the detector STRATIFIES: observations are keyed by
step composition (the decode-row count, pure-decode steps only — steps
that ran prefill are skipped, their cost depends on chunk length), and
each stratum carries its own baseline and CUSUM. A load change merely
moves traffic between strata; a PROGRAM-level slowdown — the thing worth
paging about — shifts every stratum it touches and fires inside the
first one that accumulates enough evidence.

Detector: per (stratum, series), a windowed one-sided CUSUM over an EWMA
baseline. Each tick is O(watched + phases) — same discipline as
``slo.py``'s sliding windows; an O(history) rescan per step is exactly
the observability tax this stack refuses to pay:

* each stratum's baseline initializes ROBUSTLY — median and MAD of its
  first ``min_samples`` observations — so a compile spike landing inside
  the window cannot anchor "normal" orders of magnitude too high; after
  warm-up, mean/variance track by slow EWMA (``baseline_alpha``) to
  self-calibrate to each deployment's jitter;
* the CUSUM statistic accumulates exceedance above a drift allowance of
  ``k`` baseline sigmas, WINSORIZED at ``clip`` sigmas per tick and
  LEAKY at rate ``leak``:
  ``S <- max(0, leak*S + min(x - mean - k*scale, clip*scale))``, firing
  when ``S > h*scale`` — the classic page-level change-point rule, with
  the clip chosen below the threshold so ONE arbitrarily large spike (a
  mid-run recompile) cannot fire alone, a sustained large shift crossing
  within ``ceil(h/clip)`` steps (2 at the defaults), and the leak
  keeping barely-over-allowance trickles (decode cost creeping with KV
  length) from accumulating to a page over a long run;
* while S is rising the baseline FREEZES (updating it with regressed
  samples would teach the detector that slow is normal and mask the
  shift).

Firing fans out like every alert in this stack: a registry counter
bumps, the flight recorder keeps a ``perf_regression`` event, and the
tracer drops an instant so the waterfall shows WHEN the shift landed.
Attribution: at fire time the detector compares every per-phase series'
fast-window mean IN THE FIRING STRATUM against its own frozen baseline
and blames the phase with the largest absolute level shift — for a
chaos ``slow_program`` stall of phase P, that is P by construction,
which is what the seeded drill in ``bench.py --perfwatch`` asserts.

After firing, the detector re-baselines the firing stratum onto the new
level (the shift is now "normal"; a second regression on top should
fire again) and latches a firing gauge until :meth:`acknowledge`.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Tuple


class _CusumSeries:
    """O(1)/tick one-sided CUSUM with a robust warm-up and EWMA baseline
    for one series (amortized: the warm-up's one median costs
    O(min_samples log min_samples), once)."""

    __slots__ = (
        "name", "mean", "var", "cusum", "n", "alpha", "k", "h", "clip",
        "leak", "rel_floor", "min_samples", "_warmup",
    )

    def __init__(
        self, name: str, *, alpha: float, k: float, h: float,
        clip: float, leak: float, rel_floor: float, min_samples: int,
    ):
        self.name = name
        self.mean = 0.0
        self.var = 0.0
        self.cusum = 0.0
        self.n = 0
        self.alpha = alpha
        self.k = k
        self.h = h
        self.clip = clip
        self.leak = leak
        self.rel_floor = rel_floor
        self.min_samples = max(2, min_samples)
        self._warmup: Optional[List[float]] = []

    def std(self) -> float:
        return math.sqrt(max(self.var, 0.0))

    def _scale(self) -> float:
        # Floor the scale at ``rel_floor`` of the mean: near-zero-jitter
        # warm-ups (synthetic clocks, idle phases) can't make every sample
        # look like an infinite-sigma shift, and sub-floor wiggle around
        # the mean — KV growth across a generation, allocator jitter — is
        # serving weather, not an incident (chronic slow drift is the SLO
        # monitor's beat; this detector hunts level SHIFTS).
        return max(self.std(), 1e-9, self.rel_floor * abs(self.mean))

    def push(self, x: float) -> bool:
        """Feed one sample; True when the CUSUM crosses the threshold."""
        x = float(x)
        self.n += 1
        if self._warmup is not None:
            # Warm-up: collect, then anchor the baseline on median/MAD —
            # robust to compile-dominated steps, which run orders of
            # magnitude over steady state.
            self._warmup.append(x)
            if len(self._warmup) >= self.min_samples:
                vals = sorted(self._warmup)
                med = vals[len(vals) // 2]
                mad = sorted(abs(v - med) for v in vals)[len(vals) // 2]
                self.mean = med
                self.var = (1.4826 * mad) ** 2  # MAD -> sigma, normal
                self._warmup = None
            return False
        scale = self._scale()
        # Winsorize the per-tick increment: one arbitrarily large spike
        # (a mid-run recompile) contributes at most clip*scale < h*scale,
        # so firing needs a SUSTAINED shift. The statistic also LEAKS
        # (``S <- leak*S`` before each increment): a marginal trickle of
        # exceedance saturates at ``inc/(1-leak)`` instead of growing
        # without bound, so only shifts whose per-tick exceedance tops
        # ``(1-leak)*h*scale`` can ever cross — a big shift clips through
        # in ``ceil(h/clip)`` ticks, barely-over-allowance drift never
        # does.
        exceed = min(x - self.mean - self.k * scale, self.clip * scale)
        self.cusum = max(0.0, self.leak * self.cusum + exceed)
        if self.cusum > self.h * scale:
            return True
        if exceed < 0.0:
            # Only track the baseline while the statistic is quiet — a
            # rising S means the level may have shifted; freezing keeps
            # the regressed samples out of "normal".
            delta = x - self.mean
            self.mean += self.alpha * delta
            self.var = (1 - self.alpha) * (self.var + self.alpha * delta**2)
        return False

    def rebaseline(self, x: float) -> None:
        """Adopt the current level as the new normal (post-fire); no
        re-warm-up — the detector is live and the level is known."""
        self.mean = x
        self.var = 0.0
        self.cusum = 0.0
        self._warmup = None

    def state(self) -> dict:
        return {
            "mean": self.mean,
            "std": self.std(),
            "cusum": self.cusum,
            "samples": self.n,
            "warming_up": self._warmup is not None,
        }


class RegressionDetector:
    """Watches step-time + TPOT per decode-row stratum (and the
    per-phase series for blame).

    Tunables: ``k`` (drift allowance, baseline sigmas — shifts smaller
    than this never accumulate), ``h`` (decision threshold, sigmas of
    accumulated exceedance), ``clip`` (per-tick increment cap, sigmas;
    keep ``clip < h`` or a single spike can fire), ``leak`` (CUSUM decay
    per tick — bounds what barely-over-allowance drift can accumulate,
    see :class:`_CusumSeries`), ``rel_floor`` (scale floor as a fraction
    of the baseline mean: shifts below ~``rel_floor`` of the level are
    below this detector's beat — KV growth across a generation moves
    step time that much legitimately; chronic percent-scale degradation
    belongs to the SLO monitor), ``min_samples`` (per-stratum median/MAD
    warm-up length before any alarm), ``baseline_alpha`` (EWMA rate;
    smaller = steadier baseline), ``phase_alpha`` (fast-window EWMA used
    only for attribution).
    ``max_strata`` bounds memory: beyond that many distinct decode-row
    counts, new compositions are ignored (each stratum is a handful of
    ~100-byte series objects; a serving engine has at most ``max_slots``
    strata, so the cap is a safety net, not a working limit). Defaults
    catch a sustained ~2x step-time shift within ~2-4 post-shift steps
    at steady batch while staying quiet through CPU-backend jitter,
    isolated mid-run compile spikes, AND open-loop load ramps — the
    seeded-drill budget asserted in tests and ``bench.py --perfwatch``.
    """

    WATCHED = ("step_wall_seconds", "tpot_step_seconds")

    def __init__(
        self,
        *,
        k: float = 1.0,
        h: float = 4.0,
        clip: float = 3.0,
        leak: float = 0.9,
        rel_floor: float = 0.25,
        min_samples: int = 8,
        baseline_alpha: float = 0.05,
        phase_alpha: float = 0.3,
        max_strata: int = 64,
        flight=None,
        tracer=None,
    ):
        self.flight = flight
        self.tracer = tracer
        self.max_strata = max_strata
        self._mk = lambda name: _CusumSeries(
            name, alpha=baseline_alpha, k=k, h=h, clip=clip, leak=leak,
            rel_floor=rel_floor, min_samples=min_samples,
        )
        # Keyed by (decode_rows, series name) / (decode_rows, phase).
        self._watch: Dict[Tuple[int, str], _CusumSeries] = {}
        self._phase_base: Dict[Tuple[int, str], _CusumSeries] = {}
        self._phase_fast: Dict[Tuple[int, str], float] = {}
        self._strata: set = set()
        self.phase_alpha = phase_alpha
        self.steps = 0
        self.skipped_steps = 0
        self.firing = False
        self.alerts = 0
        self.events: List[dict] = []
        self.last_attribution: Optional[str] = None

    # -------------------------------------------------------------- feeding

    def observe(
        self,
        *,
        step_wall_seconds: float,
        tpot_step_seconds: Optional[float] = None,
        decode_rows: int = 0,
        prefill_tokens: int = 0,
        phases: Optional[Dict[str, float]] = None,
    ) -> Optional[dict]:
        """One engine step. Returns the alert event when the detector
        fires this tick, else None. O(watched series + phases).

        Only pure-decode steps are compared (``prefill_tokens == 0``,
        ``decode_rows > 0``): prefill cost scales with chunk length, so
        mixed steps have no stationary level to hold them against. Those
        steps are counted in ``skipped_steps`` — a run that is all
        prefill is a run the detector honestly cannot watch, and the
        counter says so.
        """
        self.steps += 1
        if prefill_tokens > 0 or decode_rows <= 0:
            self.skipped_steps += 1
            return None
        stratum = int(decode_rows)
        if stratum not in self._strata:
            if len(self._strata) >= self.max_strata:
                self.skipped_steps += 1
                return None
            self._strata.add(stratum)
        phases = phases or {}
        for name, dt in phases.items():
            key = (stratum, name)
            base = self._phase_base.get(key)
            if base is None:
                base = self._phase_base[key] = self._mk(
                    f"phase_{name}@rows{stratum}"
                )
                self._phase_fast[key] = float(dt)
            base.push(float(dt))
            fast = self._phase_fast[key]
            self._phase_fast[key] = (
                fast + self.phase_alpha * (float(dt) - fast)
            )

        fired_on = None
        values = {"step_wall_seconds": step_wall_seconds}
        if tpot_step_seconds is not None:
            values["tpot_step_seconds"] = tpot_step_seconds
        for name, value in values.items():
            key = (stratum, name)
            series = self._watch.get(key)
            if series is None:
                series = self._watch[key] = self._mk(
                    f"{name}@rows{stratum}"
                )
            if series.push(value) and fired_on is None:
                fired_on = name
        if fired_on is None:
            return None
        return self._fire(stratum, fired_on, values)

    # -------------------------------------------------------------- firing

    def _attribute(self, stratum: int) -> Optional[str]:
        """Blame the phase whose fast level shifted most above its
        baseline in the FIRING stratum, in absolute seconds (relative
        shifts over-blame microscopic phases whose baseline is near
        zero; other strata saw different load, not this incident)."""
        worst, worst_shift = None, 0.0
        for (rows, name), base in self._phase_base.items():
            if rows != stratum:
                continue
            if base._warmup is not None:
                continue  # no trusted baseline yet — can't blame it
            shift = self._phase_fast[(rows, name)] - base.mean
            if shift > worst_shift:
                worst, worst_shift = name, shift
        return worst

    def _fire(
        self, stratum: int, series: str, values: Dict[str, float]
    ) -> dict:
        self.alerts += 1
        self.firing = True
        phase = self._attribute(stratum)
        self.last_attribution = phase
        watch = self._watch[(stratum, series)]
        event = {
            "t": time.time(),
            "step": self.steps,
            "series": series,
            "decode_rows": stratum,
            # How many comparable samples this stratum had ever seen at
            # fire time — drills subtract the injection-time count to get
            # detection latency in the detector's own information units
            # (skipped prefill steps can't count against it).
            "stratum_samples": watch.n,
            "value": values[series],
            "baseline_mean": watch.mean,
            "baseline_std": watch.std(),
            "attributed_phase": phase,
        }
        self.events.append(event)
        if len(self.events) > 64:
            del self.events[0]
        if self.flight is not None:
            try:
                self.flight.record(
                    "perf_regression",
                    series=series,
                    value=values[series],
                    baseline_mean=event["baseline_mean"],
                    attributed_phase=phase,
                )
            except Exception:
                pass
        if self.tracer is not None and getattr(self.tracer, "enabled", False):
            try:
                self.tracer.instant(
                    "perf_regression", series=series, phase=str(phase)
                )
            except Exception:
                pass
        # The shifted level is the new normal IN THIS STRATUM; re-arm for
        # the NEXT shift. Other strata keep their evidence — a program
        # regression should fire there too, and counts as further alerts.
        for name, value in values.items():
            self._watch[(stratum, name)].rebaseline(value)
        for (rows, name), base in self._phase_base.items():
            if rows == stratum:
                base.rebaseline(self._phase_fast[(rows, name)])
        return event

    def acknowledge(self) -> None:
        """Clear the firing latch (alert count stays — it is monotonic)."""
        self.firing = False

    # ------------------------------------------------------------ reporting

    def state(self) -> dict:
        """The ``/statusz`` block."""
        return {
            "steps": self.steps,
            "skipped_steps": self.skipped_steps,
            "strata": sorted(self._strata),
            "alerts": self.alerts,
            "firing": self.firing,
            "last_attribution": self.last_attribution,
            "watched": {
                s.name: s.state() for s in self._watch.values()
            },
            "phases": {
                base.name: {
                    "baseline_mean": base.mean,
                    "fast_mean": self._phase_fast[key],
                }
                for key, base in self._phase_base.items()
            },
            "events": list(self.events[-8:]),
        }

    def register_into(self, registry) -> None:
        registry.counter_fn(
            "perf_regressions_total",
            lambda: float(self.alerts),
            help="Sustained perf-level shifts detected by CUSUM",
        )
        registry.gauge_fn(
            "perf_regression_firing",
            lambda: float(self.firing),
            help="1 after a perf regression until acknowledged",
        )


__all__ = ["RegressionDetector"]
