"""Fleet-wide distributed tracing: merge, waterfalls, sampling.

The per-component tracers (:mod:`.tracer`) each record on their own
monotonic clock, in their own process lanes; this module assembles them
into ONE story per request:

* :func:`merge_traces` — align any number of tracers (live objects or
  exported/scraped ``/trace`` documents) onto a single timeline using the
  wall-clock epoch each tracer records at construction, remapping process
  ids so door / router / replica lanes stack top-down in causal order.
* :func:`request_waterfall` — walk every span carrying one ``trace_id``
  through an exact-partition state machine: each interval between
  consecutive trace events is assigned to exactly one of
  ``queue_wait / pacing / route / prefill / decode_active /
  backpressure_stall / preempt_rework / failover_gap``, so the components
  sum to the end-to-end latency *by construction* (float rounding is the
  only slack).
* :class:`TraceSampler` — head sampling by rate plus tail-based "always
  keep" for requests that failed, failed over, or violated their tenant
  SLO; bounded memory via a flight-recorder-style kept ring.
* :func:`prune_trace` — apply a sampler's drop decisions to a live tracer
  (remove ended, not-kept request/door/router spans and their flow
  arrows; the engine step timeline is global and always stays).

Identity model: span ids are fleet-unique by construction (engine req_ids
are strided per replica, router fleet ids and door stream ids live in
their own categories), so merged async events never collide. The one
identity that crosses layers is the string ``trace_id``: every ``b`` span
opened for a request carries it in ``args``, and every layer hashes it to
the same 48-bit Perfetto flow id (:func:`~.tracer.flow_id`).
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from distributed_pytorch_tpu.obs.tracer import (
    _PID_DOOR,
    _PID_ROUTER,
    flow_id,
)

# The waterfall partition. Every microsecond of a request's end-to-end
# latency lands in exactly one bucket.
WATERFALL_COMPONENTS = (
    "queue_wait",
    "pacing",
    "route",
    "prefill",
    "decode_active",
    "backpressure_stall",
    "preempt_rework",
    "failover_gap",
)

_SPAN_CATS = ("request", "door", "router")
# pids stay < 10 at the source, so stride-10 remapping keeps every
# (source, lane) pair distinct in the merged document.
_PID_STRIDE = 10


# ------------------------------------------------------------------- merge


def _as_doc(source) -> Dict[str, object]:
    """Accept a live Tracer or an exported/scraped Perfetto document."""
    to_perfetto = getattr(source, "to_perfetto", None)
    if callable(to_perfetto):
        return to_perfetto()
    if isinstance(source, dict) and "traceEvents" in source:
        return source
    raise TypeError(
        f"not a tracer or Chrome trace document: {type(source).__name__}"
    )


def _default_label(doc: Dict[str, object], index: int) -> str:
    events = doc.get("traceEvents", [])
    pids = {e.get("pid") for e in events if e.get("ph") != "M"}
    if _PID_DOOR in pids:
        return "door"
    if _PID_ROUTER in pids:
        return "router"
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "")
            if name.startswith("engine"):
                return name if name != "engine" else f"engine{index}"
    return f"src{index}"


def merge_traces(
    *sources, labels: Optional[Sequence[str]] = None
) -> Dict[str, object]:
    """Assemble door / router / replica traces into one Perfetto document.

    ``sources`` are live :class:`~.tracer.Tracer` objects or Chrome trace
    dicts (including documents scraped from remote ``/trace`` endpoints).
    Each source's timestamps are shifted by the delta between its
    ``wall_epoch_s`` metadata and the earliest epoch across all sources —
    documents predating the epoch field align at offset 0. Process ids
    are remapped to ``source_index * 10 + pid`` and the process-name
    metadata is prefixed with a per-source label, so lanes from different
    replicas remain tellable apart; span/flow *ids* are left untouched
    (they are fleet-unique / shared on purpose — flow arrows only connect
    because every layer hashed the same ``trace_id``)."""
    docs = [_as_doc(s) for s in sources]
    if not docs:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "metadata": {"wall_epoch_s": 0.0, "sources": []}}
    epochs = [
        float(d.get("metadata", {}).get("wall_epoch_s", 0.0)) for d in docs
    ]
    base = min(epochs)
    names: List[str] = []
    merged: List[dict] = []
    for i, (doc, epoch) in enumerate(zip(docs, epochs)):
        label = (
            labels[i] if labels is not None and i < len(labels)
            else _default_label(doc, i)
        )
        names.append(label)
        shift_us = (epoch - base) * 1e6
        events = doc.get("traceEvents", [])
        used_pids = {e.get("pid") for e in events if e.get("ph") != "M"}
        for e in events:
            e = dict(e)
            pid = e.get("pid")
            if e.get("ph") == "M":
                # Keep only metadata for lanes this source actually used,
                # so empty placeholder lanes don't clutter the merge.
                if pid not in used_pids:
                    continue
                if e.get("name") == "process_name":
                    args = dict(e.get("args", {}))
                    args["name"] = f"{label}: {args.get('name', '')}"
                    e["args"] = args
            else:
                e["ts"] = float(e.get("ts", 0.0)) + shift_us
            if pid is not None:
                e["pid"] = i * _PID_STRIDE + int(pid)
            merged.append(e)
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "metadata": {"wall_epoch_s": base, "sources": names},
    }


# --------------------------------------------------------------- waterfall


def trace_ids(doc: Dict[str, object]) -> List[str]:
    """Every distinct ``trace_id`` opened in ``doc``, in first-seen order."""
    seen: Dict[str, None] = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") == "b":
            tid = e.get("args", {}).get("trace_id")
            if tid is not None:
                seen.setdefault(str(tid), None)
    return list(seen)


def _stall_windows(events: Iterable[dict]) -> List[Tuple[float, float]]:
    wins = []
    for e in events:
        if e.get("name") == "backpressure_stall" and e.get("ph") == "i":
            dur_us = float(e.get("args", {}).get("dur_s", 0.0)) * 1e6
            ts = float(e.get("ts", 0.0))
            if dur_us > 0:
                wins.append((ts - dur_us, ts))
    return wins


def request_waterfall(
    doc: Dict[str, object], trace_id: str
) -> Dict[str, object]:
    """Per-request latency waterfall from a (merged) trace document.

    Selects every span whose ``b`` event carries ``trace_id`` — the door
    stream, the router decision, and each engine incarnation (original
    replica, hedge twin, failover survivor) — sorts their events onto one
    timeline, and assigns each inter-event interval to exactly one
    :data:`WATERFALL_COMPONENTS` bucket:

    * door open → admission is ``queue_wait``, with the token-bucket
      ``pacing_s`` the admit event reports carved out into ``pacing``;
    * door admission → engine span open is ``route``;
    * engine open → slot admission is engine-side ``queue_wait``;
    * admission → first token is ``prefill``; token → token is
      ``decode_active``;
    * a ``preempt`` mark flips the state to ``preempt_rework`` until
      decoding resumes; a router ``failover`` mark retro-assigns the
      silent interval since the victim's last sign of life — and
      everything until the survivor decodes again — to ``failover_gap``;
    * door-level backpressure windows are subtracted from overlapping
      ``decode_active`` time into ``backpressure_stall``.

    The buckets sum to ``e2e_s`` (last event minus first event) by
    construction; callers assert a small tolerance for float rounding.
    """
    events = doc.get("traceEvents", [])
    keys: Set[Tuple[str, int]] = set()
    for e in events:
        if (
            e.get("ph") == "b"
            and e.get("args", {}).get("trace_id") == trace_id
        ):
            keys.add((e.get("cat"), e.get("id")))
    if not keys:
        raise KeyError(f"trace_id {trace_id!r} not found in trace")
    sel = [
        e
        for e in events
        if e.get("ph") in ("b", "n", "e")
        and e.get("cat") in _SPAN_CATS
        and (e.get("cat"), e.get("id")) in keys
    ]
    sel.sort(key=lambda e: float(e.get("ts", 0.0)))
    comp: Dict[str, float] = {name: 0.0 for name in WATERFALL_COMPONENTS}
    decode_windows: List[Tuple[float, float]] = []
    prev_ts: Optional[float] = None
    cur = "queue_wait"
    in_failover = False
    in_rework = False
    for e in sel:
        ts = float(e.get("ts", 0.0))
        name = e.get("name")
        cat = e.get("cat")
        ph = e.get("ph")
        if prev_ts is not None and ts > prev_ts:
            # The failover mark reattributes the preceding silence: the
            # victim produced nothing between its last event and the
            # router noticing the death.
            label = (
                "failover_gap"
                if (cat == "router" and name == "failover")
                else cur
            )
            span_s = (ts - prev_ts) / 1e6
            comp[label] += span_s
            if label == "decode_active":
                decode_windows.append((prev_ts, ts))
        prev_ts = ts
        if cat == "door":
            if ph == "b":
                cur = "queue_wait"
            elif name == "admitted":
                pacing = float(e.get("args", {}).get("pacing_s", 0.0))
                take = min(max(pacing, 0.0), comp["queue_wait"])
                comp["queue_wait"] -= take
                comp["pacing"] += take
                cur = "route"
        elif cat == "router":
            if ph == "b":
                cur = "route"
            elif name == "failover":
                cur = "failover_gap"
                in_failover = True
                in_rework = False
        elif cat == "request":
            if ph == "b":
                if in_failover:
                    pass  # survivor re-admission: still the failover gap
                elif cur == "decode_active":
                    pass  # hedge twin opened while the primary decodes
                else:
                    cur = "queue_wait"
            elif name == "admit":
                if in_failover:
                    cur = "failover_gap"
                elif in_rework:
                    cur = "preempt_rework"
                elif cur == "decode_active":
                    pass  # hedge twin admission under a decoding primary
                else:
                    cur = "prefill"
            elif name in ("decode_token", "verify_round"):
                cur = "decode_active"
                in_failover = False
                in_rework = False
            elif name == "preempt":
                cur = "preempt_rework"
                in_rework = True
    # Door-level backpressure windows: time the pump refused to step the
    # backend because a consumer lagged. Re-bucket the overlap out of
    # decode_active — a stalled engine is not decoding this request.
    for w0, w1 in _stall_windows(events):
        moved = 0.0
        for d0, d1 in decode_windows:
            moved += max(0.0, min(w1, d1) - max(w0, d0))
        moved_s = min(moved / 1e6, comp["decode_active"])
        comp["decode_active"] -= moved_s
        comp["backpressure_stall"] += moved_s
    first_ts = float(sel[0].get("ts", 0.0))
    last_ts = float(sel[-1].get("ts", 0.0))
    return {
        "trace_id": trace_id,
        "e2e_s": (last_ts - first_ts) / 1e6,
        "components": comp,
        "n_events": len(sel),
        "spans": sorted(
            {(cat, sid) for cat, sid in keys},
            key=lambda k: (k[0], k[1]),
        ),
    }


def format_waterfall(wf: Dict[str, object]) -> str:
    """Render one waterfall as an aligned text table (CLI / smoke output)."""
    e2e = float(wf["e2e_s"]) or 1.0
    lines = [f"trace {wf['trace_id']}  e2e {float(wf['e2e_s']) * 1e3:.2f} ms"]
    for name in WATERFALL_COMPONENTS:
        val = float(wf["components"].get(name, 0.0))
        frac = val / e2e
        bar = "#" * int(round(frac * 40))
        lines.append(
            f"  {name:<18} {val * 1e3:9.2f} ms  {frac * 100:5.1f}%  {bar}"
        )
    total = sum(float(v) for v in wf["components"].values())
    lines.append(f"  {'sum':<18} {total * 1e3:9.2f} ms")
    return "\n".join(lines)


# ---------------------------------------------------------------- sampling


class TraceSampler:
    """Head + tail sampling policy over fleet trace ids.

    Head decision: a deterministic hash of ``(seed, trace_id)`` against
    ``head_rate`` — every layer would reach the same verdict
    independently, though in practice only the door consults it. Tail
    decision at request end: always keep requests that failed (rejected /
    cancelled / expired), failed over between replicas, or violated their
    tenant's SLO, regardless of the head draw.

    Memory is bounded flight-recorder-style: at most ``max_kept`` kept
    trace ids are remembered; keeping one more evicts (and schedules the
    pruning of) the oldest. Ended traces that are not kept go onto a
    pending-drop set the owner drains with :meth:`drain_drops` and applies
    via :func:`prune_trace`."""

    def __init__(
        self,
        head_rate: float = 1.0,
        *,
        keep_failed: bool = True,
        keep_failed_over: bool = True,
        keep_slo_violations: bool = True,
        max_kept: int = 256,
        seed: int = 0,
    ):
        if not 0.0 <= head_rate <= 1.0:
            raise ValueError(f"head_rate must be in [0, 1], got {head_rate}")
        if max_kept < 1:
            raise ValueError(f"max_kept must be >= 1, got {max_kept}")
        self.head_rate = float(head_rate)
        self.keep_failed = keep_failed
        self.keep_failed_over = keep_failed_over
        self.keep_slo_violations = keep_slo_violations
        self.max_kept = int(max_kept)
        self.seed = int(seed)
        self._kept_ring: "collections.deque[str]" = collections.deque()
        self._kept: Set[str] = set()
        self._pending_drop: Set[str] = set()
        self.ended = 0
        self.kept_head = 0
        self.kept_tail = 0
        self.dropped = 0
        self.evicted = 0

    def head_keep(self, trace_id: str) -> bool:
        """Deterministic per-trace head draw (same verdict in any process)."""
        digest = hashlib.sha1(
            f"{self.seed}:{trace_id}".encode("utf-8")
        ).digest()
        draw = int.from_bytes(digest[:8], "big") / float(2 ** 64)
        return draw < self.head_rate

    def note_end(
        self,
        trace_id: str,
        *,
        failed: bool = False,
        failed_over: bool = False,
        slo_violated: bool = False,
    ) -> bool:
        """Record one finished request; returns True iff its trace is kept."""
        self.ended += 1
        tail = (
            (failed and self.keep_failed)
            or (failed_over and self.keep_failed_over)
            or (slo_violated and self.keep_slo_violations)
        )
        head = self.head_keep(trace_id)
        if tail or head:
            if tail and not head:
                self.kept_tail += 1
            else:
                self.kept_head += 1
            self._kept.add(trace_id)
            self._kept_ring.append(trace_id)
            if len(self._kept_ring) > self.max_kept:
                oldest = self._kept_ring.popleft()
                self._kept.discard(oldest)
                self._pending_drop.add(oldest)
                self.evicted += 1
            return True
        self.dropped += 1
        self._pending_drop.add(trace_id)
        return False

    def kept_ids(self) -> List[str]:
        return list(self._kept_ring)

    def drain_drops(self) -> Set[str]:
        """Hand the pending drop set to the owner (clears it)."""
        drops, self._pending_drop = self._pending_drop, set()
        return drops

    def counters(self) -> Dict[str, int]:
        return {
            "traces_ended": self.ended,
            "traces_kept_head": self.kept_head,
            "traces_kept_tail": self.kept_tail,
            "traces_dropped": self.dropped,
            "traces_evicted": self.evicted,
        }


def prune_trace(tracer, drop_ids: Iterable[str]) -> int:
    """Remove every span (door/router/request) and flow arrow belonging to
    a dropped ``trace_id`` from a live tracer, in place. Engine step
    slices, phase slices, and counter tracks are global context and always
    survive. Returns the number of events removed. Callers hold the
    tracer's owning lock (the registry lock) around this."""
    drop = {str(t) for t in drop_ids}
    if not drop:
        return 0
    keys: Set[Tuple[str, int]] = set()
    for e in tracer.events:
        if (
            e.get("ph") == "b"
            and e.get("args", {}).get("trace_id") in drop
        ):
            keys.add((e.get("cat"), e.get("id")))
    flow_drop = {flow_id(t) for t in drop}
    kept_events: List[dict] = []
    removed = 0
    opened = closed = 0
    for e in tracer.events:
        cat = e.get("cat")
        if cat in _SPAN_CATS and (cat, e.get("id")) in keys:
            removed += 1
            if e.get("ph") == "b":
                opened += 1
            elif e.get("ph") == "e":
                closed += 1
            continue
        if cat == "flow" and e.get("id") in flow_drop:
            removed += 1
            continue
        kept_events.append(e)
    tracer.events = kept_events
    tracer.spans_opened -= opened
    tracer.spans_closed -= closed
    return removed


__all__ = [
    "WATERFALL_COMPONENTS",
    "TraceSampler",
    "format_waterfall",
    "merge_traces",
    "prune_trace",
    "request_waterfall",
    "trace_ids",
]
